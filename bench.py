"""Benchmark entry point. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

On a TPU host: Llama-style training-step MFU on one chip (the reference's north-star
axis — BASELINE.json "MaxText Llama-3-8B ... >=50% MFU"; baseline = 50% MFU, so
vs_baseline = MFU/50). The model is sized to a single chip's HBM; MFU is
size-independent, making it the honest single-chip comparable.

Without a TPU: control-plane scheduling throughput vs the reference's documented cap
(75 submitted jobs/min/replica, reference server/background/__init__.py:57).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _tpu_peak_tflops(device) -> float:
    # ONE chip-peak table for bench MFU and telemetry MFU (train.py owns it);
    # two copies would let the numbers silently disagree for the same run.
    from dstack_tpu.workloads.train import _device_peak_flops

    return _device_peak_flops(device)


def _run_train_variant(
    cfg,
    batch: int,
    seq: int,
    grad_accum: int = 1,
    prefetch: int = 0,
    steps: int = 8,
    mesh=None,
    batch_spec=None,
    cfg_overrides=None,
    autotune=False,
) -> dict:
    """One variant of the train step: returns compile_s + p50/p90/median step
    seconds. prefetch=0 feeds one static device-resident batch (the legacy
    path); prefetch>0 streams fresh host batches through the data-pipeline
    prefetcher so the host->HBM transfer overlaps the previous step.
    cfg_overrides (attn_impl/quant/tp_overlap/fsdp_overlap/attn_window — the
    kernel levers) are dataclass-replaced onto cfg so the sweep attributes
    each lever separately. autotune=True sweeps flash/splash block sizes for
    this shape first (kernels/autotune.py) so the variant's compile picks up
    the tuned winner — the --autotune CLI path, measured."""
    import dataclasses
    import statistics

    import jax

    from dstack_tpu.workloads import data as data_lib
    from dstack_tpu.workloads import train as train_lib

    if cfg_overrides:
        cfg = dataclasses.replace(cfg, **cfg_overrides)
    if autotune and cfg.attn_impl in ("flash", "splash"):
        import jax.numpy as jnp

        from dstack_tpu.workloads.kernels import autotune as autotune_lib

        probe = jax.random.normal(
            jax.random.PRNGKey(0), (1, seq, 1, cfg.head_dim), jnp.float32
        )
        autotune_lib.tune(cfg.attn_impl, probe, probe, probe,
                          causal=True, window=cfg.attn_window)
    optimizer = train_lib.make_optimizer(mu_dtype="bfloat16")
    state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
    step_fn = train_lib.make_train_step(cfg, optimizer, mesh, grad_accum=grad_accum)

    feed = None
    if prefetch > 0:
        spec = batch_spec
        if mesh is None:
            # Single chip: prefetch onto the default device (no mesh spec).
            source = data_lib.synthetic_batches(
                cfg.vocab_size, batch, seq, process_index=0, process_count=1
            )
            feed = data_lib.Prefetcher(
                (
                    (jax.device_put(t), jax.device_put(g))
                    for t, g in source
                ),
                depth=prefetch,
            )
        else:
            source = data_lib.synthetic_batches(cfg.vocab_size, batch, seq)
            feed = data_lib.Prefetcher(
                data_lib.sharded_batches(source, mesh, spec, batch), depth=prefetch
            )

        def next_batch():
            return next(feed)

    else:
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size
        )
        targets = jax.random.randint(
            jax.random.PRNGKey(2), (batch, seq), 0, cfg.vocab_size
        )

        def next_batch():
            return tokens, targets

    try:
        # Warmup/compile. float() forces a device sync (block_until_ready is
        # not reliable through every PJRT transport).
        t0 = time.perf_counter()
        tok, tgt = next_batch()
        state, m = step_fn(state, tok, tgt)
        float(m["loss"])
        compile_s = time.perf_counter() - t0

        # Per-step sync + median: immune to one-off relay stalls; each step's
        # float() costs ~10 ms of round trip (<1% bias, conservative).
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            tok, tgt = next_batch()
            state, m = step_fn(state, tok, tgt)
            float(m["loss"])
            times.append(time.perf_counter() - t0)
    finally:
        if feed is not None:
            feed.close()

    stats = train_lib._step_time_stats(times)
    out = {
        "compile_s": round(compile_s, 2),
        "median_s": statistics.median(times),
        "p50_ms": round(stats["p50_s"] * 1000, 1),
        "p90_ms": round(stats["p90_s"] * 1000, 1),
        "grad_accum": grad_accum,
        "prefetch": prefetch,
        "batch": batch,
        # Goodput % for this bounded run, through the SAME ledger the server
        # derives from workload telemetry (services/metrics.py): productive
        # step time over wall clock with the compile stall debited. This is
        # the baseline ROADMAP item 3's preemption benches regress against.
        "goodput_pct": _variant_goodput_pct(compile_s, times),
    }
    if cfg_overrides:
        out.update({k: v for k, v in cfg_overrides.items()})
    return out


def _variant_goodput_pct(compile_s: float, step_times: list) -> float:
    """Feed a variant's measured timings through the server's goodput ledger
    (synthesized telemetry points with real offsets), so the bench number and
    the /metrics number can never drift apart in definition."""
    import datetime

    from dstack_tpu.server.services.metrics import compute_goodput
    from dstack_tpu.utils.common import now_utc, to_iso

    base = now_utc()

    def iso(off: float) -> str:
        return to_iso(base + datetime.timedelta(seconds=off))

    points = [
        {"ts": iso(0.0), "kind": "mark", "event": "run_start"},
        {"ts": iso(0.0), "kind": "mark", "event": "compile_start"},
        {"ts": iso(compile_s), "kind": "mark", "event": "compile_end",
         "compile_s": compile_s},
    ]
    off = compile_s
    for i, dt in enumerate(step_times):
        off += dt
        points.append(
            {"ts": iso(off), "kind": "step", "step": i + 2, "step_time_s": dt}
        )
    ledger = compute_goodput(points)
    return round((ledger["ratio"] or 0.0) * 100, 2)


def _variant_plan(batch: int) -> list:
    """The variant sweep shared by the TPU bench and the `make bench-train`
    CPU smoke — one list so the smoke always covers every variant the
    headline MFU can be attributed to. Pipeline variants (accum/prefetch,
    PR 4) plus the kernel/precision levers (PR 7): the in-repo flash kernel,
    int8 quantized matmuls, and their combination; plus the raw-speed
    round-two levers: fp8 matmuls (v5p+ MXUs; elsewhere the variant records
    validate_config's rejection), block-sparse splash attention (dense-causal
    and local-window), and autotuned flash block sizes. The tp_overlap /
    fsdp_overlap collective-matmul variants need a multi-device mesh and are
    planned separately (_tp_variant_plan / _fsdp_variant_plan)."""
    return [
        ("static", dict(batch=batch, grad_accum=1, prefetch=0)),
        ("prefetch2", dict(batch=batch, grad_accum=1, prefetch=2)),
        ("accum2_prefetch2", dict(batch=2 * batch, grad_accum=2, prefetch=2)),
        ("flash", dict(batch=batch, grad_accum=1, prefetch=2,
                       cfg_overrides={"attn_impl": "flash"})),
        ("int8", dict(batch=batch, grad_accum=1, prefetch=2,
                      cfg_overrides={"quant": "int8"})),
        ("flash_int8", dict(batch=batch, grad_accum=1, prefetch=2,
                            cfg_overrides={"attn_impl": "flash",
                                           "quant": "int8"})),
        ("fp8", dict(batch=batch, grad_accum=1, prefetch=2,
                     cfg_overrides={"quant": "fp8"})),
        ("splash", dict(batch=batch, grad_accum=1, prefetch=2,
                        cfg_overrides={"attn_impl": "splash"})),
        ("splash_window", dict(batch=batch, grad_accum=1, prefetch=2,
                               cfg_overrides={"attn_impl": "splash",
                                              "attn_window": 64})),
        ("flash_autotuned", dict(batch=batch, grad_accum=1, prefetch=2,
                                 cfg_overrides={"attn_impl": "flash"},
                                 autotune=True)),
    ]


def _tp_variant_plan(batch: int) -> list:
    """Collective-matmul variants; callers supply a tp>1 mesh (skipped — with
    the reason recorded — on a single chip). Attribution-only: they run on a
    different device count than the 1-chip headline, so they never compete
    for best_variant."""
    return [
        ("tp_overlap", dict(batch=batch, grad_accum=1, prefetch=2,
                            cfg_overrides={"tp_overlap": True})),
        ("tp_overlap_int8", dict(batch=batch, grad_accum=1, prefetch=2,
                                 cfg_overrides={"tp_overlap": True,
                                                "quant": "int8"})),
    ]


def _fsdp_variant_plan(batch: int) -> list:
    """FSDP allgather-matmul ring variants; callers supply a dp*fsdp>1 mesh.
    Attribution-only in bench_tpu_train (different device count than the
    1-chip headline); the pipeline smoke runs them on its main mesh."""
    return [
        ("fsdp_overlap", dict(batch=batch, grad_accum=1, prefetch=2,
                              cfg_overrides={"fsdp_overlap": True})),
        ("fsdp_overlap_int8", dict(batch=batch, grad_accum=1, prefetch=2,
                                   cfg_overrides={"fsdp_overlap": True,
                                                  "quant": "int8"})),
    ]


def bench_tpu_train() -> dict:
    import jax

    from dstack_tpu.workloads.config import get_config

    dev = jax.devices()[0]
    # ~670M-param wide-geometry model (see config.PRESETS["v5e_bench"] notes and
    # the round-3 sweep in BASELINE.md): flash attention + chunked CE + bf16
    # Adam-mu fit batch 24 in the 16 GB chip with full-remat.
    cfg = get_config("v5e_bench")
    batch, seq = 24, 2048

    # Sweep the overlapped-pipeline variants. "static" is the historical
    # measurement (one device-resident batch, accum=1); "prefetch" streams
    # fresh host batches through the async prefetcher; "accum" doubles the
    # global batch at constant microbatch/HBM via fp32-accumulated grads. The
    # headline MFU is the best variant so the trajectory attributes the win;
    # an OOM-ing variant records its error instead of killing the bench.
    variants = {}
    for name, kw in _variant_plan(batch):
        try:
            variants[name] = _run_train_variant(cfg, seq=seq, **kw)
        except Exception as e:  # noqa: BLE001 — typically RESOURCE_EXHAUSTED
            variants[name] = {"error": f"{type(e).__name__}: {e}"[:200]}

    # Collective-matmul attribution: needs a tp mesh, so it runs across ALL
    # local chips and reports per-chip tok/s in its own record — never in the
    # 1-chip headline race ("median_s" is dropped before the best-variant
    # scan below).
    n_dev = jax.device_count()
    for name, kw in _tp_variant_plan(batch):
        if n_dev < 2:
            variants[name] = {"skipped": f"needs >1 device for tp (have {n_dev})"}
            continue
        if cfg.n_kv_heads % n_dev:
            variants[name] = {
                "skipped": f"tp={n_dev} does not divide n_kv_heads={cfg.n_kv_heads}"
            }
            continue
        try:
            from dstack_tpu.workloads.sharding import BATCH_SPEC, make_mesh

            mesh = make_mesh(dp=1, fsdp=1, tp=n_dev, sp=1)
            with mesh:
                v = _run_train_variant(
                    cfg, seq=seq, mesh=mesh, batch_spec=BATCH_SPEC, **kw
                )
            v["devices"] = n_dev
            v["tok_per_sec_per_chip"] = round(
                v["batch"] * seq / v.pop("median_s") / n_dev, 1
            )
            variants[name] = v
        except Exception as e:  # noqa: BLE001
            variants[name] = {"error": f"{type(e).__name__}: {e}"[:200]}

    # FSDP allgather-matmul attribution: needs a dp*fsdp>1 mesh, so it runs
    # across ALL local chips on a pure-fsdp mesh — attribution-only, like the
    # tp variants.
    for name, kw in _fsdp_variant_plan(batch):
        if n_dev < 2:
            variants[name] = {
                "skipped": f"needs >1 device for the fsdp ring (have {n_dev})"
            }
            continue
        if cfg.d_model % n_dev:
            variants[name] = {
                "skipped": f"dp*fsdp={n_dev} does not divide d_model={cfg.d_model}"
            }
            continue
        try:
            from dstack_tpu.workloads.sharding import BATCH_SPEC, make_mesh

            mesh = make_mesh(dp=1, fsdp=n_dev, tp=1, sp=1)
            with mesh:
                v = _run_train_variant(
                    cfg, seq=seq, mesh=mesh, batch_spec=BATCH_SPEC, **kw
                )
            v["devices"] = n_dev
            v["tok_per_sec_per_chip"] = round(
                v["batch"] * seq / v.pop("median_s") / n_dev, 1
            )
            variants[name] = v
        except Exception as e:  # noqa: BLE001
            variants[name] = {"error": f"{type(e).__name__}: {e}"[:200]}

    ok = {k: v for k, v in variants.items() if "median_s" in v}
    if not ok:
        raise RuntimeError(f"all train variants failed: {variants}")
    best_name = min(ok, key=lambda k: ok[k]["median_s"] / ok[k]["batch"])
    best = ok[best_name]

    tokens_per_sec = best["batch"] * seq / best["median_s"]
    # causal=True: count only the executed (lower-triangle) attention FLOPs.
    flops_per_sec = tokens_per_sec * cfg.flops_per_token(seq, causal=True)
    mfu_pct = 100.0 * flops_per_sec / _tpu_peak_tflops(dev)
    for v in ok.values():
        v.pop("median_s", None)
    return {
        "metric": "llama_train_step_mfu_1chip",
        "value": round(mfu_pct, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu_pct / 50.0, 4),
        "extra": {
            "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
            "params_m": round(cfg.num_params() / 1e6, 1),
            "device": getattr(dev, "device_kind", "unknown"),
            "batch": best["batch"],
            "seq": seq,
            "best_variant": best_name,
            "goodput_pct": best.get("goodput_pct"),
            # Per-variant compile time + step-time distribution: the MFU
            # trajectory now attributes WHERE a win came from.
            "variants": variants,
        },
    }


def bench_train_pipeline() -> dict:
    """`make bench-train`: the accumulation/prefetch sweep in a bounded-steps
    CPU smoke mode (8 fake devices, tiny config) — proves every variant of the
    overlapped pipeline end to end and prints one JSON line. Not an MFU
    measurement; vs_baseline is best-variant tok/s over the static feed."""
    import os

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    from dstack_tpu.workloads.config import get_config
    from dstack_tpu.workloads.sharding import BATCH_SPEC, make_mesh

    steps = int(os.environ.get("DSTACK_TPU_BENCH_TRAIN_STEPS", "6"))
    cfg = get_config("test", max_seq_len=128)
    devices = jax.devices()[:8]
    mesh = make_mesh(dp=2, fsdp=4, devices=devices)
    batch, seq = 16, 128

    variants = {}
    with mesh:
        for name, kw in _variant_plan(batch):
            variants[name] = _run_train_variant(
                cfg, seq=seq, steps=steps, mesh=mesh, batch_spec=BATCH_SPEC, **kw
            )
    # Collective-matmul variants on a tp=4 mesh (same 8 devices, different
    # axes) — proves the ppermute ring end to end on CPU.
    tp_mesh = make_mesh(dp=1, fsdp=2, tp=4, devices=devices)
    with tp_mesh:
        for name, kw in _tp_variant_plan(batch):
            variants[name] = _run_train_variant(
                cfg, seq=seq, steps=steps, mesh=tp_mesh, batch_spec=BATCH_SPEC,
                **kw
            )
    # FSDP allgather-matmul variants on the MAIN dp2xfsdp4 mesh (dp*fsdp=8
    # divides the test config's d_model) — proves the weight-shard ring end
    # to end on CPU.
    with mesh:
        for name, kw in _fsdp_variant_plan(batch):
            variants[name] = _run_train_variant(
                cfg, seq=seq, steps=steps, mesh=mesh, batch_spec=BATCH_SPEC,
                **kw
            )

    rate = {k: v["batch"] * seq / v.pop("median_s") for k, v in variants.items()}
    # tp/fsdp overlap variants are attribution-only — never the headline,
    # matching bench_tpu_train's contract (tp runs under different sharding;
    # fsdp keeps the rule for consistency even on the main mesh).
    excluded = {name for name, _ in _tp_variant_plan(batch)} | {
        name for name, _ in _fsdp_variant_plan(batch)
    }
    best = max((k for k in rate if k not in excluded), key=rate.get)
    return {
        "metric": "train_pipeline_smoke_tok_per_sec",
        "value": round(rate[best], 1),
        "unit": "tok/s",
        "vs_baseline": round(rate[best] / rate["static"], 4),
        "extra": {
            "steps": steps,
            "best_variant": best,
            "goodput_pct": variants[best].get("goodput_pct"),
            "tok_per_sec": {k: round(v, 1) for k, v in rate.items()},
            "variants": variants,
        },
    }


class _CaptureEmitter:
    """Telemetry stand-in for the preemption bench: marks/steps land in an
    in-memory point list with REAL wall-clock timestamps, shaped exactly like
    the sidecar points the server ingests — so the same list feeds
    compute_goodput and the bench's ledger can't drift from /metrics."""

    def __init__(self):
        self.points = []

    def _now(self) -> str:
        from dstack_tpu.utils.common import now_utc, to_iso

        return to_iso(now_utc())

    def emit(self, kind, **fields):
        self.points.append({"ts": self._now(), "kind": kind, **fields})

    def mark(self, event, **fields):
        self.emit("mark", event=event, **fields)

    def step(self, step, step_time_s, **fields):
        self.emit("step", step=step, step_time_s=step_time_s, **fields)

    def flush(self, timeout=0.0):
        pass

    def close(self, timeout=0.0):
        pass

    def stats(self):
        return {}


class _InjectedKill(Exception):
    """Raised by the bench's on_step hook to simulate the process dying."""


def _preemption_round(
    cfg, mesh, batch, seq, total_steps, kills, checkpoint_every, ckpt_dir,
    step_fn, optimizer,
):
    """One schedule execution: run the PRODUCTION train loop
    (train._timed_loop + make_checkpoint_hook, the same code a real workload
    runs), dying at each step in ``kills``; checkpoint_every > 0 resumes each
    attempt from the last complete checkpoint (restart-from-step-0
    otherwise). Returns (telemetry points, {step: loss}, attempts). Because
    the loop and emitter are the real ones, the point stream feeding the
    ledger is by construction the stream real workloads ship. The step
    function is shared across attempts (the persistent-compilation-cache
    assumption: a restarted process re-traces against a warm XLA cache; both
    arms share it equally, and attempt 1's real compile is measured either
    way)."""
    import jax

    from dstack_tpu.workloads import data as data_lib
    from dstack_tpu.workloads import train as train_lib
    from dstack_tpu.workloads.checkpoint import CheckpointManager

    emitter = _CaptureEmitter()
    mgr = (
        CheckpointManager(ckpt_dir, telemetry=emitter)
        if checkpoint_every > 0
        else None
    )
    losses = {}
    remaining_kills = sorted(kills)
    attempts = 0
    while True:
        attempts += 1
        emitter.mark("run_start" if attempts == 1 else "restart", attempt=attempts)
        state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
        start = 0
        if mgr is not None and mgr.latest_step() is not None:
            state, manifest = mgr.restore(state)
            start = int(manifest["step"])
        feed = data_lib.input_pipeline(
            mesh, train_lib.batch_sharding(mesh).spec, batch, seq, cfg.vocab_size,
            prefetch=0, start_batch=start,
        )
        kill_at = next((k for k in remaining_kills if k > start), None)
        box = {"state": state}

        def do_step():
            tokens, targets = next(feed)
            box["state"], m = step_fn(box["state"], tokens, targets)
            return m["loss"]

        # resumed=True pins the hook's env crash injection off — the bench
        # injects its own kills below, on its own schedule.
        save_hook = train_lib.make_checkpoint_hook(
            mgr, checkpoint_every if mgr is not None else 0, total_steps,
            lambda: box["state"], mesh_shape=dict(mesh.shape), resumed=True,
        )

        def on_step(step, loss):
            losses[step] = float(loss)
            save_hook(step, loss)
            if kill_at is not None and step >= kill_at:
                raise _InjectedKill(step)

        killed = False
        try:
            train_lib._timed_loop(
                total_steps, batch, seq, do_step, telemetry=emitter,
                start_step=start, on_step=on_step,
            )
        except _InjectedKill:
            # Injected preemption: the process dies here — nothing more is
            # emitted, exactly like a real SIGKILL. Drain the in-flight
            # checkpoint write first (a real kill lands at an arbitrary
            # point; the commit markers make a torn write unreadable rather
            # than wrong either way).
            remaining_kills.remove(kill_at)
            killed = True
            if mgr is not None:
                mgr.wait()
        finally:
            feed.close()
        if not killed:
            break
    if mgr is not None:
        mgr.close()
    return emitter.points, losses, attempts


def bench_preemption() -> dict:
    """`make bench-preemption`: goodput under an injected kill schedule, the
    ROADMAP item 3 headline. A live train loop (8 fake CPU devices, dp2/fsdp4)
    is killed mid-run at fixed steps; the checkpoint+resume arm restores from
    the last async checkpoint while the baseline arm restarts from step 0.
    Both arms' real timings run through the SERVER's goodput ledger
    (services/metrics.compute_goodput — restart gaps and re-done steps are
    debited as restart_s/rework_s), and the headline is the goodput uplift.
    FAILS (raises) if a resumed step's loss ever diverges from the
    uninterrupted reference, or if the uplift lands under the 1.5x
    acceptance floor."""
    import os
    import shutil
    import tempfile

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    from dstack_tpu.server.services.metrics import compute_goodput
    from dstack_tpu.workloads import train as train_lib
    from dstack_tpu.workloads.config import get_config
    from dstack_tpu.workloads.sharding import make_mesh

    total_steps = int(os.environ.get("DSTACK_TPU_BENCH_PREEMPT_STEPS", "30"))
    kills = [total_steps // 3 + 2, (2 * total_steps) // 3 + 2]
    every = 4
    # Tiny geometry: the bench measures the RATIO of wasted to productive
    # wall clock under kills, which is size-independent — what matters is
    # that step time dominates the warm-cache restart overhead (~0.2s steps
    # vs ~0.1s re-init on CPU), mirroring the real-TPU regime where multi-
    # second steps dominate restart costs.
    cfg = get_config(
        "test", max_seq_len=64, d_model=128, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=344, vocab_size=1024, remat=False,
    )
    batch, seq = 8, 64
    mesh = make_mesh(dp=2, fsdp=4, devices=jax.devices()[:8])
    optimizer = train_lib.make_optimizer(mu_dtype="bfloat16")
    step_fn = train_lib.make_train_step(cfg, optimizer, mesh)

    with mesh:
        # Uninterrupted reference: the loss-identity oracle (and the warm
        # compile both arms inherit — symmetric by construction).
        ref_points, ref_losses, _ = _preemption_round(
            cfg, mesh, batch, seq, total_steps, [], 0, "", step_fn, optimizer
        )
        ckpt_dir = tempfile.mkdtemp(prefix="dstack-bench-ckpt-")
        try:
            off_points, off_losses, off_attempts = _preemption_round(
                cfg, mesh, batch, seq, total_steps, kills, 0, "", step_fn, optimizer
            )
            on_points, on_losses, on_attempts = _preemption_round(
                cfg, mesh, batch, seq, total_steps, kills, every, ckpt_dir,
                step_fn, optimizer,
            )
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)

    # Acceptance: a resumed run's loss sequence is IDENTICAL to the
    # uninterrupted run at equal steps — asserted, not eyeballed. (The
    # baseline arm replays from step 0 with the same seeds, so it must match
    # too; any divergence is a checkpoint/data-seek bug.)
    for step, loss in on_losses.items():
        if loss != ref_losses[step]:
            raise AssertionError(
                f"checkpoint+resume diverged at step {step}: "
                f"{loss} != {ref_losses[step]} (uninterrupted)"
            )
    for step, loss in off_losses.items():
        if loss != ref_losses[step]:
            raise AssertionError(
                f"restart-from-0 replay diverged at step {step}: "
                f"{loss} != {ref_losses[step]}"
            )

    ref = compute_goodput(ref_points)
    off = compute_goodput(off_points)
    on = compute_goodput(on_points)
    uplift = (on["ratio"] or 0.0) / max(off["ratio"] or 1e-9, 1e-9)
    if uplift < 1.5:
        raise AssertionError(
            f"goodput uplift {uplift:.2f}x under the injected kill schedule is "
            f"below the 1.5x floor (on={on}, off={off})"
        )
    return {
        "metric": "preemption_goodput_uplift",
        "value": round(uplift, 3),
        "unit": "x (checkpoint+resume vs restart-from-0 goodput)",
        "vs_baseline": round(uplift, 3),
        "extra": {
            "total_steps": total_steps,
            "kill_steps": kills,
            "checkpoint_every": every,
            "goodput_pct": {
                "uninterrupted": round((ref["ratio"] or 0) * 100, 2),
                "checkpoint_resume": round((on["ratio"] or 0) * 100, 2),
                "restart_from_0": round((off["ratio"] or 0) * 100, 2),
            },
            "ledger_checkpoint_resume": on,
            "ledger_restart_from_0": off,
            "attempts": {"checkpoint_resume": on_attempts, "restart_from_0": off_attempts},
            "loss_identity_steps": len(on_losses),
        },
    }


def _histogram_summaries(family: str, label_key: str = None) -> dict:
    """p50/p90/mean/count per label value (or one merged entry) from a tracer
    histogram — recorded into bench extras so BENCH_* files capture latency
    DISTRIBUTIONS, not just throughput."""
    from dstack_tpu.core import tracing

    snap = tracing.histogram_snapshot(family)
    if snap is None:
        return {}
    _, series = snap
    out = {}
    if label_key is None:
        s = tracing.summary(family)
        return {"all": _round_summary(s)} if s else {}
    for labels, _, _, _ in series:
        key = labels.get(label_key, "?")
        s = tracing.summary(family, labels)
        if s:
            out[key] = _round_summary(s)
    return out


def _round_summary(s: dict) -> dict:
    return {
        "count": s["count"],
        "mean_ms": round(s["mean"] * 1000, 3),
        "p50_ms": round(s["p50"] * 1000, 3),
        "p90_ms": round(s["p90"] * 1000, 3),
    }


def bench_scheduler() -> dict:
    """150 single-job runs through the real scheduler loops against the mock TPU
    backend + scripted runner (no cloud, no network)."""
    import asyncio

    from dstack_tpu.core import tracing
    from dstack_tpu.server.background import tasks
    from tests.common import FakeRunnerClient, api_server, setup_mock_backend, tpu_task_spec

    N = 150  # the reference's per-replica active-run capacity (BASELINE.md)
    tracing.reset()

    async def run() -> float:
        FakeRunnerClient.reset()
        tasks.get_runner_client = FakeRunnerClient.for_jpd
        async with api_server() as api:
            await setup_mock_backend(api)
            for i in range(N):
                await api.post(
                    "/api/project/main/runs/submit", tpu_task_spec(f"bench-{i}", "v5e-8")
                )
            t0 = time.perf_counter()
            for _ in range(1000):
                await tasks.process_submitted_jobs(api.db, batch=25)
                await tasks.process_running_jobs(api.db, batch=50)
                await tasks.process_terminating_jobs(api.db, batch=50)
                await tasks.process_runs(api.db, batch=50)
                done = await api.db.fetchone(
                    "SELECT COUNT(*) AS n FROM runs WHERE status = 'done'"
                )
                if done["n"] >= N:
                    break
            return time.perf_counter() - t0

    async def submit_assign_latency(nudge: bool, n: int = 10,
                                    interval: float = 0.4,
                                    cross_replica: bool = False) -> list:
        """Submit->assign latency with the REAL periodic loop running: each
        submit waits until its job leaves 'submitted'. With the wake nudge
        (submit_run sets the loop's event) the pass starts immediately; with
        the nudge disabled the job waits out the remainder of the poll
        interval — the latency the nudge removes. cross_replica simulates a
        submit landing on ANOTHER replica: the in-process event is hidden (as
        in no-nudge mode) and the loop is registered with the run_leases
        notify poll, so ONLY the DB stamp submit_run writes can cut the sleep
        short — the path that wakes replica B next short-tick."""
        from dstack_tpu.server import background as bg
        from dstack_tpu.server.services import leases as leases_service

        FakeRunnerClient.reset()
        tasks.get_runner_client = FakeRunnerClient.for_jpd
        lats = []
        async with api_server() as api:
            await setup_mock_backend(api)
            sched = bg.BackgroundScheduler()
            notify_poll = None
            if cross_replica:
                notify_poll = lambda: leases_service.last_notify(
                    api.db, "process_submitted_jobs"
                )
            sched.add_periodic(
                lambda: tasks.process_submitted_jobs(api.db, batch=25),
                interval,
                "process_submitted_jobs",
                notify_poll=notify_poll,
            )
            if not nudge:
                # Pre-nudge behavior (and the cross-replica simulation): the
                # loop still polls on its interval but submit_run's wake()
                # finds no event to set — on a real fleet the event lives in
                # the other replica's process.
                bg._WAKE_EVENTS.pop("process_submitted_jobs", None)
            try:
                for i in range(n):
                    tag = "x" if cross_replica else ("n" if nudge else "p")
                    name = f"lat-{tag}-{i}"
                    t0 = time.perf_counter()
                    await api.post(
                        "/api/project/main/runs/submit",
                        tpu_task_spec(name, "v5e-8"),
                    )
                    while True:
                        row = await api.db.fetchone(
                            "SELECT status FROM jobs WHERE run_name = ?", (name,)
                        )
                        if row is not None and row["status"] != "submitted":
                            break
                        await asyncio.sleep(0.002)
                    lats.append(time.perf_counter() - t0)
            finally:
                await sched.stop()
        return lats

    async def project_queue_waits(n: int = 30) -> dict:
        """Per-project queue-wait distribution (ISSUE 19): a 3-project mixed
        submit storm through the real loops, p50/p99 of submission -> first
        provisioning event per project — the fairness readout the usage API's
        queue_wait column aggregates."""
        from dstack_tpu.utils.common import from_iso

        FakeRunnerClient.reset()
        tasks.get_runner_client = FakeRunnerClient.for_jpd
        projects = ["acct-a", "acct-b", "acct-c"]
        async with api_server() as api:
            for p in projects:
                await api.post("/api/projects/create", {"project_name": p})
                await setup_mock_backend(api, p)
            for i in range(n):
                await api.post(
                    f"/api/project/{projects[i % 3]}/runs/submit",
                    tpu_task_spec(f"qw-{i}", "v5e-8" if i % 2 else "v5e-16"),
                )
            for _ in range(400):
                await tasks.process_submitted_jobs(api.db, batch=25)
                await tasks.process_running_jobs(api.db, batch=50)
                await tasks.process_terminating_jobs(api.db, batch=50)
                await tasks.process_runs(api.db, batch=50)
                done = await api.db.fetchone(
                    "SELECT COUNT(*) AS n FROM runs WHERE status = 'done'"
                )
                if done["n"] >= n:
                    break
            rows = await api.db.fetchall(
                "SELECT p.name AS project, r.submitted_at,"
                " MIN(e.timestamp) AS placed"
                " FROM runs r JOIN projects p ON p.id = r.project_id"
                " JOIN run_events e ON e.run_id = r.id AND e.job_id IS NOT NULL"
                "  AND e.new_status = 'provisioning'"
                " GROUP BY r.id"
            )
            waits: dict = {}
            for r in rows:
                w = (
                    from_iso(r["placed"]) - from_iso(r["submitted_at"])
                ).total_seconds()
                waits.setdefault(r["project"], []).append(max(0.0, w))
            out = {}
            for p, vals in sorted(waits.items()):
                vals.sort()
                out[p] = {
                    "runs": len(vals),
                    "p50_ms": round(vals[len(vals) // 2] * 1000, 1),
                    "p99_ms": round(
                        vals[min(len(vals) - 1, int(len(vals) * 0.99))] * 1000, 1
                    ),
                }
            return out

    dt = asyncio.run(run())
    lat_nudge = asyncio.run(submit_assign_latency(nudge=True))
    lat_poll = asyncio.run(submit_assign_latency(nudge=False))
    lat_cross = asyncio.run(submit_assign_latency(nudge=False, cross_replica=True))
    qw_by_project = asyncio.run(project_queue_waits())
    import statistics

    rate = N * 60.0 / dt
    return {
        "metric": "runs_scheduled_to_done_per_min",
        "value": round(rate, 1),
        "unit": "runs/min",
        "vs_baseline": round(rate / 75.0, 4),
        "extra": {
            "runs": N,
            "seconds": round(dt, 2),
            # Per-pass and per-phase latency distributions from the tracer.
            "pass_durations": _histogram_summaries(
                "dstack_tpu_scheduler_pass_duration_seconds", "pass"
            ),
            "phase_durations": {
                phase: (_histogram_summaries(family) or {}).get("all")
                for phase, family in (
                    ("queue", "dstack_tpu_run_queue_wait_seconds"),
                    ("provision", "dstack_tpu_run_provision_duration_seconds"),
                    ("pull", "dstack_tpu_run_pull_duration_seconds"),
                )
            },
            # Submit->assign latency through the live periodic loop: "nudge"
            # = submit_run wakes process_submitted_jobs (current behavior),
            # "interval_poll" = the pre-nudge fixed-interval sleep,
            # "cross_replica" = the in-process event is invisible (submit on
            # replica A) and only the run_leases notify stamp wakes the loop.
            "submit_to_assign_p50_ms": {
                "nudge": round(statistics.median(lat_nudge) * 1000.0, 1),
                "interval_poll": round(statistics.median(lat_poll) * 1000.0, 1),
                "cross_replica": round(statistics.median(lat_cross) * 1000.0, 1),
            },
            # Queue-wait fairness across a 3-project mixed storm (ISSUE 19).
            "queue_wait_by_project": qw_by_project,
        },
    }


async def _seed_bench_service(db, run_name: str, *replica_ports: int) -> None:
    """Insert a ready service run + one running replica per port, each
    pointing at a local stub (no cloud, no runner): the proxy's own overhead
    is what's measured. Replicas are distinct job rows with job_num 0 — the
    same shape ``list_service_replicas`` discovers in production."""
    import json

    proj = await db.fetchone("SELECT * FROM projects LIMIT 1")
    run_spec = {
        "run_name": run_name,
        "configuration": {
            "type": "service",
            "commands": ["serve"],
            "port": 8000,
            "auth": False,
        },
    }
    await db.execute(
        "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at, status,"
        " run_spec) VALUES (?, ?, ?, ?, '2026-01-01', 'running', ?)",
        (f"run-{run_name}", proj["id"], proj["owner_id"], run_name, json.dumps(run_spec)),
    )
    for i, replica_port in enumerate(replica_ports):
        job_spec = {
            "job_name": f"{run_name}-0-{i}",
            "image_name": "stub",
            "requirements": {"resources": {}},
            "service_port": 8000,
        }
        jpd = {
            "backend": "local",  # direct endpoint: no SSH tunnel in the loop
            "instance_type": {"name": "local", "resources": {"cpus": 1, "memory_gb": 1, "disk_gb": 1}},
            "instance_id": f"i-{run_name}-{i}" if i else f"i-{run_name}",
            "hostname": "127.0.0.1",
            "region": "local",
        }
        jrd = {"ports_mapping": {"8000": replica_port}, "probe_ready": True}
        await db.execute(
            "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, job_spec, status,"
            " submitted_at, job_provisioning_data, job_runtime_data)"
            " VALUES (?, ?, ?, ?, 0, ?, 'running', '2026-01-01', ?, ?)",
            (f"job-{run_name}-{i}" if i else f"job-{run_name}", proj["id"],
             f"run-{run_name}", run_name,
             json.dumps(job_spec), json.dumps(jpd), json.dumps(jrd)),
        )


def bench_proxy() -> dict:
    """Requests/sec through the in-server service proxy against a local stub
    replica: the fast path (route-table cache + pooled keep-alive upstream
    session) vs the legacy per-request-DB/per-request-session path."""
    import asyncio

    from aiohttp import web as aioweb

    from dstack_tpu.core.services import http_forward
    from dstack_tpu.server import settings
    from dstack_tpu.server.services import proxy as proxy_service
    from tests.common import api_server

    N = 250
    CONCURRENCY = 16
    # Paired rounds with the mode order flipped each time: medians cancel
    # host-load drift in either direction (shared CI hosts throttle).
    ROUNDS = 6

    async def run() -> dict:
        async def pong(request):
            return aioweb.Response(text="pong")

        stub = aioweb.Application()
        stub.router.add_route("*", "/{tail:.*}", pong)
        stub_runner = aioweb.AppRunner(stub)
        await stub_runner.setup()
        site = aioweb.TCPSite(stub_runner, "127.0.0.1", 0)
        await site.start()
        stub_port = site._server.sockets[0].getsockname()[1]

        saved_ttl = settings.PROXY_ROUTE_CACHE_TTL
        try:
            async with api_server() as api:
                await _seed_bench_service(api.db, "bench-svc", stub_port)
                proxy_port = api.client.server.port
                request_bytes = (
                    b"GET /proxy/services/main/bench-svc/ping HTTP/1.1\r\n"
                    b"Host: 127.0.0.1\r\nConnection: keep-alive\r\n\r\n"
                )

                async def hammer(n: int) -> float:
                    # Raw-socket keep-alive clients: the measurement is the
                    # proxy's cost, not an HTTP client library's.
                    per_worker = n // CONCURRENCY

                    async def worker() -> None:
                        reader, writer = await asyncio.open_connection(
                            "127.0.0.1", proxy_port
                        )
                        try:
                            for _ in range(per_worker):
                                writer.write(request_bytes)
                                await writer.drain()
                                header = await reader.readuntil(b"\r\n\r\n")
                                status = header.split(b" ", 2)[1]
                                assert status == b"200", header[:200]
                                length = 0
                                for line in header.split(b"\r\n"):
                                    if line.lower().startswith(b"content-length:"):
                                        length = int(line.split(b":")[1])
                                await reader.readexactly(length)
                        finally:
                            writer.close()

                    t0 = time.perf_counter()
                    await asyncio.gather(*(worker() for _ in range(CONCURRENCY)))
                    return per_worker * CONCURRENCY / (time.perf_counter() - t0)

                import statistics

                def set_mode(fast: bool) -> None:
                    settings.PROXY_ROUTE_CACHE_TTL = 3600 if fast else 0
                    http_forward.set_pooling(fast)
                    proxy_service.route_table.clear()

                async def measure(fast: bool) -> float:
                    # fast: cached routes + pooled keep-alive connections;
                    # legacy: per-request DB resolution + fresh session.
                    set_mode(fast)
                    await hammer(16)  # warmup (fast: builds route entry + pool)
                    return await hammer(N)

                # Paired design: each round measures both modes back to back
                # (order flipped), and the speedup is the median of PER-ROUND
                # ratios — correlated host-load drift hits both measurements
                # of a pair and cancels out of the ratio.
                legacy_rates, fast_rates, ratios = [], [], []
                for i in range(ROUNDS):
                    pair = {}
                    for fast in ((False, True) if i % 2 == 0 else (True, False)):
                        pair[fast] = await measure(fast)
                    legacy_rates.append(pair[False])
                    fast_rates.append(pair[True])
                    ratios.append(pair[True] / pair[False])
                return {
                    "before": statistics.median(legacy_rates),
                    "after": statistics.median(fast_rates),
                    "speedup": statistics.median(ratios),
                }
        finally:
            settings.PROXY_ROUTE_CACHE_TTL = saved_ttl
            http_forward.set_pooling(True)
            proxy_service.route_table.clear()
            proxy_service.stats.reset()
            await http_forward.close_session()
            await stub_runner.cleanup()

    from dstack_tpu.core import tracing

    tracing.reset()
    r = asyncio.run(run())
    return {
        "metric": "proxy_requests_per_sec",
        "value": round(r["after"], 1),
        "unit": "req/s",
        # Baseline = the legacy per-request-session/per-request-DB path;
        # median of per-round paired ratios (host drift cancels per pair).
        "vs_baseline": round(r["speedup"], 2),
        "extra": {
            "legacy_req_per_sec": round(r["before"], 1),
            "requests": N,
            "concurrency": CONCURRENCY,
            # End-to-end proxied latency distribution across both modes,
            # from the tracer's service-latency histogram.
            "latency": _histogram_summaries(
                "dstack_tpu_service_request_latency_seconds"
            ).get("all"),
        },
    }


def smoke_observability() -> dict:
    """`make smoke-observability`: boot the server in-process, drive one run
    through the full FSM, and assert the events timeline + /metrics histogram
    families are live. Then drive a REAL train workload through the native
    runner agent (local backend) and assert its telemetry lands: workload
    points in the DB, run families on /metrics, workload columns in
    `dstack-tpu metrics` output, and a goodput ledger that accounts for the
    compile stall. Raises (non-zero exit) on any missing piece."""
    import asyncio

    from dstack_tpu.core import tracing
    from dstack_tpu.server.background import tasks
    from tests.common import FakeRunnerClient, api_server, drive, setup_mock_backend, tpu_task_spec

    tracing.reset()

    async def run() -> dict:
        FakeRunnerClient.reset()
        real_runner_client = tasks.get_runner_client
        tasks.get_runner_client = FakeRunnerClient.for_jpd
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("smoke-obs", "v5e-8")
            )
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "smoke-obs"})
            assert run["status"] == "done", f"run ended {run['status']}"

            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "smoke-obs"}
            )
            statuses = [e["new_status"] for e in data["events"] if e["job_id"]]
            assert statuses == [
                "submitted", "provisioning", "pulling", "running", "terminating", "done",
            ], statuses
            phases = data["phases"]
            assert all(
                phases[p] is not None for p in ("queue", "provision", "pull", "total")
            ), phases

            resp = await api.client.get("/metrics")
            text = await resp.text()
            for family in (
                "dstack_tpu_run_queue_wait_seconds",
                "dstack_tpu_run_provision_duration_seconds",
                "dstack_tpu_scheduler_pass_duration_seconds",
            ):
                assert f"{family}_bucket{{" in text, f"{family} has no samples"
                assert f"{family}_count" in text, family
            tasks.get_runner_client = real_runner_client
            workload = await _smoke_workload_telemetry(api)
            return {
                "metric": "smoke_observability",
                "value": len(data["events"]),
                "unit": "events",
                "phases_ms": {
                    k: round(v * 1000, 1) for k, v in phases.items() if v is not None
                },
                "workload": workload,
            }

    result = asyncio.run(run())
    print(json.dumps(result))
    return result


async def _smoke_workload_telemetry(api) -> dict:
    """The workload-telemetry leg of smoke_observability: a real train run on
    the native C++ agent (local backend), sampled live by the metrics loop."""
    import asyncio
    import os

    import dstack_tpu
    from dstack_tpu.server.background import tasks
    from dstack_tpu.server.services import metrics as metrics_service

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(dstack_tpu.__file__)))
    spec = {
        "run_spec": {
            "run_name": "smoke-train",
            "configuration": {
                "type": "task",
                "commands": [
                    # Enough steps that live collection passes observe the
                    # stepping phase (the run gauges render for RUNNING jobs).
                    "python3 -m dstack_tpu.workloads.train"
                    " --config test --steps 400 --batch 2 --seq 64"
                ],
                "env": {
                    "PYTHONPATH": repo_root,
                    "JAX_PLATFORMS": "cpu",
                    "DSTACK_TPU_OVERLAP_FLAGS": "0",
                },
            },
        }
    }
    await api.post("/api/project/main/runs/submit", spec)
    # Collect BEFORE the scheduler passes each round: on the round where the
    # job process exits, the job row still says running, so the tail picks up
    # the emitter's final flush before pull flips the status.
    deadline = asyncio.get_event_loop().time() + 180
    status = None
    live_metrics_text = ""
    while asyncio.get_event_loop().time() < deadline:
        await metrics_service.collect_job_metrics(api.db)
        await tasks.process_submitted_jobs(api.db)
        await tasks.process_running_jobs(api.db)
        await tasks.process_terminating_jobs(api.db)
        await tasks.process_runs(api.db)
        await tasks.process_instances(api.db)
        run = await api.post("/api/project/main/runs/get", {"run_name": "smoke-train"})
        status = run["status"]
        if status == "running" and not live_metrics_text:
            got = await api.db.fetchone(
                "SELECT COUNT(*) AS n FROM workload_metrics_points WHERE kind = 'step'"
            )
            if got["n"] > 0:
                resp = await api.client.get("/metrics")
                live_metrics_text = await resp.text()
        if status in ("done", "failed", "terminated"):
            break
        await asyncio.sleep(0.3)
    assert status == "done", f"real train run ended {status}"

    n = await api.db.fetchone(
        "SELECT COUNT(*) AS n FROM workload_metrics_points"
    )
    assert n["n"] > 0, "no workload telemetry reached the server"
    wl = await api.post(
        "/api/project/main/runs/get_metrics", {"run_name": "smoke-train"}
    )
    assert wl["latest"] is not None, f"no step points: {wl}"
    assert wl["latest"]["tokens_per_sec"] > 0, wl["latest"]
    ledger = wl["goodput"]
    assert ledger["ratio"] is not None and ledger["compile_s"] > 0, ledger

    # The per-run gauges render while the job RUNS (the hardware-gauge
    # contract) — asserted against the exposition scraped mid-run; the step
    # histogram is fed at ingestion and survives the run's completion.
    assert live_metrics_text, "no /metrics scrape landed while the run was live"
    for family in ("dstack_tpu_run_tokens_per_sec", "dstack_tpu_run_goodput_ratio"):
        assert f'{family}{{run="smoke-train"}}' in live_metrics_text, (
            f"{family} missing from the live /metrics scrape"
        )
    resp = await api.client.get("/metrics")
    text = await resp.text()
    assert 'dstack_tpu_run_step_seconds_bucket{le="0.005",run="smoke-train"}' in text

    # The CLI surface: `dstack-tpu metrics smoke-train` (sync requests client
    # against the in-process server — run it off the event loop).
    cli_out = await _render_cli_metrics(api, "smoke-train")
    for column in ("STEP", "TOK/S", "MFU", "goodput:"):
        assert column in cli_out, f"CLI workload column {column!r} missing:\n{cli_out}"
    return {
        "steps_reported": ledger["steps"],
        "goodput_pct": round(ledger["ratio"] * 100, 2),
        "compile_s": ledger["compile_s"],
        "tokens_per_sec": wl["latest"]["tokens_per_sec"],
    }


async def _render_cli_metrics(api, run_name: str) -> str:
    """Run `dstack-tpu metrics <run>` against the in-process test server and
    return its stdout (executor thread: the requests client is synchronous)."""
    import argparse
    import asyncio
    import contextlib
    import io

    from dstack_tpu.api.client import Client
    from dstack_tpu.cli import main as cli_main

    url = str(api.client.make_url("")).rstrip("/")
    client = Client(url, api.token, project="main")
    args = argparse.Namespace(
        run_name=run_name, replica=0, job=0, limit=20, watch=False, interval=5.0,
        json=False,
    )

    def _run() -> str:
        old_client = cli_main._client
        cli_main._client = lambda: client
        try:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                cli_main.cmd_metrics(args)
            return buf.getvalue()
        finally:
            cli_main._client = old_client

    return await asyncio.get_event_loop().run_in_executor(None, _run)


async def _render_cli_usage(api, json_out: bool = False) -> str:
    """Run `dstack-tpu usage` against the in-process test server and return
    its stdout (executor thread: the requests client is synchronous)."""
    import argparse
    import asyncio
    import contextlib
    import io

    from dstack_tpu.api.client import Client
    from dstack_tpu.cli import main as cli_main

    url = str(api.client.make_url("")).rstrip("/")
    client = Client(url, api.token, project="main")
    args = argparse.Namespace(project=None, since=None, json=json_out)

    def _run() -> str:
        old_client = cli_main._client
        cli_main._client = lambda: client
        try:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                cli_main.cmd_usage(args)
            return buf.getvalue()
        finally:
            cli_main._client = old_client

    return await asyncio.get_event_loop().run_in_executor(None, _run)


def smoke_usage() -> dict:
    """`make smoke-usage`: fleet accounting end to end. A real server drives
    one v5e-8 run whose scripted agent keeps it running across several passes
    (so the run has real wall time); one metering tick must land ledger
    chip-seconds within 10% of wall x chips, and `dstack-tpu usage` must
    render the row. Then an unplaceable run (max_price below every offer)
    must leave a placement_attempt event with reason no_offers, surface
    `waiting: no_offers` in ps -v, and raise the pending-reason gauge.
    Raises (non-zero exit) on any missing piece."""
    import asyncio

    from dstack_tpu.core import tracing
    from dstack_tpu.server.background import tasks
    from dstack_tpu.server.services import usage as usage_service
    from dstack_tpu.utils.common import from_iso
    from tests.common import (
        FakeRunnerClient,
        api_server,
        setup_mock_backend,
        tpu_task_spec,
    )

    tracing.reset()
    usage_service.reset()

    class SlowAgent(FakeRunnerClient):
        # Stay running for several pulls so the run accrues real wall time.
        def default_script(self):
            return [{"job_states": [{"state": "running"}], "logs": [], "offset": 1}] * 8 + [
                {
                    "job_states": [{"state": "done", "exit_status": 0}],
                    "logs": [],
                    "offset": 2,
                }
            ]

    async def run() -> dict:
        SlowAgent.reset()
        tasks.get_runner_client = SlowAgent.for_jpd
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("smoke-acct", "v5e-8")
            )
            status = None
            for _ in range(40):
                await tasks.process_submitted_jobs(api.db)
                await tasks.process_running_jobs(api.db)
                await tasks.process_terminating_jobs(api.db)
                await tasks.process_runs(api.db)
                await tasks.process_instances(api.db)
                row = await api.post(
                    "/api/project/main/runs/get", {"run_name": "smoke-acct"}
                )
                status = row["status"]
                if status in ("done", "failed", "terminated"):
                    break
                await asyncio.sleep(0.1)
            assert status == "done", f"run ended {status}"

            # One metering tick AFTER completion still captures the whole
            # lifecycle window (accrual is lifecycle-anchored, not tick-based).
            touched = await usage_service.meter(api.db)
            assert touched == 1, f"meter touched {touched} runs"

            anchor = await api.db.fetchone(
                "SELECT MIN(timestamp) AS ts FROM run_events"
                " WHERE job_id IS NOT NULL AND new_status = 'provisioning'"
            )
            job = await api.db.fetchone(
                "SELECT finished_at FROM jobs WHERE finished_at IS NOT NULL"
            )
            wall = (
                from_iso(job["finished_at"]) - from_iso(anchor["ts"])
            ).total_seconds()
            assert wall > 0.5, f"run too fast to meter meaningfully ({wall:.3f}s)"
            ledger = await api.db.fetchone(
                "SELECT SUM(chip_seconds) AS cs, SUM(dollars) AS d FROM usage_samples"
            )
            expected = 8 * wall  # v5e-8: 8 chips, 1 host
            drift = abs(ledger["cs"] - expected) / expected
            assert drift < 0.10, (
                f"ledger {ledger['cs']:.2f} chip-s vs wall*chips {expected:.2f}"
                f" ({drift * 100:.1f}% off)"
            )
            assert ledger["d"] > 0

            # The CLI renders the row (fleet header + per-run table).
            cli_out = await _render_cli_usage(api)
            for needle in ("fleet:", "smoke-acct", "CHIP-S", "QUEUE-WAIT"):
                assert needle in cli_out, f"usage CLI missing {needle!r}:\n{cli_out}"

            # Placement decision log: an unplaceable run says WHY it waits.
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec(
                    "smoke-stuck",
                    "v5e-8",
                    max_price=0.0001,
                    retry={"on_events": ["no-capacity"], "duration": 3600},
                ),
            )
            await tasks.process_submitted_jobs(api.db)
            events = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "smoke-stuck"}
            )
            attempts = [
                e for e in events["events"] if e["new_status"] == "placement_attempt"
            ]
            assert attempts and attempts[0]["reason"] == "no_offers", events["events"]
            stuck = await api.post(
                "/api/project/main/runs/get", {"run_name": "smoke-stuck"}
            )
            assert stuck["status_message"] == "waiting: no_offers", stuck
            resp = await api.client.get("/metrics")
            text = await resp.text()
            needle = (
                'dstack_tpu_run_pending_reason{reason="no_offers",run="smoke-stuck"} 1'
            )
            assert needle in text, "pending-reason gauge missing from /metrics"

            return {
                "metric": "smoke_usage",
                "value": round(ledger["cs"], 2),
                "unit": "chip_seconds",
                "wall_chip_seconds": round(expected, 2),
                "drift_pct": round(drift * 100, 2),
                "dollars": round(ledger["d"], 6),
                "pending_reason": attempts[0]["reason"],
            }

    result = asyncio.run(run())
    print(json.dumps(result))
    return result


def smoke_gang() -> dict:
    """`make smoke-gang`: gang-wide observability end to end. A simulated
    4-host gang (one run, 4 jobs on the mock backend) runs through the REAL
    server with REAL TelemetryEmitters — each job's sidecar written by the
    production emitter, tailed by a scripted agent exactly like the C++ agent
    tails it — and host 3's step cadence artificially delayed 2.5x. Asserts
    the acceptance criterion: the straggler is detected and attributed to the
    RIGHT host within 2 collection passes of the skew appearing (run_event +
    `dstack_tpu_run_straggler{host}` on a LIVE /metrics scrape + per-host CLI
    table), while the goodput ledger and step histogram stay lead-lineage-
    only. Raises (non-zero exit) on any missing piece."""
    import asyncio
    import os
    import tempfile

    from dstack_tpu.core import tracing
    from dstack_tpu.server.background import tasks
    from dstack_tpu.server.services import gang_health
    from dstack_tpu.server.services import metrics as metrics_service
    from dstack_tpu.utils.common import now_utc, to_iso
    from dstack_tpu.workloads.telemetry import TelemetryEmitter
    from tests.common import FakeRunnerClient, api_server, drive, setup_mock_backend, tpu_task_spec
    from tests.test_run_events import parse_exposition

    tracing.reset()
    gang_health.reset()
    tmp = tempfile.mkdtemp(prefix="smoke-gang-")

    class GangAgent(FakeRunnerClient):
        """A scripted agent whose /api/metrics tails a real emitter's sidecar
        (complete lines only, offset advancing — the executor.cpp contract)
        and adds the agent-side kind="host" hardware point."""

        sidecars: dict = {}  # job_num -> path

        def __init__(self, key):
            super().__init__(key)
            self.offset = 0

        def default_script(self):
            # The gang stays running until the smoke is done observing it.
            return [{"job_states": [{"state": "running"}], "logs": [], "offset": 1}]

        async def metrics(self):
            n = self.submitted.job_num if self.submitted else 0
            path = type(self).sidecars.get(n)
            points = []
            if path and os.path.exists(path):
                with open(path, "rb") as f:
                    f.seek(self.offset)
                    chunk = f.read()
                last_nl = chunk.rfind(b"\n")
                if last_nl >= 0:
                    for line in chunk[: last_nl + 1].splitlines():
                        try:
                            points.append(json.loads(line))
                        except ValueError:
                            continue
                    self.offset += last_nl + 1
            points.append({
                "ts": to_iso(now_utc()), "kind": "host", "host": f"host{n}",
                "cpu_percent": 40.0 + n, "mem_used_bytes": (n + 1) * 2 ** 30,
            })
            return {
                "timestamp": to_iso(now_utc()),
                "cpu_usage_micro": 1000,
                "memory_usage_bytes": 1 << 20,
                "workload": points,
            }

    async def run() -> dict:
        GangAgent.reset()
        GangAgent.sidecars = {}
        real_tasks_client = tasks.get_runner_client
        real_metrics_client = metrics_service.get_runner_client
        tasks.get_runner_client = GangAgent.for_jpd
        metrics_service.get_runner_client = GangAgent.for_jpd
        emitters = []
        try:
            async with api_server() as api:
                await setup_mock_backend(api)
                await api.post(
                    "/api/project/main/runs/submit",
                    tpu_task_spec("smoke-gang", "v5e-32"),  # 4 hosts
                )
                await drive(api.db)
                run = await api.post(
                    "/api/project/main/runs/get", {"run_name": "smoke-gang"}
                )
                assert run["status"] == "running", f"gang not running: {run['status']}"
                jobs = await api.db.fetchall(
                    "SELECT job_num FROM jobs WHERE status = 'running'"
                )
                assert len(jobs) == 4, f"expected a 4-host gang, got {len(jobs)}"

                # One REAL emitter per host; host 3's cadence delayed 2.5x.
                for n in range(4):
                    path = os.path.join(tmp, f"job{n}.jsonl")
                    GangAgent.sidecars[n] = path
                    em = TelemetryEmitter(path, flush_interval=60)  # manual flush
                    em.set_identity(host=f"host{n}", proc=n)
                    emitters.append(em)

                step = {"n": 0}

                def emit_window(steps=5, slow_factor=2.5):
                    for _ in range(steps):
                        step["n"] += 1
                        for n, em in enumerate(emitters):
                            dt = 0.05 * (slow_factor if n == 3 else 1.0)
                            em.step(step["n"], round(dt, 6),
                                    tokens_per_sec=1000.0, mfu=0.3,
                                    input_wait_s=0.001,
                                    collective_wait_s=0.001 if n == 3 else dt - 0.05 + 0.002)
                    for em in emitters:
                        em.flush()

                async def straggler_events():
                    return await api.db.fetchall(
                        "SELECT * FROM run_events WHERE new_status = 'straggler_detected'"
                    )

                # Pass 1: skew appears; the rule needs 2 consecutive windows.
                emit_window()
                await tasks.process_metrics(api.db)
                assert not await straggler_events(), "flagged after ONE window (no hysteresis?)"
                # Pass 2: detection — within 2 collection passes of the skew.
                emit_window()
                await tasks.process_metrics(api.db)
                events = await straggler_events()
                assert len(events) == 1, f"no straggler event after 2 passes: {events}"
                assert events[0]["reason"] == "host3", (
                    f"straggler attributed to {events[0]['reason']}, expected host3"
                )

                # The {host} gauge on a LIVE scrape (run still running).
                resp = await api.client.get("/metrics")
                families = parse_exposition(await resp.text())
                straggler = {
                    l["host"]: v
                    for _, l, v in families["dstack_tpu_run_straggler"]["samples"]
                    if l.get("run") == "smoke-gang"
                }
                assert straggler.get("host3") == 1.0, straggler
                assert all(v == 0.0 for h, v in straggler.items() if h != "host3"), straggler
                skew = next(
                    v for _, l, v in
                    families["dstack_tpu_run_step_skew_ratio"]["samples"]
                    if l.get("run") == "smoke-gang"
                )
                assert skew > 2.0, f"skew gauge {skew} (expected ~2.5)"
                host_cpu = {
                    l["host"]: v
                    for _, l, v in families["dstack_tpu_host_cpu_percent"]["samples"]
                    if l.get("run") == "smoke-gang"
                }
                assert host_cpu.get("host3") == 43.0, host_cpu

                # Lead-lineage-only invariants survive the per-host join: the
                # step histogram counts ONE host's stream, not 4x.
                hist = families["dstack_tpu_run_step_seconds"]["samples"]
                counts = [v for nm, l, v in hist
                          if nm.endswith("_count") and l.get("run") == "smoke-gang"]
                assert counts == [float(step["n"])], (
                    f"step histogram {counts} != lead stream {step['n']} (gang multiplied?)"
                )

                # The per-host CLI table names and flags the host.
                cli_out = await _render_cli_metrics(api, "smoke-gang")
                for needle in ("HOST", "host3", "STRAGGLER", "step skew:", "COLL WAIT"):
                    assert needle in cli_out, f"CLI missing {needle!r}:\n{cli_out}"

                # The timeline surfaces it too (dstack-tpu events).
                data = await api.post(
                    "/api/project/main/runs/get_events", {"run_name": "smoke-gang"}
                )
                straggler_ev = [
                    e for e in data["events"] if e["new_status"] == "straggler_detected"
                ]
                assert straggler_ev and straggler_ev[0]["reason"] == "host3"

                return {
                    "metric": "smoke_gang",
                    "value": 2,
                    "unit": "passes_to_detect",
                    "skew_ratio": round(skew, 3),
                    "straggler": events[0]["reason"],
                    "gang_hosts": len(jobs),
                    "lead_steps": step["n"],
                }
        finally:
            for em in emitters:
                em.close(timeout=0.2)
            tasks.get_runner_client = real_tasks_client
            metrics_service.get_runner_client = real_metrics_client

    result = asyncio.run(run())
    print(json.dumps(result))
    return result


def smoke_preemption() -> dict:
    """`make smoke-preemption`: the elastic-training rescue loop end to end.
    Boots the server, drives a REAL train run through the native C++ agent
    (local backend) with async checkpointing on, kills the workload mid-run
    (injected crash at a fixed step), and asserts the whole chain: the gang
    retries (run_events reason=gang_retry), the resubmitted attempt RESUMES
    from the last checkpoint (its step points continue past the save point
    instead of restarting at 2), the goodput ledger debits restart_s, and
    the dstack_tpu_run_recovery_seconds histogram lands on /metrics. Raises
    (non-zero exit) on any missing piece."""
    import asyncio
    import os
    import shutil
    import tempfile

    import dstack_tpu
    from dstack_tpu.core import tracing
    from dstack_tpu.server import settings
    from dstack_tpu.server.background import tasks
    from dstack_tpu.server.services import metrics as metrics_service
    from tests.common import api_server

    tracing.reset()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(dstack_tpu.__file__)))
    ckpt_dir = tempfile.mkdtemp(prefix="dstack-smoke-preempt-")
    crash_step, every, steps = 12, 5, 20
    saved_backoff = settings.RETRY_BACKOFF_BASE
    settings.RETRY_BACKOFF_BASE = 0.2  # don't stall the smoke on retry backoff

    async def run() -> dict:
        async with api_server() as api:
            spec = {
                "run_spec": {
                    "run_name": "smoke-preempt",
                    "configuration": {
                        "type": "task",
                        "commands": [
                            "python3 -m dstack_tpu.workloads.train"
                            f" --config test --steps {steps} --batch 2 --seq 64"
                            " --prefetch 0"
                            f" --checkpoint-every {every}"
                            f" --checkpoint-dir {ckpt_dir} --resume"
                        ],
                        "env": {
                            "PYTHONPATH": repo_root,
                            "JAX_PLATFORMS": "cpu",
                            "DSTACK_TPU_OVERLAP_FLAGS": "0",
                            "DSTACK_TPU_TRAIN_CRASH_AT_STEP": str(crash_step),
                        },
                        "retry": {"on_events": ["error"], "duration": 600},
                    },
                }
            }
            await api.post("/api/project/main/runs/submit", spec)
            deadline = asyncio.get_event_loop().time() + 420
            status = None
            while asyncio.get_event_loop().time() < deadline:
                await metrics_service.collect_job_metrics(api.db)
                await tasks.process_submitted_jobs(api.db)
                await tasks.process_running_jobs(api.db)
                await tasks.process_terminating_jobs(api.db)
                await tasks.process_runs(api.db)
                await tasks.process_instances(api.db)
                run = await api.post(
                    "/api/project/main/runs/get", {"run_name": "smoke-preempt"}
                )
                status = run["status"]
                if status in ("done", "failed", "terminated"):
                    break
                await asyncio.sleep(0.3)
            assert status == "done", f"rescued run ended {status}"

            # The gang retried exactly once, and the timeline says why.
            jobs = await api.db.fetchall(
                "SELECT submission_num, status FROM jobs WHERE run_name = 'smoke-preempt'"
            )
            assert max(j["submission_num"] for j in jobs) == 1, jobs
            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "smoke-preempt"}
            )
            retries = [
                e for e in data["events"]
                if e["new_status"] == "submitted" and e["reason"] == "gang_retry"
            ]
            assert retries, "no gang_retry submitted event in the timeline"

            # The resumed attempt continued from the checkpoint: its first
            # step point is past the last save, not a restart at step 2.
            resumed_steps = await api.db.fetchall(
                "SELECT w.data FROM workload_metrics_points w JOIN jobs j ON j.id = w.job_id"
                " WHERE j.run_name = 'smoke-preempt' AND j.submission_num = 1"
                " AND w.kind = 'step'"
            )
            assert resumed_steps, "no telemetry from the resumed attempt"
            first_resumed = min(json.loads(r["data"])["step"] for r in resumed_steps)
            last_save = (crash_step // every) * every
            assert first_resumed > last_save, (
                f"resumed attempt started at step {first_resumed}, expected"
                f" > {last_save} (the last checkpoint)"
            )

            # Goodput ledger: the preemption shows up as restart_s (the gap
            # between the killed process's last point and the resume's
            # run_start), rework stays bounded by crash-to-checkpoint.
            wl = await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "smoke-preempt"}
            )
            ledger = wl["goodput"]
            assert ledger["restart_s"] > 0, f"no restart debit: {ledger}"
            assert ledger["steps"] >= steps - 2, ledger

            resp = await api.client.get("/metrics")
            text = await resp.text()
            needle = 'dstack_tpu_run_recovery_seconds_count{run="smoke-preempt"}'
            assert needle in text, "recovery histogram missing from /metrics"
            count = float(
                next(l for l in text.splitlines() if l.startswith(needle)).split()[-1]
            )
            assert count >= 1, text[:500]
            return {
                "metric": "smoke_preemption",
                "value": round(ledger["restart_s"], 2),
                "unit": "restart_s recovered",
                "first_resumed_step": first_resumed,
                "recoveries": count,
                "goodput_pct": round((ledger["ratio"] or 0) * 100, 2),
                "ledger": ledger,
            }

    try:
        result = asyncio.run(run())
    finally:
        settings.RETRY_BACKOFF_BASE = saved_backoff
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    print(json.dumps(result))
    return result


def _serve_bench_config():
    """Tiny fp32 model for the CPU serving bench/smoke: big enough that a
    decode step does real matmul work, small enough that a full open-loop run
    finishes in seconds."""
    from dstack_tpu.workloads.config import get_config

    return get_config(
        "test", n_layers=2, d_model=128, n_heads=4, n_kv_heads=2, d_ff=256,
        vocab_size=1024, max_seq_len=256, dtype="float32",
        param_dtype="float32", remat=False,
    )


def _serve_schedule(n_requests: int, seed: int = 7) -> list:
    """Open-loop arrival plan: (arrival_s, prompt_tokens, max_new). MIXED
    generation lengths on purpose (2..96): uniform-length batches hide exactly
    the slot waste static batching suffers — a finished short request idles
    its slot until the longest one in the batch drains. Arrivals saturate the
    engine (~200 req/s offered), so throughput measures drain capacity and
    queueing shows up in the TTFT tail."""
    import random

    rng = random.Random(seed)
    schedule, t = [], 0.0
    for _ in range(n_requests):
        t += rng.expovariate(1 / 0.005)
        prompt = [rng.randrange(1, 1024) for _ in range(rng.randint(4, 32))]
        schedule.append((t, prompt, rng.randint(2, 96)))
    return schedule


def _serve_prefix_schedule(
    n_requests: int, seed: int = 11, shared_frac: float = 0.8,
    prefix_len: int = 96,
) -> list:
    """Shared-prefix arrival plan: `shared_frac` of requests open with the
    same `prefix_len`-token prefix (a system prompt / few-shot header) plus a
    short unique suffix; the rest are fully random. Generation is kept short
    on purpose — the workload is prefill-dominated, which is exactly the
    regime prefix caching exists for."""
    import random

    rng = random.Random(seed)
    prefix = [rng.randrange(1, 1024) for _ in range(prefix_len)]
    schedule, t = [], 0.0
    for _ in range(n_requests):
        t += rng.expovariate(1 / 0.005)
        suffix = [rng.randrange(1, 1024) for _ in range(rng.randint(4, 12))]
        if rng.random() < shared_frac:
            prompt = prefix + suffix
        else:
            prompt = [rng.randrange(1, 1024) for _ in range(rng.randint(16, 48))]
        schedule.append((t, prompt, rng.randint(2, 12)))
    return schedule


def _prefix_cache_compare(cfg, params, rounds: int = 3) -> dict:
    """Shared-prefix mix, prefix cache on vs off (paired order-flipped
    rounds, median-of-ratio like the continuous/static headline). The on
    engine prefills only each request's unique suffix after the first."""
    import statistics

    n = int(os.environ.get("DSTACK_TPU_BENCH_SERVE_PREFIX_REQUESTS", "24"))
    schedule = _serve_prefix_schedule(n)
    # Both sides run the same fixed prefill chunk: chunk shapes then compile
    # once for either variant (a bucketed whole-suffix prefill would keep
    # minting new shapes mid-measurement), and the on/off delta isolates the
    # cache — the only difference left is how many chunks each prompt needs.
    pool = dict(page_size=16, num_pages=96, max_batch=4, max_seq=192,
                prefill_chunk=32)
    for on in (True, False):
        _run_serve_variant(cfg, params, schedule, prefix_cache=on, **pool)
    on_rounds, off_rounds, ratios = [], [], []
    hit_rate = 0.0
    for i in range(rounds):
        pair = {}
        order = (True, False) if i % 2 == 0 else (False, True)
        for on in order:
            pair[on] = _run_serve_variant(
                cfg, params, schedule, prefix_cache=on, **pool
            )
        on_rounds.append(pair[True])
        off_rounds.append(pair[False])
        hit_rate = max(hit_rate, pair[True].get("prefix_hit_rate", 0.0))
        ratios.append(
            pair[True]["tokens_per_sec"] / pair[False]["tokens_per_sec"]
        )
    mid = sorted(range(rounds), key=lambda i: ratios[i])[rounds // 2]
    return {
        "tokens_per_sec_on": on_rounds[mid]["tokens_per_sec"],
        "tokens_per_sec_off": off_rounds[mid]["tokens_per_sec"],
        "speedup": round(statistics.median(ratios), 2),
        "per_round_ratio": [round(r, 2) for r in ratios],
        "prefix_hit_rate": hit_rate,
        "shared_frac": 0.8,
    }


def _long_prompt_itl_compare(cfg, params) -> dict:
    """One giant prompt injected into a stream of short requests: inter-token
    latency p99 of the SHORT requests, chunked prefill vs whole-prompt. The
    headline TPU question scaled to CPU: the giant prompt's single monolithic
    prefill step is exactly the decode stall chunking removes. The injected
    prompt is 32k tokens in the production geometry; here it is scaled with
    the bench model (DSTACK_TPU_BENCH_SERVE_LONG_PROMPT, default 384)."""
    import random

    long_len = int(os.environ.get("DSTACK_TPU_BENCH_SERVE_LONG_PROMPT", "384"))
    rng = random.Random(13)
    pool = dict(page_size=16, num_pages=96, max_batch=4, max_seq=512)
    long_prompt = [rng.randrange(1, 1024) for _ in range(long_len)]

    from dstack_tpu.workloads import serve as serve_lib

    out = {}
    for label, chunk in (("unchunked", 0), ("chunk32", 32)):
        engine = serve_lib.ServeEngine(
            cfg, serve_lib.EngineConfig(prefill_chunk=chunk, **pool),
            params=params,
        )
        warm = engine.submit([1, 2, 3], max_new_tokens=2)
        while not warm.done:
            engine.step()
        # Short decodes running steadily...
        shorts = [
            engine.submit([rng.randrange(1, 1024) for _ in range(8)],
                          max_new_tokens=64)
            for _ in range(3)
        ]
        for _ in range(4):
            engine.step()
        # ...then the giant prompt lands mid-flight.
        engine.submit(long_prompt, max_new_tokens=8)
        itls = []
        short_ids = {s.req_id for s in shorts}
        while engine.has_work():
            t0 = time.perf_counter()
            events = engine.step()
            dt = time.perf_counter() - t0
            for ev in events:
                if ev.req_id in short_ids:
                    itls.append(dt)
        from dstack_tpu.utils.common import nearest_rank

        itls.sort()
        out[label] = {
            "itl_p50_ms": round(nearest_rank(itls, 0.50) * 1000, 2),
            "itl_p99_ms": round(nearest_rank(itls, 0.99) * 1000, 2),
            "itl_max_ms": round(itls[-1] * 1000, 2),
        }
    out["long_prompt_tokens"] = long_len
    out["p99_improvement"] = round(
        out["unchunked"]["itl_p99_ms"] / max(out["chunk32"]["itl_p99_ms"], 1e-9),
        2,
    )
    return out


def _spec_decode_check(cfg, params, draft_params=None, prompts=None,
                       max_new=24) -> dict:
    """Speculative decode vs the plain engine on the same prompts: records
    the acceptance rate and RAISES if any emitted token differs — a spec
    implementation that drifts from greedy is a correctness bug, not a perf
    data point. Strict identity only holds in fp32 (the verify forward
    reorders attention reductions vs the C==1 decode, and bf16 rounding can
    flip argmax near-ties — see the serve.py numerics caveat), so this hard
    check is pinned to fp32 regardless of what the bench config says.

    ``draft_params`` swaps the proposer from host n-gram to the model-based
    draft head (accept-rate fallback disabled — this measures the head, not
    the safety net); the token-identity assertion is the same either way,
    because drafts are only ever a throughput bet the verify forward scores.
    ``prompts`` overrides the default repetitive mix (which exists to feed
    the n-gram proposer so acceptance is exercised, not just trivially 0)."""
    from dstack_tpu.workloads import serve as serve_lib

    import random

    if getattr(cfg, "dtype", "float32") != "float32":
        raise ValueError(
            "_spec_decode_check requires an fp32 config: in bf16 the verify "
            "forward can legitimately flip argmax near-ties, and this check "
            "is specified to fail only on real scheduling bugs"
        )

    if prompts is None:
        rng = random.Random(17)
        # Repetitive prompts on purpose: the n-gram proposer feeds on
        # recurrence (the greedy tail of a tiny synthetic model loops
        # quickly, too).
        base = [rng.randrange(1, 512) for _ in range(6)]
        prompts = [base * 3 + [rng.randrange(1, 512)] for _ in range(4)]
    pool = dict(page_size=16, num_pages=96, max_batch=4, max_seq=192)
    outputs = {}
    for label, k in (("plain", 0), ("spec4", 4)):
        engine = serve_lib.ServeEngine(
            cfg,
            serve_lib.EngineConfig(spec_tokens=k,
                                   spec_fallback_threshold=0.0, **pool),
            params=params,
            draft_params=draft_params if k else None,
        )
        reqs = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
        steps = 0
        t0 = time.perf_counter()
        while engine.has_work():
            engine.step()
            steps += 1
            assert steps < 5000
        outputs[label] = {
            "tokens": [r.tokens for r in reqs],
            "steps": steps,
            "wall_s": time.perf_counter() - t0,
            "accept_rate": engine.spec_accept_rate,
        }
    if outputs["spec4"]["tokens"] != outputs["plain"]["tokens"]:
        raise RuntimeError(
            "speculative decode diverged from greedy: "
            f"plain={outputs['plain']['tokens']} "
            f"spec={outputs['spec4']['tokens']}"
        )
    return {
        "token_identical": True,
        "proposer": "draft" if draft_params is not None else "ngram",
        "spec_accept_rate": round(outputs["spec4"]["accept_rate"], 4),
        "steps_plain": outputs["plain"]["steps"],
        "steps_spec": outputs["spec4"]["steps"],
        "step_reduction": round(
            outputs["plain"]["steps"] / max(outputs["spec4"]["steps"], 1), 2
        ),
    }


def _natural_prompts(n, seed, vocab=1024, lo=12, hi=32) -> list:
    """Non-repetitive natural-text-like prompts: Zipf-weighted unigram draws
    over the vocab. Real text has a heavy-tailed unigram distribution but
    (unlike the repetitive mixes above) almost no verbatim n-gram recurrence
    inside one prompt — exactly the regime where n-gram lookup hits its
    acceptance ceiling and a model-based head does not."""
    import random

    rng = random.Random(seed)
    ranks = list(range(1, vocab))
    weights = [1.0 / (r ** 1.1) for r in ranks]
    return [
        rng.choices(ranks, weights=weights, k=rng.randint(lo, hi))
        for _ in range(n)
    ]


def _distill_draft_head(cfg, params, steps=None, seed=29):
    """On-policy distillation for the draft-vs-ngram bench: roll the target
    out greedily on natural-mix prompts (a plain engine — the exact serve
    distribution, prompt + the target's own continuations), then teacher-
    force the head on those sequences with train.py's distill step. Returns
    ``(draft_params, info)``; the loss trajectory lands in bench extras so a
    regression in the distill loop is visible from the bench line alone."""
    import jax
    import jax.numpy as jnp

    from dstack_tpu.workloads import model as model_lib
    from dstack_tpu.workloads import serve as serve_lib
    from dstack_tpu.workloads import train as train_lib

    steps = steps or int(os.environ.get("DSTACK_TPU_BENCH_DRAFT_STEPS", "80"))
    prompts = _natural_prompts(16, seed)
    engine = serve_lib.ServeEngine(
        cfg,
        serve_lib.EngineConfig(page_size=16, num_pages=96, max_batch=4,
                               max_seq=192),
        params=params,
    )
    reqs = [engine.submit(p, max_new_tokens=32) for p in prompts]
    guard = 0
    while engine.has_work():
        engine.step()
        guard += 1
        assert guard < 20000, "rollout engine never drained"
    seq = min(len(p) for p in prompts) + 32  # every row full, no padding
    rows = [(p + r.tokens)[:seq] for p, r in zip(prompts, reqs)]
    tokens = jnp.asarray(rows, jnp.int32)

    draft = model_lib.init_draft_params(cfg, jax.random.PRNGKey(seed + 1))
    opt = train_lib.make_optimizer(learning_rate=5e-3)
    state = train_lib.DraftTrainState(
        params=params, draft=draft, opt_state=opt.init(draft),
        step=jnp.zeros((), jnp.int32),
    )
    step_fn = train_lib.make_draft_distill_step(cfg, opt)
    losses = []
    for _ in range(steps):
        state, loss = step_fn(state, tokens)
        losses.append(float(loss))
    return state.draft, {
        "steps": steps,
        "rollout_tokens": int(tokens.size),
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
    }


def _draft_vs_ngram_compare(cfg, params) -> dict:
    """The draft-head headline: on a NON-repetitive natural-text-like mix,
    the distilled draft head vs the n-gram proposer, side by side — accept
    rate and decode-step reduction vs the non-speculative engine, with the
    token-identity assertion running for BOTH proposers.

    The head is distilled on greedy rollouts from the same prompt mix the
    bench serves — the production shape (EAGLE-style heads train on live
    traffic), and the only meaningful protocol here: a random-init tiny
    target has no cross-prompt structure to generalize over, so a held-out
    split would measure noise, not the proposer. The n-gram proposer gets
    the same serve-time information it always has (each request's own
    emitted stream); what the comparison isolates is the mechanism — on
    text without verbatim recurrence, lookup has nothing to hit and a
    model-based head still does."""
    draft, distill = _distill_draft_head(cfg, params)
    prompts = _natural_prompts(6, seed=29)
    ngram = _spec_decode_check(cfg, params, prompts=prompts)
    head = _spec_decode_check(cfg, params, draft_params=draft,
                              prompts=prompts)
    return {
        "mix": "zipf_natural",
        "ngram_accept_rate": ngram["spec_accept_rate"],
        "draft_accept_rate": head["spec_accept_rate"],
        "ngram_step_reduction": ngram["step_reduction"],
        "draft_step_reduction": head["step_reduction"],
        "token_identical": True,  # both checks raise on any divergence
        "distill": distill,
    }


def _run_serve_variant(cfg, params, schedule, **engine_kwargs) -> dict:
    """Drive one engine variant through the open-loop schedule; report
    tokens/s/chip, p50/p99 TTFT, and inter-token latency. Open loop: arrivals
    follow the schedule's clock whether or not the engine keeps up, so queue
    growth shows up as TTFT tail, exactly like production overload."""
    from dstack_tpu.workloads import serve as serve_lib

    engine = serve_lib.ServeEngine(
        cfg, serve_lib.EngineConfig(**engine_kwargs), params=params
    )
    # Warm the jit caches (decode + this schedule's prefill buckets) so the
    # measured run times scheduling, not compilation.
    warm = engine.submit([1, 2, 3], max_new_tokens=2)
    while not warm.done:
        engine.step()
    # Fresh flight recorder sized to retain EVERY request of the schedule (the
    # default ring is sized for production debugging, not benchmarking), and
    # free of the warm-up request, so the stage-breakdown percentiles below
    # cover exactly the measured run.
    engine.flight = serve_lib.FlightRecorder(capacity=len(schedule) + 8)

    arrivals = {}      # req_id -> arrival time
    token_times = {}   # req_id -> [emission times]
    reqs = {}
    idx = 0
    t0 = time.perf_counter()
    first_arrival = schedule[0][0]
    while idx < len(schedule) or engine.has_work():
        now = time.perf_counter() - t0
        while idx < len(schedule) and schedule[idx][0] <= now:
            arrival, prompt, max_new = schedule[idx]
            req = engine.submit(prompt, max_new_tokens=max_new)
            arrivals[req.req_id] = arrival
            token_times[req.req_id] = []
            reqs[req.req_id] = req
            idx += 1
        if engine.has_work():
            events = engine.step()
            t_emit = time.perf_counter() - t0
            for ev in events:
                token_times[ev.req_id].append(t_emit)
        elif idx < len(schedule):
            time.sleep(max(0.0, schedule[idx][0] - (time.perf_counter() - t0)))
    t_end = time.perf_counter() - t0

    from dstack_tpu.utils.common import nearest_rank

    ttfts = sorted(
        times[0] - arrivals[rid] for rid, times in token_times.items() if times
    )
    itls = sorted(
        b - a for times in token_times.values() for a, b in zip(times, times[1:])
    )
    total_tokens = sum(len(t) for t in token_times.values())
    assert all(r.done for r in reqs.values()), "engine left requests unfinished"

    # Stage attribution from the engine's flight recorder (ISSUE 18): where
    # each request's wall time went — admission-queue wait vs prefill vs
    # decode — so a routing/policy A/B can see WHICH stage moved, not just
    # that the TTFT tail did.
    def _stage_pcts(key: str) -> tuple:
        vals = sorted(t.get(key, 0.0) for t in engine.flight.snapshot())
        if not vals:
            return 0.0, 0.0
        return (
            round(nearest_rank(vals, 0.50) * 1000, 2),
            round(nearest_rank(vals, 0.99) * 1000, 2),
        )

    queue_p50, queue_p99 = _stage_pcts("queue_wait_s")
    prefill_p50, prefill_p99 = _stage_pcts("prefill_s")
    decode_p50, decode_p99 = _stage_pcts("decode_s")
    return {
        "tokens_per_sec": round(total_tokens / max(t_end - first_arrival, 1e-9), 1),
        "ttft_p50_ms": round(nearest_rank(ttfts, 0.50) * 1000, 1),
        "ttft_p99_ms": round(nearest_rank(ttfts, 0.99) * 1000, 1),
        "itl_p50_ms": round(nearest_rank(itls, 0.50) * 1000, 2),
        "itl_p99_ms": round(nearest_rank(itls, 0.99) * 1000, 2),
        "queue_wait_p50_ms": queue_p50,
        "queue_wait_p99_ms": queue_p99,
        "prefill_p50_ms": prefill_p50,
        "prefill_p99_ms": prefill_p99,
        "decode_p50_ms": decode_p50,
        "decode_p99_ms": decode_p99,
        "steps": engine.total_steps,
        "preemptions": engine.total_preemptions,
        "requests": len(schedule),
        "policy": engine.ecfg.policy,
        "page_size": engine.ecfg.page_size,
        "prefix_hit_rate": round(engine.prefix_hit_rate, 4),
        "spec_accept_rate": round(engine.spec_accept_rate, 4),
    }


def _decode_itl_compare(cfg, params, steps: int = 12) -> dict:
    """Per-step decode latency (the inter-token-latency floor) with the
    Pallas paged-attention kernel vs the XLA gather, on identical engine
    state. On CPU the Pallas kernel runs in interpret mode — expect it to
    LOSE there (the comparison proves token-path parity and records the
    shape of the trade); on a TPU host the same code times the compiled
    kernel against the gather's full-window materialization."""
    from dstack_tpu.workloads import serve as serve_lib

    out = {}
    for impl in ("xla", "pallas"):
        eng = serve_lib.ServeEngine(
            cfg,
            serve_lib.EngineConfig(page_size=16, num_pages=96, max_batch=4,
                                   max_seq=160, decode_impl=impl),
            params=params,
        )
        for i in range(4):
            eng.submit([7 + i, 3, 11, 2], max_new_tokens=steps + 8)
        eng.step()  # admit + prefill (+ compile)
        eng.step()  # first pure-decode step (+ decode compile)
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            eng.step()
            times.append(time.perf_counter() - t0)
        times.sort()
        out[impl] = {
            "itl_p50_ms": round(times[len(times) // 2] * 1000, 2),
            "itl_mean_ms": round(sum(times) / len(times) * 1000, 2),
        }
    out["pallas_over_xla"] = round(
        out["pallas"]["itl_p50_ms"] / max(out["xla"]["itl_p50_ms"], 1e-9), 2
    )
    return out


def _routing_schedule(
    n_groups: int = 9, per_group: int = 8, seed: int = 23,
    prefix_len: int = 128,
) -> list:
    """Arrival plan for the fleet-routing bench: 80% of requests belong to
    one of `n_groups` prefix GROUPS (distinct `prefix_len`-token system
    prompts, short unique suffixes, short generations — the prefill-dominated
    regime), every 5th request is fully random. Group members arrive in
    SHUFFLED waves (one request per group per wave, order re-drawn each wave
    — a fixed wave order would hand a modulo cursor accidental per-parity
    group affinity), so a round-robin fleet sends every group to every
    replica. The group set is sized so ALL groups exceed one replica's page
    pool while each replica's affinity share fits — the regime where
    cache-aware routing makes fleet cache capacity additive and round-robin
    LRU-thrashes (see _run_routing_variant's pool geometry)."""
    import random

    rng = random.Random(seed)
    prefixes = [
        [rng.randrange(1, 1024) for _ in range(prefix_len)]
        for _ in range(n_groups)
    ]
    slots = []
    for _wave in range(per_group):
        wave = list(range(n_groups))
        rng.shuffle(wave)
        slots.extend(wave)
    schedule, t = [], 0.0
    for i, g in enumerate(slots):
        t += rng.expovariate(1 / 0.004)
        if i % 5 == 4:  # exactly 20% unshared traffic, deterministically
            prompt = [rng.randrange(1, 1024) for _ in range(rng.randint(16, 32))]
        else:
            prompt = prefixes[g] + [
                rng.randrange(1, 1024) for _ in range(rng.randint(2, 6))
            ]
        schedule.append((t, prompt, rng.randint(2, 4)))
    return schedule


def _run_routing_variant(
    cfg, params, schedule, policy: str, n_replicas: int = 2
) -> dict:
    """Drive the open-loop schedule through N in-process engine replicas with
    the proxy's ACTUAL routing decision code (services/routing.choose) picking
    the replica per request — prefix-affinity vs round-robin differ only in
    that call, exactly as in the server. Queue-depth feedback reaches the
    router the same way production does (the X-Dstack-Queue-Depth value a
    response would carry), so spill behavior is measured, not simulated."""
    from dstack_tpu.server import settings as server_settings
    from dstack_tpu.server.services import routing
    from dstack_tpu.workloads import serve as serve_lib

    # Pool geometry tuned against _routing_schedule: 9 groups x 8 prefix
    # pages = 72 pages of fleet prefix working set vs 64 pages per replica —
    # one replica cannot keep every group resident (round-robin LRU-thrashes),
    # but an affinity share of ~5 groups (40 pages) plus active requests fits.
    pool = dict(page_size=16, num_pages=64, max_batch=4, max_seq=192,
                prefill_chunk=32, prefix_cache=True)
    engines = [
        serve_lib.ServeEngine(cfg, serve_lib.EngineConfig(**pool), params=params)
        for _ in range(n_replicas)
    ]
    for eng in engines:
        warm = eng.submit([1, 2, 3], max_new_tokens=2)
        while not warm.done:
            eng.step()
    endpoints = [("bench-replica", 9000 + i) for i in range(n_replicas)]
    by_ep = dict(zip(endpoints, engines))
    run_id = run_name = "bench-routing"
    routing.state.forget_run(run_id, run_name)
    saved_policy = server_settings.PROXY_ROUTING_POLICY
    server_settings.PROXY_ROUTING_POLICY = (
        "prefix" if policy == "prefix" else "round_robin"
    )
    cursor = 0
    arrivals, token_times, reqs = {}, {}, {}
    try:
        idx = 0
        t0 = time.perf_counter()
        first_arrival = schedule[0][0]
        while idx < len(schedule) or any(e.has_work() for e in engines):
            now = time.perf_counter() - t0
            while idx < len(schedule) and schedule[idx][0] <= now:
                arrival, prompt, max_new = schedule[idx]
                body = json.dumps({"prompt_tokens": prompt}).encode()
                ep = routing.choose(
                    run_id, run_name, endpoints, endpoints,
                    routing.prefix_key(body), cursor,
                )
                cursor += 1
                req = by_ep[ep].submit(prompt, max_new_tokens=max_new)
                arrivals[(ep, req.req_id)] = arrival
                token_times[(ep, req.req_id)] = []
                reqs[(ep, req.req_id)] = req
                idx += 1
            stepped = False
            for ep, eng in zip(endpoints, engines):
                if not eng.has_work():
                    continue
                events = eng.step()
                t_emit = time.perf_counter() - t0
                for ev in events:
                    token_times[(ep, ev.req_id)].append(t_emit)
                routing.state.record_queue_depth(run_id, ep, eng.queue_depth)
                stepped = True
            if not stepped and idx < len(schedule):
                time.sleep(max(0.0, schedule[idx][0] - (time.perf_counter() - t0)))
        t_end = time.perf_counter() - t0
        decisions = routing.state.decisions_for(run_name)
    finally:
        server_settings.PROXY_ROUTING_POLICY = saved_policy
        routing.state.forget_run(run_id, run_name)

    from dstack_tpu.utils.common import nearest_rank

    ttfts = sorted(
        times[0] - arrivals[key] for key, times in token_times.items() if times
    )
    total_tokens = sum(len(t) for t in token_times.values())
    assert all(r.done for r in reqs.values()), "routing bench left requests unfinished"
    # FLEET hit rate from raw counts, not a mean of per-replica ratios — a
    # replica that served two requests must not weigh as much as one that
    # served twenty.
    hits = sum(e.total_prefix_hit_tokens for e in engines)
    lookups = sum(e.total_prefix_lookup_tokens for e in engines)
    n_decisions = max(sum(decisions.values()), 1)
    return {
        "policy": policy,
        "replicas": n_replicas,
        "tokens_per_sec": round(total_tokens / max(t_end - first_arrival, 1e-9), 1),
        "ttft_p50_ms": round(nearest_rank(ttfts, 0.50) * 1000, 1),
        "ttft_p99_ms": round(nearest_rank(ttfts, 0.99) * 1000, 1),
        "prefix_hit_rate": round(hits / max(lookups, 1), 4),
        "requests_per_replica": [
            sum(1 for (ep, _rid) in reqs if ep == e) for e in endpoints
        ],
        "spill_rate": round(
            decisions.get(("prefix", "spilled"), 0) / n_decisions, 4
        ),
        "decisions": {
            f"{pol}/{outcome}": n for (pol, outcome), n in sorted(decisions.items())
        },
    }


def bench_routing() -> dict:
    """`make bench-routing`: fleet-wide prefix-aware routing vs round-robin —
    N in-process replicas (each with its private prefix cache) behind the
    proxy's real routing decision code, an 80%-shared-prefix open-loop mix,
    paired order-flipped rounds. Headline = aggregate fleet tok/s ratio; the
    fleet prefix_hit_rate split shows WHY (affinity keeps each prefix group's
    KV on one replica instead of re-prefilling it everywhere)."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import statistics

    import jax

    from dstack_tpu.workloads import model as model_lib

    cfg = _serve_bench_config()
    params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
    n_replicas = int(os.environ.get("DSTACK_TPU_BENCH_ROUTING_REPLICAS", "2"))
    rounds = int(os.environ.get("DSTACK_TPU_BENCH_ROUTING_ROUNDS", "3"))
    schedule = _routing_schedule()

    # Rehearsal: compile every chunk/decode shape before measurement.
    _run_routing_variant(cfg, params, schedule, "prefix", n_replicas)
    _run_routing_variant(cfg, params, schedule, "round_robin", n_replicas)

    prefix_rounds, rr_rounds, ratios = [], [], []
    for i in range(rounds):
        pair = {}
        order = ("prefix", "round_robin") if i % 2 == 0 else ("round_robin", "prefix")
        for policy in order:
            pair[policy] = _run_routing_variant(
                cfg, params, schedule, policy, n_replicas
            )
        prefix_rounds.append(pair["prefix"])
        rr_rounds.append(pair["round_robin"])
        ratios.append(
            pair["prefix"]["tokens_per_sec"] / pair["round_robin"]["tokens_per_sec"]
        )
    mid = sorted(range(rounds), key=lambda i: ratios[i])[rounds // 2]
    prefix, rr = prefix_rounds[mid], rr_rounds[mid]
    return {
        "metric": "routing_prefix_over_rr_tokens_per_sec",
        "value": round(statistics.median(ratios), 2),
        "unit": "x",
        "vs_baseline": round(statistics.median(ratios), 2),
        "extra": {
            "replicas": n_replicas,
            "rounds": rounds,
            "requests": len(schedule),
            "per_round_ratio": [round(r, 2) for r in ratios],
            "prefix": prefix,
            "round_robin": rr,
            "fleet_hit_rate_prefix": prefix["prefix_hit_rate"],
            "fleet_hit_rate_rr": rr["prefix_hit_rate"],
            "spill_rate": prefix["spill_rate"],
            "ttft_p99_ms_prefix": prefix["ttft_p99_ms"],
            "ttft_p99_ms_rr": rr["ttft_p99_ms"],
        },
    }


def bench_serve() -> dict:
    """`make bench-serve`: the continuous-batching engine under an open-loop
    synthetic load — continuous vs static batching plus a page-size sweep, PR 4
    style (headline = continuous; per-variant numbers in extras). On one CPU
    device this is a scheduling bench, not a model-speed bench; on a TPU host
    the same code measures the chip."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from dstack_tpu.workloads import model as model_lib

    import statistics

    cfg = _serve_bench_config()
    params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
    n = int(os.environ.get("DSTACK_TPU_BENCH_SERVE_REQUESTS", "24"))
    rounds = int(os.environ.get("DSTACK_TPU_BENCH_SERVE_ROUNDS", "3"))
    schedule = _serve_schedule(n)
    pool = dict(page_size=16, num_pages=96, max_batch=4, max_seq=160)

    # Rehearsal runs compile every prefill bucket the schedule touches (the
    # jitted fns are memoized per config, so warmth carries across engines);
    # page-size variants have their own cache shapes and rehearse separately.
    _run_serve_variant(cfg, params, schedule, policy="continuous", **pool)
    _run_serve_variant(cfg, params, schedule, policy="static", **pool)

    # Paired rounds with the order flipped each time (the bench_proxy design):
    # the headline ratio is the median of per-round ratios, so correlated
    # host-load drift cancels inside each pair.
    cont_rounds, static_rounds, ratios = [], [], []
    for i in range(rounds):
        pair = {}
        order = ("continuous", "static") if i % 2 == 0 else ("static", "continuous")
        for policy in order:
            pair[policy] = _run_serve_variant(
                cfg, params, schedule, policy=policy, **pool
            )
        cont_rounds.append(pair["continuous"])
        static_rounds.append(pair["static"])
        ratios.append(
            pair["continuous"]["tokens_per_sec"] / pair["static"]["tokens_per_sec"]
        )

    def _median_round(rs: list) -> dict:
        return sorted(rs, key=lambda r: r["tokens_per_sec"])[len(rs) // 2]

    cont = _median_round(cont_rounds)
    static = _median_round(static_rounds)
    variants = {"continuous": cont, "static": static}
    # Page-size sweep (informational extras): second run is the measured one.
    for name, kw in (
        ("continuous_page4", dict(page_size=4, policy="continuous",
                                  num_pages=384, max_batch=4, max_seq=160)),
        ("continuous_page64", dict(page_size=64, policy="continuous",
                                   num_pages=24, max_batch=4, max_seq=160)),
    ):
        try:
            _run_serve_variant(cfg, params, schedule, **kw)
            variants[name] = _run_serve_variant(cfg, params, schedule, **kw)
        except Exception as e:  # noqa: BLE001
            variants[name] = {"error": f"{type(e).__name__}: {e}"[:200]}

    # Decode-kernel attribution: Pallas paged kernel vs XLA gather per-step
    # latency on identical state (PR 7).
    try:
        decode_itl = _decode_itl_compare(cfg, params)
    except Exception as e:  # noqa: BLE001
        decode_itl = {"error": f"{type(e).__name__}: {e}"[:200]}

    # Tier-2 attribution (PR 9): shared-prefix tok/s with the prefix cache on
    # vs off, injected-long-prompt ITL chunked vs not, and the speculative-
    # decode acceptance rate. Spec divergence is NOT caught into extras — a
    # spec engine that stops being token-identical to greedy must fail the
    # bench run loudly.
    spec_decode = _spec_decode_check(cfg, params)
    # Draft-head vs n-gram on the non-repetitive natural mix: like the
    # repetitive check above, token-identity failures raise — only the
    # accept-rate/step-reduction numbers are data points.
    spec_natural = _draft_vs_ngram_compare(cfg, params)
    try:
        prefix_cache = _prefix_cache_compare(cfg, params)
    except Exception as e:  # noqa: BLE001
        prefix_cache = {"error": f"{type(e).__name__}: {e}"[:200]}

    # Fleet routing attribution (PR 16): cache-aware vs round-robin replica
    # pick over two in-process replicas on the grouped shared-prefix mix.
    # One warm pair here (first pair compiles + warms); `make bench-routing`
    # runs the full paired order-flipped rounds.
    try:
        r_sched = _routing_schedule()
        for policy in ("prefix", "round_robin"):
            _run_routing_variant(cfg, params, r_sched, policy)
        r_prefix = _run_routing_variant(cfg, params, r_sched, "prefix")
        r_rr = _run_routing_variant(cfg, params, r_sched, "round_robin")
        routing_extra = {
            "speedup": round(
                r_prefix["tokens_per_sec"] / max(r_rr["tokens_per_sec"], 1e-9), 2
            ),
            "fleet_hit_rate_prefix": r_prefix["prefix_hit_rate"],
            "fleet_hit_rate_rr": r_rr["prefix_hit_rate"],
            "spill_rate": r_prefix["spill_rate"],
            "ttft_p99_ms_prefix": r_prefix["ttft_p99_ms"],
            "ttft_p99_ms_rr": r_rr["ttft_p99_ms"],
        }
    except Exception as e:  # noqa: BLE001
        routing_extra = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:
        long_prompt_itl = _long_prompt_itl_compare(cfg, params)
    except Exception as e:  # noqa: BLE001
        long_prompt_itl = {"error": f"{type(e).__name__}: {e}"[:200]}

    n_dev = max(jax.device_count(), 1)
    return {
        "metric": "serve_tokens_per_sec_per_chip",
        "value": round(cont["tokens_per_sec"] / n_dev, 1),
        "unit": "tok/s/chip",
        # Baseline = static batching on the same mixed-length schedule: the
        # continuous engine's whole reason to exist is beating this.
        "vs_baseline": round(statistics.median(ratios), 2),
        "extra": {
            "requests": n,
            "rounds": rounds,
            "devices": n_dev,
            "ttft_p50_ms": cont["ttft_p50_ms"],
            "ttft_p99_ms": cont["ttft_p99_ms"],
            "itl_p50_ms": cont["itl_p50_ms"],
            "itl_p99_ms": cont["itl_p99_ms"],
            # Stage attribution (ISSUE 18): where request wall time went in
            # the median continuous round — the measurement substrate for the
            # routing A/B ("did the TTFT tail move because queueing shrank,
            # or because prefill got cheaper?").
            "stage_breakdown": {
                "queue_wait_p50_ms": cont["queue_wait_p50_ms"],
                "queue_wait_p99_ms": cont["queue_wait_p99_ms"],
                "prefill_p50_ms": cont["prefill_p50_ms"],
                "prefill_p99_ms": cont["prefill_p99_ms"],
                "decode_p50_ms": cont["decode_p50_ms"],
                "decode_p99_ms": cont["decode_p99_ms"],
            },
            "per_round_ratio": [round(r, 2) for r in ratios],
            "decode_itl": decode_itl,
            "prefix_hit_rate": prefix_cache.get("prefix_hit_rate", 0.0),
            "spec_accept_rate": spec_decode["spec_accept_rate"],
            "routing": routing_extra,
            "prefix_cache": prefix_cache,
            "long_prompt_itl": long_prompt_itl,
            "spec_decode": spec_decode,
            "spec_natural_mix": spec_natural,
            "variants": variants,
        },
    }


def bench_kernels() -> dict:
    """`make bench-kernels`: every in-repo Pallas kernel + quantized matmul +
    the collective-matmul ring, end to end in CPU interpret mode — one JSON
    line with per-kernel wall time and max error vs the XLA reference. Not a
    speed bench (interpret mode measures correctness, not the chip); its job
    is to prove the exact kernel code paths the TPU runs are importable,
    traceable, and numerically tight, one command before a TPU submit."""
    import os

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp

    from dstack_tpu.workloads import quantize as quant_lib
    from dstack_tpu.workloads.attention import (
        blockwise_attention,
        paged_chunk_attention,
        paged_decode_attention,
    )
    from dstack_tpu.workloads.kernels import (
        collective_matmul,
        flash_attention,
        paged_chunk_attention_pallas,
        paged_decode_attention_pallas,
    )
    from dstack_tpu.workloads.sharding import make_mesh

    results = {}
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)

    # -- flash fwd + bwd vs blockwise --------------------------------------
    q = jax.random.normal(ks[0], (2, 128, 4, 32))
    k = jax.random.normal(ks[1], (2, 128, 2, 32))
    v = jax.random.normal(ks[2], (2, 128, 2, 32))
    t0 = time.perf_counter()
    out = flash_attention(q, k, v, causal=True)
    ref = blockwise_attention(q, k, v, causal=True)
    fwd_err = float(jnp.max(jnp.abs(out - ref)))

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v, causal=True)))

    gk = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(blockwise_attention), argnums=(0, 1, 2))(q, k, v)
    bwd_err = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(gk, gr))
    results["flash"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "fwd_max_err": fwd_err,
        "bwd_max_err": bwd_err,
    }

    # -- paged decode kernel vs XLA gather ---------------------------------
    qd = jax.random.normal(ks[3], (4, 4, 32))
    kp = jax.random.normal(ks[4], (24, 8, 2, 32))
    vp = jax.random.normal(ks[5], (24, 8, 2, 32))
    pt = jax.random.randint(ks[6], (4, 8), 0, 24)
    lens = jnp.array([3, 17, 40, 64], jnp.int32)
    t0 = time.perf_counter()
    pk = paged_decode_attention_pallas(qd, kp, vp, pt, lens)
    px = paged_decode_attention(qd, kp, vp, pt, lens)
    results["paged_decode"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "max_err": float(jnp.max(jnp.abs(pk - px))),
    }

    # -- paged chunk kernel (chunked prefill / spec verify) vs XLA ---------
    qc = jax.random.normal(ks[7], (4, 4, 4, 32))
    starts = jnp.array([0, 5, 17, 40], jnp.int32)
    cvalid = jnp.array([4, 4, 2, 4], jnp.int32)
    t0 = time.perf_counter()
    ck = paged_chunk_attention_pallas(qc, kp, vp, pt, starts, starts + cvalid)
    cx = paged_chunk_attention(qc, kp, vp, pt, starts)
    # Compare only each slot's valid queries: the Pallas kernel additionally
    # clamps to kv_len, which pad queries (discarded by the engine) exceed.
    cerr_chunk = max(
        float(jnp.max(jnp.abs(ck[s, :int(cvalid[s])] - cx[s, :int(cvalid[s])])))
        for s in range(4)
    )
    results["paged_chunk"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "max_err": cerr_chunk,
    }

    # -- int8 matmul error bound -------------------------------------------
    x = jax.random.normal(ks[0], (64, 256))
    w = jax.random.normal(ks[1], (256, 128))
    t0 = time.perf_counter()
    yq = quant_lib.int8_matmul(x, w)
    yr = x @ w
    rel = float(
        jnp.linalg.norm(yq - yr) / jnp.maximum(jnp.linalg.norm(yr), 1e-9)
    )
    results["int8_matmul"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "rel_err": round(rel, 5),
    }

    # -- splash fwd + bwd vs masked reference (window + dense causal) ------
    from dstack_tpu.workloads.kernels import splash_attention
    from dstack_tpu.workloads.kernels.splash import splash_reference

    t0 = time.perf_counter()
    sp_fwd_err = 0.0
    sp_bwd_err = 0.0
    for window in (0, 48):
        so = splash_attention(q, k, v, causal=True, window=window)
        sr = splash_reference(q, k, v, causal=True, window=window)
        sp_fwd_err = max(sp_fwd_err, float(jnp.max(jnp.abs(so - sr))))

        def sloss(fn, w=window):
            return lambda q, k, v: jnp.sum(
                jnp.sin(fn(q, k, v, causal=True, window=w))
            )

        gs = jax.grad(sloss(splash_attention), argnums=(0, 1, 2))(q, k, v)
        gm = jax.grad(sloss(splash_reference), argnums=(0, 1, 2))(q, k, v)
        sp_bwd_err = max(
            sp_bwd_err,
            max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(gs, gm)),
        )
    results["splash"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "fwd_max_err": sp_fwd_err,
        "bwd_max_err": sp_bwd_err,
    }

    # -- fp8 matmul error bound --------------------------------------------
    t0 = time.perf_counter()
    yf8 = quant_lib.fp8_matmul(x, w)
    fp8_rel = float(
        jnp.linalg.norm(yf8 - yr) / jnp.maximum(jnp.linalg.norm(yr), 1e-9)
    )
    results["fp8_matmul"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "rel_err": round(fp8_rel, 5),
    }

    # -- collective matmul == all-reduce matmul on an 8-device mesh --------
    mesh = make_mesh(dp=1, fsdp=2, tp=4, sp=1)
    xb = jax.random.normal(ks[2], (8, 16, 64))
    wb = jax.random.normal(ks[3], (64, 32))
    t0 = time.perf_counter()
    with mesh:
        yc = jax.jit(lambda a, b: collective_matmul(a, b, mesh))(xb, wb)
    cerr = float(jnp.max(jnp.abs(yc - jnp.einsum("btk,kn->btn", xb, wb))))
    results["collective_matmul"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "max_err": cerr,
    }

    # -- FSDP allgather matmul == gathered matmul on the same mesh ---------
    from dstack_tpu.workloads.kernels import allgather_matmul

    t0 = time.perf_counter()
    with mesh:
        ya = jax.jit(lambda a, b: allgather_matmul(a, b, mesh))(xb, wb)
    aerr = float(jnp.max(jnp.abs(ya - jnp.einsum("btk,kn->btn", xb, wb))))
    results["allgather_matmul"] = {
        "wall_s": round(time.perf_counter() - t0, 2),
        "max_err": aerr,
    }

    worst = max(
        results["flash"]["fwd_max_err"],
        results["flash"]["bwd_max_err"],
        results["splash"]["fwd_max_err"],
        results["splash"]["bwd_max_err"],
        results["paged_decode"]["max_err"],
        results["paged_chunk"]["max_err"],
        results["collective_matmul"]["max_err"],
        results["allgather_matmul"]["max_err"],
    )
    # int8/fp8 are lossy by design — gauged against their own rounding-noise
    # bounds on gaussian operands (~1% for int8's 256 levels; fp8-e4m3 keeps
    # only a 3-bit mantissa, so ~4-5% after the dual per-channel quant)
    # rather than the exact-kernel 1e-4 floor.
    int8_rel = results["int8_matmul"]["rel_err"]
    fp8_rel = results["fp8_matmul"]["rel_err"]
    if worst > 1e-4 or int8_rel > 0.05 or fp8_rel > 0.1:
        raise RuntimeError(
            f"kernel smoke out of bounds (exact>{1e-4}, int8_rel>0.05, or "
            f"fp8_rel>0.1): {results}"
        )
    return {
        "metric": "kernel_smoke_max_err",
        "value": worst,
        "unit": "abs_err",
        # A returned record always passed the floor; failure raises above.
        "vs_baseline": 1.0,
        "extra": results,
    }


def smoke_draft() -> dict:
    """`make smoke-draft`: the draft-head distillation loop end to end on
    CPU, 30 steps — the loss must actually DROP (the loop fits the frozen
    target's argmax, not noise) and the trained head must satisfy the
    proposer contract the serve engine builds rows from ([S, k] int32). The
    fast pre-submit gate for train.py --draft-head / model.py draft changes."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp

    from dstack_tpu.workloads import model as model_lib
    from dstack_tpu.workloads import serve as serve_lib

    cfg = _serve_bench_config()
    params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
    t0 = time.perf_counter()
    draft, info = _distill_draft_head(cfg, params, steps=30)
    wall = time.perf_counter() - t0
    assert info["loss_last"] < info["loss_first"] * 0.5, (
        f"distill loss never converged: {info}"
    )
    fn = serve_lib.make_draft_fn(cfg, 4)
    probe = fn(params, draft, jnp.zeros((2, cfg.d_model), jnp.float32),
               jnp.asarray([5, 7], jnp.int32))
    assert probe.shape == (2, 4), probe.shape
    assert probe.dtype == jnp.int32, probe.dtype
    result = {
        "metric": "smoke_draft",
        "value": info["loss_last"],
        "unit": "distill_loss",
        "wall_s": round(wall, 1),
        **info,
    }
    print(json.dumps(result))
    return result


def smoke_serve() -> dict:
    """`make smoke-serve`: boot the server in-process, stand up a REAL serving
    engine as a replica, stream tokens through the proxy's SSE pass-through,
    then close the autoscaler loop — injected p90 latency scales a fake
    service up (run_events shows the autoscaler actor + the cold-start
    histogram fills), an idle window scales it back to zero. Raises on any
    missing piece."""
    import asyncio
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import aiohttp
    from aiohttp import web as aioweb

    from dstack_tpu.core import tracing
    from dstack_tpu.server.background import tasks
    from dstack_tpu.server.services import proxy as proxy_service
    from dstack_tpu.workloads import model as model_lib
    from dstack_tpu.workloads import serve as serve_lib
    from tests.common import FakeRunnerClient, api_server, drive, setup_mock_backend

    tracing.reset()
    proxy_service.stats.reset()

    async def run() -> dict:
        import jax

        cfg = _serve_bench_config()
        # The smoke engine speculates with the MODEL-BASED draft head (a
        # random-init one: correctness and the gauge plumbing are what a
        # smoke proves; accept-rate QUALITY is bench_serve's job) — every
        # request below therefore drives the draft proposer + hidden-state
        # plumbing through the proxy end to end.
        draft_params = model_lib.init_draft_params(cfg, jax.random.PRNGKey(3))
        engine = serve_lib.ServeEngine(
            cfg,
            serve_lib.EngineConfig(page_size=8, num_pages=64, max_batch=4,
                                   max_seq=128, prefix_cache=True,
                                   prefill_chunk=16, spec_tokens=2),
            params=model_lib.init_params(cfg, jax.random.PRNGKey(0)),
            draft_params=draft_params,
        )
        runner = serve_lib.EngineRunner(engine, idle_wait=0.01)
        runner.start()
        app_runner = aioweb.AppRunner(serve_lib.create_serve_app(runner))
        await app_runner.setup()
        site = aioweb.TCPSite(app_runner, "127.0.0.1", 0)
        await site.start()
        engine_port = site._server.sockets[0].getsockname()[1]

        FakeRunnerClient.reset()
        tasks.get_runner_client = FakeRunnerClient.for_jpd
        # Service replicas must STAY running (the stock script finishes jobs,
        # which is right for tasks and wrong for services).
        saved_script = FakeRunnerClient.default_script
        FakeRunnerClient.default_script = lambda self: [
            {"job_states": [{"state": "running"}], "logs": [], "offset": 1}
        ]
        try:
            async with api_server() as api:
                # --- tokens stream through the proxy, unbuffered ---------
                await _seed_bench_service(api.db, "smoke-serve", engine_port)
                url = (
                    f"http://127.0.0.1:{api.client.server.port}"
                    "/proxy/services/main/smoke-serve/generate"
                )
                events = []
                async with aiohttp.ClientSession() as session:
                    async with session.post(
                        url,
                        json={"prompt": "hello tpu", "max_tokens": 8,
                              "stream": True},
                    ) as resp:
                        assert resp.status == 200, await resp.text()
                        assert resp.headers["Content-Type"].startswith(
                            "text/event-stream"
                        )
                        async for line in resp.content:
                            if line.startswith(b"data: "):
                                events.append(line[6:].strip())
                assert events[-1] == b"[DONE]" and len(events) == 9, events
                # The first-chunk hook recorded TTFT + engine queue depth.
                q = proxy_service.stats.latency_quantiles("run-smoke-serve")
                assert q and q["count"] >= 1, q
                assert proxy_service.stats.queue_depth("run-smoke-serve") is not None

                # --- tier-2: shared-prefix + speculative through the proxy
                # Two requests sharing a >1-block prompt prefix: the second
                # must hit the prefix cache, and both decode speculatively
                # (the engine above runs prefix_cache + spec_tokens=2).
                shared = [((7 * i) % 200) + 1 for i in range(20)]
                async with aiohttp.ClientSession() as session:
                    for suffix in ([3, 5], [9, 11]):
                        async with session.post(
                            url,
                            json={"prompt_tokens": shared + suffix,
                                  "max_tokens": 6, "stream": False},
                        ) as resp:
                            assert resp.status == 200, await resp.text()
                            body = await resp.json()
                            assert len(body["tokens"]) == 6
                assert engine.prefix_hit_rate > 0, (
                    "second shared-prefix request never hit the cache: "
                    f"{engine.stats()}"
                )
                gauges = proxy_service.stats.engine_gauges("run-smoke-serve")
                assert "prefix_cache_hit_ratio" in gauges, gauges
                assert "spec_accept_ratio" in gauges, gauges
                assert gauges["prefix_cache_hit_ratio"] > 0, gauges
                # ...and they render on the server's /metrics exposition.
                resp = await api.client.get("/metrics")
                metrics_text = await resp.text()
                for family in (
                    "dstack_tpu_service_prefix_cache_hit_ratio",
                    "dstack_tpu_service_spec_accept_ratio",
                ):
                    assert f'{family}{{run="smoke-serve"}}' in metrics_text, (
                        f"{family} has no sample for smoke-serve"
                    )
                # Draft proposer output contract — the shape/dtype the
                # engine builds verify rows from, checked on the exact jitted
                # fn the engine dispatches (make_draft_fn is memoized per
                # (cfg, k, quant, mesh), so this IS the engine's instance).
                import jax.numpy as jnp

                dfn = serve_lib.make_draft_fn(cfg, engine.ecfg.spec_tokens)
                probe = dfn(
                    engine._serve_params, engine.draft_params,
                    jnp.zeros((3, cfg.d_model), jnp.float32),
                    jnp.asarray([1, 2, 3], jnp.int32),
                )
                assert probe.shape == (3, engine.ecfg.spec_tokens), probe.shape
                assert probe.dtype == jnp.int32, probe.dtype
                stats_now = engine.stats()
                assert stats_now["spec_proposer"] == "draft", stats_now
                assert "spec_accept_rate_windowed" in stats_now, stats_now
                tier2 = {
                    "prefix_hit_rate": round(engine.prefix_hit_rate, 4),
                    "spec_accept_rate": round(engine.spec_accept_rate, 4),
                    "spec_proposer": stats_now["spec_proposer"],
                }

                # --- fleet: two tp=2-SHARDED replicas + cache-aware routing
                # Two ServeEngines, each tensor-parallel over a DISJOINT pair
                # of the 8 fake CPU devices, serve the same weights behind
                # the real proxy. The same shared-prefix traffic runs twice —
                # round_robin, then prefix — against fresh replicas each
                # time: affinity pins every prefix group to one replica (one
                # cold fill per group fleet-wide), rr cold-fills both, so the
                # prefix pass must win on aggregate fleet hit rate. Routing
                # decision counters must render on /metrics.
                import random as _random

                from dstack_tpu.server import settings as server_settings
                from dstack_tpu.server.services import routing as routing_service
                from dstack_tpu.workloads import sharding as sharding_lib

                devices = jax.devices()
                assert len(devices) >= 4, (
                    "smoke-serve needs XLA_FLAGS=--xla_force_host_platform_"
                    f"device_count=8 (got {len(devices)} devices)"
                )
                host_params = engine.params  # same weights on every replica
                meshes = [
                    sharding_lib.make_serve_mesh(2, devices=devices[0:2]),
                    sharding_lib.make_serve_mesh(2, devices=devices[2:4]),
                ]

                async def _sharded_replica(mesh):
                    eng = serve_lib.ServeEngine(
                        cfg,
                        serve_lib.EngineConfig(page_size=8, num_pages=64,
                                               max_batch=4, max_seq=128,
                                               prefix_cache=True,
                                               prefill_chunk=16),
                        params=host_params,
                        mesh=mesh,
                    )
                    # Genuinely sharded, not replicated: each projection leaf
                    # is split across the pair, the KV pages over heads.
                    assert dict(mesh.shape) == {"dd": 1, "tp": 2}
                    assert len(eng.k_pages.sharding.device_set) == 2
                    rnr = serve_lib.EngineRunner(eng, idle_wait=0.01)
                    rnr.start()
                    arun = aioweb.AppRunner(serve_lib.create_serve_app(rnr))
                    await arun.setup()
                    fsite = aioweb.TCPSite(arun, "127.0.0.1", 0)
                    await fsite.start()
                    return eng, rnr, arun, fsite._server.sockets[0].getsockname()[1]

                # 5 prefix groups x 9 full pages: longer than the router's
                # 64-token prefix key window, so every request in a group
                # hashes identically; 2-token unique suffixes + short
                # generations keep the run prefill-dominated. Waves are
                # shuffled so rr's cursor parity can't accidentally give it
                # perfect affinity (the bench_routing lesson).
                rng = _random.Random(5)
                prefixes = [
                    [((11 * g + 3 * i) % 500) + 1 for i in range(72)]
                    for g in range(5)
                ]
                order = []
                for _ in range(4):
                    wave = list(range(5))
                    rng.shuffle(wave)
                    order.extend(wave)

                async def _drive_fleet(run_name, port_a, port_b):
                    await _seed_bench_service(api.db, run_name, port_a, port_b)
                    furl = (
                        f"http://127.0.0.1:{api.client.server.port}"
                        f"/proxy/services/main/{run_name}/generate"
                    )
                    async with aiohttp.ClientSession() as session:
                        for i, g in enumerate(order):
                            prompt = prefixes[g] + [600 + 2 * i, 601 + 2 * i]
                            async with session.post(
                                furl,
                                json={"prompt_tokens": prompt,
                                      "max_tokens": 4, "stream": False},
                            ) as resp:
                                assert resp.status == 200, await resp.text()
                                body = await resp.json()
                                assert len(body["tokens"]) == 4

                def _fleet_hit(engs):
                    hits = sum(e.total_prefix_hit_tokens for e in engs)
                    looks = sum(e.total_prefix_lookup_tokens for e in engs)
                    return hits / max(1, looks)

                saved_policy = server_settings.PROXY_ROUTING_POLICY
                fleet_rates = {}
                try:
                    for policy, fname in (("round_robin", "smoke-fleet-rr"),
                                          ("prefix", "smoke-fleet")):
                        replicas = [await _sharded_replica(m) for m in meshes]
                        server_settings.PROXY_ROUTING_POLICY = policy
                        try:
                            await _drive_fleet(
                                fname, replicas[0][3], replicas[1][3]
                            )
                        finally:
                            for _, rnr, arun, _p in replicas:
                                rnr.shutdown()
                                await arun.cleanup()
                        engs = [r[0] for r in replicas]
                        fleet_rates[policy] = _fleet_hit(engs)
                        if policy == "prefix":
                            # Affinity spread real work across BOTH shards.
                            assert all(
                                e.total_prefix_lookup_tokens > 0 for e in engs
                            ), [e.stats() for e in engs]
                finally:
                    server_settings.PROXY_ROUTING_POLICY = saved_policy
                assert fleet_rates["prefix"] > fleet_rates["round_robin"], (
                    "cache-aware routing never beat round-robin: "
                    f"{fleet_rates}"
                )
                dec = routing_service.state.decisions()
                assert dec.get(("smoke-fleet", "prefix", "preferred"), 0) > 0, dec
                resp = await api.client.get("/metrics")
                routing_text = await resp.text()
                routed = [
                    ln for ln in routing_text.splitlines()
                    if ln.startswith("dstack_tpu_proxy_routing_decisions_total{")
                    and 'run="smoke-fleet"' in ln
                    and 'policy="prefix"' in ln
                    and 'outcome="preferred"' in ln
                ]
                assert routed, (
                    "routing decision counter missing from /metrics"
                )
                fleet = {
                    "hit_rate_prefix": round(fleet_rates["prefix"], 4),
                    "hit_rate_rr": round(fleet_rates["round_robin"], 4),
                }

                # --- the autoscaler control loop -------------------------
                await setup_mock_backend(api)
                await api.post(
                    "/api/project/main/runs/submit",
                    {"run_spec": {
                        "run_name": "scaled-svc",
                        "configuration": {
                            "type": "service",
                            "commands": ["python -m dstack_tpu.workloads.serve"],
                            "port": 8000,
                            "auth": False,
                            "replicas": "0..2",
                            "resources": {"tpu": "v5e-8"},
                            "scaling": {
                                "metric": "latency", "target": 0.2,
                                "queue_depth_target": 2,
                                "scale_up_delay": 0, "scale_down_delay": 0,
                            },
                        },
                    }},
                )
                row = await api.db.fetchone(
                    "SELECT * FROM runs WHERE run_name = 'scaled-svc'"
                )
                # Inject demand with a sick p90: the loop must scale 0 -> 1.
                for _ in range(30):
                    proxy_service.stats.record(row["id"])
                    proxy_service.stats.record_latency(row["id"], 0.8)
                proxy_service.stats.record_queue_depth(row["id"], 7)
                await tasks.process_autoscaler(api.db)
                await drive(api.db)
                jobs = await api.db.fetchall(
                    "SELECT * FROM jobs WHERE run_id = ? AND status = 'running'",
                    (row["id"],),
                )
                assert jobs, "autoscaler never scaled the service from zero"

                data = await api.post(
                    "/api/project/main/runs/get_events",
                    {"run_name": "scaled-svc"},
                )
                auto = [e for e in data["events"] if e["actor"] == "autoscaler"]
                assert auto and auto[0]["reason"] == "scale_from_zero", auto
                snap = tracing.histogram_snapshot(
                    "dstack_tpu_service_cold_start_seconds"
                )
                assert snap is not None, "cold-start histogram never observed"
                cold = _histogram_summaries(
                    "dstack_tpu_service_cold_start_seconds", "from_zero"
                )

                # Demand evaporates: back to zero (min replicas = 0).
                proxy_service.stats.reset()
                await tasks.process_autoscaler(api.db)
                await drive(api.db)
                left = await api.db.fetchall(
                    "SELECT * FROM jobs WHERE run_id = ? AND status = 'running'",
                    (row["id"],),
                )
                assert not left, "autoscaler never scaled back to zero"
                run = await api.post(
                    "/api/project/main/runs/get", {"run_name": "scaled-svc"}
                )
                assert run["status"] == "running", run["status"]  # alive at 0

                return {
                    "metric": "smoke_serve",
                    "value": len(events) - 1,
                    "unit": "sse_tokens",
                    "ttft_ms": round(q["p50"] * 1000, 1),
                    "cold_start": cold,
                    "fleet": fleet,
                    **tier2,
                }
        finally:
            FakeRunnerClient.default_script = saved_script
            runner.shutdown()
            await app_runner.cleanup()
            proxy_service.stats.reset()
            proxy_service.route_table.clear()
            from dstack_tpu.server.services import routing as _routing
            _routing.state.reset()

    result = asyncio.run(run())
    print(json.dumps(result))
    return result


def bench_chaos() -> dict:
    """`make bench-chaos`: control-plane fault tolerance under an injected
    fault schedule. N runs are driven by TWO scheduler replicas (distinct
    lease identities sharing one DB — the multi-replica deployment shape,
    conservatively sharing one in-process locker; the DB-level lease/claim
    transactions are the guard under test) while a fraction of runner calls
    drop and backend create_slice calls 5xx; replica A is then KILLED
    mid-run (its task cancelled between awaits, exactly like a process
    crash). FAILS unless: 100%% of runs reach `done`, no slice is ever
    double-booked across the replicas, and every run orphaned by the kill is
    reclaimed + reconciled. Reports recovery-time p50/p90 (kill ->
    `reconciled` run_event) through the run_events machinery."""
    import asyncio

    from dstack_tpu.core import faults, tracing
    from dstack_tpu.server import settings
    from dstack_tpu.server.background import tasks
    from dstack_tpu.server.services import leases, resilience
    from dstack_tpu.utils.common import from_iso, now_utc
    from tests.common import FakeRunnerClient, api_server, setup_mock_backend, tpu_task_spec

    N = 24
    tracing.reset()
    resilience.reset()
    saved = (
        settings.LEASE_TTL, settings.RETRY_BACKOFF_BASE,
        settings.BREAKER_COOLDOWN, settings.BREAKER_THRESHOLD,
    )
    settings.LEASE_TTL = 1.5
    settings.RETRY_BACKOFF_BASE = 0.1
    settings.BREAKER_COOLDOWN = 0.5
    settings.BREAKER_THRESHOLD = 4

    class ChaosRunnerClient(FakeRunnerClient):
        """The scripted agent with the chaos schedule's drop faults applied:
        a dropped healthcheck reads as unreachable, a dropped pull exercises
        the disconnect grace path."""

        async def healthcheck(self):
            try:
                await faults.check("runner.request", detail=f"{self.key}/healthcheck")
            except faults.FaultInjected:
                return None
            return await super().healthcheck()

        async def pull(self, offset: int = 0):
            await faults.check("runner.request", detail=f"{self.key}/pull")
            return await super().pull(offset)

        def default_script(self):
            # Jobs stay RUNNING across ~40 pulls before finishing, so the
            # replica kill lands while real work is in flight (a 2-pull script
            # would complete every run before the chaos even starts).
            running = {"job_states": [{"state": "running"}], "logs": [], "offset": 1}
            return [running] * 40 + [
                {"job_states": [{"state": "done", "exit_status": 0}], "logs": [], "offset": 2}
            ]

    faults.configure(
        {
            "seed": 7,
            "sites": {
                "runner.request": {"fail": 0.15, "error": "injected agent drop"},
                "backend.create_slice": {
                    "fail": 0.35, "times": 12, "error": "injected backend 5xx",
                },
            },
        }
    )

    async def run() -> dict:
        ChaosRunnerClient.reset()
        tasks.get_runner_client = ChaosRunnerClient.for_jpd
        double_booked: list = []
        async with api_server() as api:
            await setup_mock_backend(api)
            for i in range(N):
                await api.post(
                    "/api/project/main/runs/submit",
                    tpu_task_spec(
                        f"chaos-{i}", "v5e-8",
                        retry={"on_events": ["no-capacity"], "duration": "1h"},
                    ),
                )

            async def check_double_booking() -> None:
                rows = await api.db.fetchall(
                    "SELECT instance_id, COUNT(*) AS n FROM jobs"
                    " WHERE instance_id IS NOT NULL"
                    " AND status IN ('provisioning', 'pulling', 'running')"
                    " GROUP BY instance_id HAVING COUNT(*) > 1"
                )
                double_booked.extend((r["instance_id"], r["n"]) for r in rows)

            async def replica(rid: str) -> None:
                with leases.as_replica(rid):
                    while True:
                        # Small submitted batch: placement claims interleave, so
                        # ownership genuinely partitions across the replicas.
                        await tasks.process_submitted_jobs(api.db, batch=8)
                        await tasks.process_running_jobs(api.db, batch=50)
                        await tasks.process_terminating_jobs(api.db, batch=50)
                        await tasks.process_runs(api.db, batch=50)
                        await check_double_booking()
                        await asyncio.sleep(0.05)

            task_a = asyncio.create_task(replica("chaos-a"))
            task_b = asyncio.create_task(replica("chaos-b"))
            await asyncio.sleep(2.0)  # both replicas mid-schedule
            partition = {
                r["owner"]: r["n"]
                for r in await api.db.fetchall(
                    "SELECT owner, COUNT(*) AS n FROM run_leases GROUP BY owner"
                )
            }

            # KILL replica A: a hard cancel between awaits is a process crash
            # as far as the DB is concerned (every transition is transactional).
            task_a.cancel()
            try:
                await task_a
            except asyncio.CancelledError:
                pass
            t_kill = now_utc()
            orphan_rows = await api.db.fetchall(
                "SELECT l.run_id FROM run_leases l JOIN runs r ON r.id = l.run_id"
                " WHERE l.owner = 'chaos-a'"
                " AND r.status NOT IN ('terminated', 'failed', 'done')"
            )
            orphans = {r["run_id"] for r in orphan_rows}
            # An empty orphan set means the schedule is mistuned (everything
            # finished before the kill) and the bench would prove nothing.
            assert orphans, "replica kill orphaned no runs; chaos schedule mistuned"

            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                done = await api.db.fetchone(
                    "SELECT COUNT(*) AS n FROM runs WHERE status = 'done'"
                )
                if done["n"] >= N:
                    break
                await asyncio.sleep(0.2)
            task_b.cancel()
            try:
                await task_b
            except asyncio.CancelledError:
                pass

            statuses = await api.db.fetchall("SELECT run_name, status FROM runs")
            not_done = [(r["run_name"], r["status"]) for r in statuses if r["status"] != "done"]
            assert not not_done, f"runs did not recover: {not_done}"
            assert not double_booked, f"double-booked slices: {double_booked}"

            # Every orphaned run was reclaimed + reconciled; recovery time is
            # kill -> its reconciled event, straight from the timeline.
            recoveries = []
            for run_id in orphans:
                evs = await api.db.fetchall(
                    "SELECT * FROM run_events WHERE run_id = ?"
                    " AND new_status = 'reconciled' ORDER BY seq",
                    (run_id,),
                )
                assert evs, f"orphaned run {run_id} was never reconciled"
                recoveries.append(
                    (from_iso(evs[0]["timestamp"]) - t_kill).total_seconds()
                )
            recoveries.sort()
            from dstack_tpu.utils.common import nearest_rank

            p50 = nearest_rank(recoveries, 0.50) if recoveries else None
            p90 = nearest_rank(recoveries, 0.90) if recoveries else None
            return {
                "metric": "chaos_recovery_p90_s",
                "value": round(p90, 2) if p90 is not None else 0.0,
                "unit": "s",
                "vs_baseline": 1.0,
                "extra": {
                    "runs": N,
                    "completed_pct": 100.0,
                    "lease_partition_at_kill": partition,
                    "orphaned_by_kill": len(orphans),
                    "recovery_p50_s": round(p50, 2) if p50 is not None else None,
                    "recovery_p90_s": round(p90, 2) if p90 is not None else None,
                    "double_booked": 0,
                    "faults_injected": faults.stats(),
                    "lease_ttl_s": settings.LEASE_TTL,
                },
            }

    try:
        result = asyncio.run(run())
    finally:
        (
            settings.LEASE_TTL, settings.RETRY_BACKOFF_BASE,
            settings.BREAKER_COOLDOWN, settings.BREAKER_THRESHOLD,
        ) = saved
        faults.clear()
        resilience.reset()
        FakeRunnerClient.reset()
    return result


def smoke_chaos() -> dict:
    """`make smoke-chaos`: lease reclaim proven through the REAL server + the
    native agent. A run executes an actual process via the local backend;
    scheduler replica A drives it to RUNNING and then dies (its passes simply
    stop — a crashed process renews nothing). Replica B must reclaim the
    expired lease, reconcile (probing the live agent), and carry the SAME
    workload process to `done` — the workload never restarts. Non-zero exit
    on any missing piece."""
    import asyncio

    from dstack_tpu.core import tracing
    from dstack_tpu.server import settings
    from dstack_tpu.server.background import tasks
    from dstack_tpu.server.services import leases
    from tests.common import api_server

    tracing.reset()
    saved_ttl = settings.LEASE_TTL
    settings.LEASE_TTL = 2.0

    async def run() -> dict:
        async with api_server() as api:
            spec = {
                "run_spec": {
                    "run_name": "smoke-chaos",
                    "configuration": {
                        "type": "task",
                        "commands": ["python3 -c 'import time; time.sleep(15)'"],
                    },
                }
            }
            await api.post("/api/project/main/runs/submit", spec)

            async def passes() -> None:
                await tasks.process_submitted_jobs(api.db)
                await tasks.process_running_jobs(api.db)
                await tasks.process_terminating_jobs(api.db)
                await tasks.process_runs(api.db)
                await tasks.process_instances(api.db)

            async def owner() -> str:
                row = await api.db.fetchone(
                    "SELECT l.owner FROM run_leases l JOIN runs r ON r.id = l.run_id"
                    " WHERE r.run_name = 'smoke-chaos'"
                )
                return row["owner"] if row else ""

            # Replica A: drive the run onto the real agent, then die.
            async def drive_a() -> None:
                with leases.as_replica("smoke-a"):
                    while True:
                        await passes()
                        run = await api.post(
                            "/api/project/main/runs/get", {"run_name": "smoke-chaos"}
                        )
                        if run["status"] == "running":
                            return
                        await asyncio.sleep(0.2)

            await asyncio.wait_for(drive_a(), timeout=180)
            assert await owner() == "smoke-a", await owner()
            t_kill = time.monotonic()

            # Replica B: reclaim after the TTL and finish the run.
            reclaimed_at = None
            status = None
            with leases.as_replica("smoke-b"):
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    await passes()
                    if reclaimed_at is None and await owner() == "smoke-b":
                        reclaimed_at = time.monotonic()
                    run = await api.post(
                        "/api/project/main/runs/get", {"run_name": "smoke-chaos"}
                    )
                    status = run["status"]
                    if status in ("done", "failed", "terminated"):
                        break
                    await asyncio.sleep(0.2)
            assert status == "done", f"rescued run ended {status}"
            assert reclaimed_at is not None, "replica B never took the lease"
            reclaim_s = reclaimed_at - t_kill

            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "smoke-chaos"}
            )
            recon = [e for e in data["events"] if e["new_status"] == "reconciled"]
            assert recon, "no reconciled event in the timeline"
            assert recon[0]["reason"] == "lease_reclaimed", recon[0]
            assert "smoke-b" in recon[0]["message"], recon[0]
            assert "1 reachable" in recon[0]["message"], recon[0]

            # The SAME submission finished — reclaim adopted, it didn't restart.
            subs = await api.db.fetchall(
                "SELECT DISTINCT submission_num FROM jobs WHERE run_name = 'smoke-chaos'"
            )
            assert [s["submission_num"] for s in subs] == [0], subs

            resp = await api.client.get("/metrics")
            text = await resp.text()
            assert "# TYPE dstack_tpu_run_leases gauge" in text
            assert "# TYPE dstack_tpu_circuit_breaker_state gauge" in text
            return {
                "metric": "smoke_chaos",
                "value": round(reclaim_s, 2),
                "unit": "s lease reclaim (kill -> new owner)",
                "reconciled_reason": recon[0]["reason"],
                "final_status": status,
            }

    try:
        result = asyncio.run(run())
    finally:
        settings.LEASE_TTL = saved_ttl
    print(json.dumps(result))
    return result


def main() -> None:
    try:
        import jax

        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:
        on_tpu = False
    result = bench_tpu_train() if on_tpu else bench_scheduler()
    print(json.dumps(result))
    # Regression guard: the north-star floor is 50% MFU (vs_baseline >= 1.0);
    # a workload/geometry change that slides below it must FAIL the bench, not
    # silently record a lower number. The scheduler bench is exempt — its
    # vs_baseline tracks host speed, not a code-regression floor.
    if result["metric"] == "llama_train_step_mfu_1chip" and result["vs_baseline"] < 1.0:
        print(
            f"FAIL: {result['metric']} = {result['value']} {result['unit']} "
            f"is below the baseline floor (vs_baseline "
            f"{result['vs_baseline']} < 1.0)",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
