"""Benchmark entry point. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

On a TPU host: Llama-style training-step MFU on one chip (the reference's north-star
axis — BASELINE.json "MaxText Llama-3-8B ... >=50% MFU"; baseline = 50% MFU, so
vs_baseline = MFU/50). The model is sized to a single chip's HBM; MFU is
size-independent, making it the honest single-chip comparable.

Without a TPU: control-plane scheduling throughput vs the reference's documented cap
(75 submitted jobs/min/replica, reference server/background/__init__.py:57).
"""

from __future__ import annotations

import json
import sys
import time


def _tpu_peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    # Public per-chip bf16 peaks (workloads/config cites them too).
    if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    if "v4" in kind:
        return 275e12
    return 197e12


def bench_tpu_train() -> dict:
    import statistics

    import jax

    from dstack_tpu.workloads import train as train_lib
    from dstack_tpu.workloads.config import get_config

    dev = jax.devices()[0]
    # ~670M-param wide-geometry model (see config.PRESETS["v5e_bench"] notes and
    # the round-3 sweep in BASELINE.md): flash attention + chunked CE + bf16
    # Adam-mu fit batch 24 in the 16 GB chip with full-remat.
    cfg = get_config("v5e_bench")
    batch, seq = 24, 2048
    optimizer = train_lib.make_optimizer(mu_dtype="bfloat16")
    state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer)
    step_fn = train_lib.make_train_step(cfg, optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0, cfg.vocab_size)

    # Warmup/compile. float() forces a device sync (block_until_ready is not reliable
    # through every PJRT transport).
    state, m = step_fn(state, tokens, targets)
    float(m["loss"])

    # Per-step sync + median: immune to one-off relay stalls; each step's float()
    # costs ~10 ms of round trip against a ~2 s step (<1% bias, conservative).
    times = []
    for _ in range(8):
        t0 = time.perf_counter()
        state, m = step_fn(state, tokens, targets)
        float(m["loss"])
        times.append(time.perf_counter() - t0)
    dt = statistics.median(times)

    tokens_per_sec = batch * seq / dt
    # causal=True: count only the executed (lower-triangle) attention FLOPs.
    flops_per_sec = tokens_per_sec * cfg.flops_per_token(seq, causal=True)
    mfu_pct = 100.0 * flops_per_sec / _tpu_peak_tflops(dev)
    return {
        "metric": "llama_train_step_mfu_1chip",
        "value": round(mfu_pct, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu_pct / 50.0, 4),
        "extra": {
            "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
            "params_m": round(cfg.num_params() / 1e6, 1),
            "device": getattr(dev, "device_kind", "unknown"),
            "batch": batch,
            "seq": seq,
        },
    }


def bench_scheduler() -> dict:
    """150 single-job runs through the real scheduler loops against the mock TPU
    backend + scripted runner (no cloud, no network)."""
    import asyncio

    from dstack_tpu.server.background import tasks
    from tests.common import FakeRunnerClient, api_server, setup_mock_backend, tpu_task_spec

    N = 150  # the reference's per-replica active-run capacity (BASELINE.md)

    async def run() -> float:
        FakeRunnerClient.reset()
        tasks.get_runner_client = FakeRunnerClient.for_jpd
        async with api_server() as api:
            await setup_mock_backend(api)
            for i in range(N):
                await api.post(
                    "/api/project/main/runs/submit", tpu_task_spec(f"bench-{i}", "v5e-8")
                )
            t0 = time.perf_counter()
            for _ in range(1000):
                await tasks.process_submitted_jobs(api.db, batch=25)
                await tasks.process_running_jobs(api.db, batch=50)
                await tasks.process_terminating_jobs(api.db, batch=50)
                await tasks.process_runs(api.db, batch=50)
                done = await api.db.fetchone(
                    "SELECT COUNT(*) AS n FROM runs WHERE status = 'done'"
                )
                if done["n"] >= N:
                    break
            return time.perf_counter() - t0

    dt = asyncio.run(run())
    rate = N * 60.0 / dt
    return {
        "metric": "runs_scheduled_to_done_per_min",
        "value": round(rate, 1),
        "unit": "runs/min",
        "vs_baseline": round(rate / 75.0, 4),
        "extra": {"runs": N, "seconds": round(dt, 2)},
    }


def main() -> None:
    try:
        import jax

        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:
        on_tpu = False
    result = bench_tpu_train() if on_tpu else bench_scheduler()
    print(json.dumps(result))
    # Regression guard: the north-star floor is 50% MFU (vs_baseline >= 1.0);
    # a workload/geometry change that slides below it must FAIL the bench, not
    # silently record a lower number. The scheduler bench is exempt — its
    # vs_baseline tracks host speed, not a code-regression floor.
    if result["metric"] == "llama_train_step_mfu_1chip" and result["vs_baseline"] < 1.0:
        print(
            f"FAIL: {result['metric']} = {result['value']} {result['unit']} "
            f"is below the baseline floor (vs_baseline "
            f"{result['vs_baseline']} < 1.0)",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
