"""Benchmark entry point. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

On a TPU host: Llama-style training-step MFU on one chip (the reference's north-star
axis — BASELINE.json "MaxText Llama-3-8B ... >=50% MFU"; baseline = 50% MFU, so
vs_baseline = MFU/50). The model is sized to a single chip's HBM; MFU is
size-independent, making it the honest single-chip comparable.

Without a TPU: control-plane scheduling throughput vs the reference's documented cap
(75 submitted jobs/min/replica, reference server/background/__init__.py:57).
"""

from __future__ import annotations

import json
import sys
import time


def _tpu_peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    # Public per-chip bf16 peaks (workloads/config cites them too).
    if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    if "v4" in kind:
        return 275e12
    return 197e12


def _run_train_variant(
    cfg,
    batch: int,
    seq: int,
    grad_accum: int = 1,
    prefetch: int = 0,
    steps: int = 8,
    mesh=None,
    batch_spec=None,
) -> dict:
    """One (grad_accum, prefetch) variant of the train step: returns
    compile_s + p50/p90/median step seconds. prefetch=0 feeds one static
    device-resident batch (the legacy path); prefetch>0 streams fresh host
    batches through the data-pipeline prefetcher so the host->HBM transfer
    overlaps the previous step."""
    import statistics

    import jax

    from dstack_tpu.workloads import data as data_lib
    from dstack_tpu.workloads import train as train_lib

    optimizer = train_lib.make_optimizer(mu_dtype="bfloat16")
    state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
    step_fn = train_lib.make_train_step(cfg, optimizer, mesh, grad_accum=grad_accum)

    feed = None
    if prefetch > 0:
        spec = batch_spec
        if mesh is None:
            # Single chip: prefetch onto the default device (no mesh spec).
            source = data_lib.synthetic_batches(
                cfg.vocab_size, batch, seq, process_index=0, process_count=1
            )
            feed = data_lib.Prefetcher(
                (
                    (jax.device_put(t), jax.device_put(g))
                    for t, g in source
                ),
                depth=prefetch,
            )
        else:
            source = data_lib.synthetic_batches(cfg.vocab_size, batch, seq)
            feed = data_lib.Prefetcher(
                data_lib.sharded_batches(source, mesh, spec, batch), depth=prefetch
            )

        def next_batch():
            return next(feed)

    else:
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size
        )
        targets = jax.random.randint(
            jax.random.PRNGKey(2), (batch, seq), 0, cfg.vocab_size
        )

        def next_batch():
            return tokens, targets

    try:
        # Warmup/compile. float() forces a device sync (block_until_ready is
        # not reliable through every PJRT transport).
        t0 = time.perf_counter()
        tok, tgt = next_batch()
        state, m = step_fn(state, tok, tgt)
        float(m["loss"])
        compile_s = time.perf_counter() - t0

        # Per-step sync + median: immune to one-off relay stalls; each step's
        # float() costs ~10 ms of round trip (<1% bias, conservative).
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            tok, tgt = next_batch()
            state, m = step_fn(state, tok, tgt)
            float(m["loss"])
            times.append(time.perf_counter() - t0)
    finally:
        if feed is not None:
            feed.close()

    stats = train_lib._step_time_stats(times)
    return {
        "compile_s": round(compile_s, 2),
        "median_s": statistics.median(times),
        "p50_ms": round(stats["p50_s"] * 1000, 1),
        "p90_ms": round(stats["p90_s"] * 1000, 1),
        "grad_accum": grad_accum,
        "prefetch": prefetch,
        "batch": batch,
    }


def _variant_plan(batch: int) -> list:
    """The (grad_accum, prefetch) sweep shared by the TPU bench and the
    `make bench-train` CPU smoke — one list so the smoke always covers every
    variant the headline MFU can be attributed to."""
    return [
        ("static", dict(batch=batch, grad_accum=1, prefetch=0)),
        ("prefetch2", dict(batch=batch, grad_accum=1, prefetch=2)),
        ("accum2_prefetch2", dict(batch=2 * batch, grad_accum=2, prefetch=2)),
    ]


def bench_tpu_train() -> dict:
    import jax

    from dstack_tpu.workloads.config import get_config

    dev = jax.devices()[0]
    # ~670M-param wide-geometry model (see config.PRESETS["v5e_bench"] notes and
    # the round-3 sweep in BASELINE.md): flash attention + chunked CE + bf16
    # Adam-mu fit batch 24 in the 16 GB chip with full-remat.
    cfg = get_config("v5e_bench")
    batch, seq = 24, 2048

    # Sweep the overlapped-pipeline variants. "static" is the historical
    # measurement (one device-resident batch, accum=1); "prefetch" streams
    # fresh host batches through the async prefetcher; "accum" doubles the
    # global batch at constant microbatch/HBM via fp32-accumulated grads. The
    # headline MFU is the best variant so the trajectory attributes the win;
    # an OOM-ing variant records its error instead of killing the bench.
    variants = {}
    for name, kw in _variant_plan(batch):
        try:
            variants[name] = _run_train_variant(cfg, seq=seq, **kw)
        except Exception as e:  # noqa: BLE001 — typically RESOURCE_EXHAUSTED
            variants[name] = {"error": f"{type(e).__name__}: {e}"[:200]}

    ok = {k: v for k, v in variants.items() if "median_s" in v}
    if not ok:
        raise RuntimeError(f"all train variants failed: {variants}")
    best_name = min(ok, key=lambda k: ok[k]["median_s"] / ok[k]["batch"])
    best = ok[best_name]

    tokens_per_sec = best["batch"] * seq / best["median_s"]
    # causal=True: count only the executed (lower-triangle) attention FLOPs.
    flops_per_sec = tokens_per_sec * cfg.flops_per_token(seq, causal=True)
    mfu_pct = 100.0 * flops_per_sec / _tpu_peak_tflops(dev)
    for v in ok.values():
        v.pop("median_s", None)
    return {
        "metric": "llama_train_step_mfu_1chip",
        "value": round(mfu_pct, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu_pct / 50.0, 4),
        "extra": {
            "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
            "params_m": round(cfg.num_params() / 1e6, 1),
            "device": getattr(dev, "device_kind", "unknown"),
            "batch": best["batch"],
            "seq": seq,
            "best_variant": best_name,
            # Per-variant compile time + step-time distribution: the MFU
            # trajectory now attributes WHERE a win came from.
            "variants": variants,
        },
    }


def bench_train_pipeline() -> dict:
    """`make bench-train`: the accumulation/prefetch sweep in a bounded-steps
    CPU smoke mode (8 fake devices, tiny config) — proves every variant of the
    overlapped pipeline end to end and prints one JSON line. Not an MFU
    measurement; vs_baseline is best-variant tok/s over the static feed."""
    import os

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    from dstack_tpu.workloads.config import get_config
    from dstack_tpu.workloads.sharding import BATCH_SPEC, make_mesh

    steps = int(os.environ.get("DSTACK_TPU_BENCH_TRAIN_STEPS", "6"))
    cfg = get_config("test", max_seq_len=128)
    devices = jax.devices()[:8]
    mesh = make_mesh(dp=2, fsdp=4, devices=devices)
    batch, seq = 16, 128

    variants = {}
    with mesh:
        for name, kw in _variant_plan(batch):
            variants[name] = _run_train_variant(
                cfg, seq=seq, steps=steps, mesh=mesh, batch_spec=BATCH_SPEC, **kw
            )

    rate = {k: v["batch"] * seq / v.pop("median_s") for k, v in variants.items()}
    best = max(rate, key=rate.get)
    return {
        "metric": "train_pipeline_smoke_tok_per_sec",
        "value": round(rate[best], 1),
        "unit": "tok/s",
        "vs_baseline": round(rate[best] / rate["static"], 4),
        "extra": {
            "steps": steps,
            "best_variant": best,
            "tok_per_sec": {k: round(v, 1) for k, v in rate.items()},
            "variants": variants,
        },
    }


def _histogram_summaries(family: str, label_key: str = None) -> dict:
    """p50/p90/mean/count per label value (or one merged entry) from a tracer
    histogram — recorded into bench extras so BENCH_* files capture latency
    DISTRIBUTIONS, not just throughput."""
    from dstack_tpu.core import tracing

    snap = tracing.histogram_snapshot(family)
    if snap is None:
        return {}
    _, series = snap
    out = {}
    if label_key is None:
        s = tracing.summary(family)
        return {"all": _round_summary(s)} if s else {}
    for labels, _, _, _ in series:
        key = labels.get(label_key, "?")
        s = tracing.summary(family, labels)
        if s:
            out[key] = _round_summary(s)
    return out


def _round_summary(s: dict) -> dict:
    return {
        "count": s["count"],
        "mean_ms": round(s["mean"] * 1000, 3),
        "p50_ms": round(s["p50"] * 1000, 3),
        "p90_ms": round(s["p90"] * 1000, 3),
    }


def bench_scheduler() -> dict:
    """150 single-job runs through the real scheduler loops against the mock TPU
    backend + scripted runner (no cloud, no network)."""
    import asyncio

    from dstack_tpu.core import tracing
    from dstack_tpu.server.background import tasks
    from tests.common import FakeRunnerClient, api_server, setup_mock_backend, tpu_task_spec

    N = 150  # the reference's per-replica active-run capacity (BASELINE.md)
    tracing.reset()

    async def run() -> float:
        FakeRunnerClient.reset()
        tasks.get_runner_client = FakeRunnerClient.for_jpd
        async with api_server() as api:
            await setup_mock_backend(api)
            for i in range(N):
                await api.post(
                    "/api/project/main/runs/submit", tpu_task_spec(f"bench-{i}", "v5e-8")
                )
            t0 = time.perf_counter()
            for _ in range(1000):
                await tasks.process_submitted_jobs(api.db, batch=25)
                await tasks.process_running_jobs(api.db, batch=50)
                await tasks.process_terminating_jobs(api.db, batch=50)
                await tasks.process_runs(api.db, batch=50)
                done = await api.db.fetchone(
                    "SELECT COUNT(*) AS n FROM runs WHERE status = 'done'"
                )
                if done["n"] >= N:
                    break
            return time.perf_counter() - t0

    dt = asyncio.run(run())
    rate = N * 60.0 / dt
    return {
        "metric": "runs_scheduled_to_done_per_min",
        "value": round(rate, 1),
        "unit": "runs/min",
        "vs_baseline": round(rate / 75.0, 4),
        "extra": {
            "runs": N,
            "seconds": round(dt, 2),
            # Per-pass and per-phase latency distributions from the tracer.
            "pass_durations": _histogram_summaries(
                "dstack_tpu_scheduler_pass_duration_seconds", "pass"
            ),
            "phase_durations": {
                phase: (_histogram_summaries(family) or {}).get("all")
                for phase, family in (
                    ("queue", "dstack_tpu_run_queue_wait_seconds"),
                    ("provision", "dstack_tpu_run_provision_duration_seconds"),
                    ("pull", "dstack_tpu_run_pull_duration_seconds"),
                )
            },
        },
    }


async def _seed_bench_service(db, run_name: str, replica_port: int) -> None:
    """Insert a ready service run + running replica pointing at a local stub
    (no cloud, no runner): the proxy's own overhead is what's measured."""
    import json

    proj = await db.fetchone("SELECT * FROM projects LIMIT 1")
    run_spec = {
        "run_name": run_name,
        "configuration": {
            "type": "service",
            "commands": ["serve"],
            "port": 8000,
            "auth": False,
        },
    }
    await db.execute(
        "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at, status,"
        " run_spec) VALUES (?, ?, ?, ?, '2026-01-01', 'running', ?)",
        (f"run-{run_name}", proj["id"], proj["owner_id"], run_name, json.dumps(run_spec)),
    )
    job_spec = {
        "job_name": f"{run_name}-0-0",
        "image_name": "stub",
        "requirements": {"resources": {}},
        "service_port": 8000,
    }
    jpd = {
        "backend": "local",  # direct endpoint: no SSH tunnel in the loop
        "instance_type": {"name": "local", "resources": {"cpus": 1, "memory_gb": 1, "disk_gb": 1}},
        "instance_id": f"i-{run_name}",
        "hostname": "127.0.0.1",
        "region": "local",
    }
    jrd = {"ports_mapping": {"8000": replica_port}, "probe_ready": True}
    await db.execute(
        "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, job_spec, status,"
        " submitted_at, job_provisioning_data, job_runtime_data)"
        " VALUES (?, ?, ?, ?, 0, ?, 'running', '2026-01-01', ?, ?)",
        (f"job-{run_name}", proj["id"], f"run-{run_name}", run_name,
         json.dumps(job_spec), json.dumps(jpd), json.dumps(jrd)),
    )


def bench_proxy() -> dict:
    """Requests/sec through the in-server service proxy against a local stub
    replica: the fast path (route-table cache + pooled keep-alive upstream
    session) vs the legacy per-request-DB/per-request-session path."""
    import asyncio

    from aiohttp import web as aioweb

    from dstack_tpu.core.services import http_forward
    from dstack_tpu.server import settings
    from dstack_tpu.server.services import proxy as proxy_service
    from tests.common import api_server

    N = 250
    CONCURRENCY = 16
    # Paired rounds with the mode order flipped each time: medians cancel
    # host-load drift in either direction (shared CI hosts throttle).
    ROUNDS = 6

    async def run() -> dict:
        async def pong(request):
            return aioweb.Response(text="pong")

        stub = aioweb.Application()
        stub.router.add_route("*", "/{tail:.*}", pong)
        stub_runner = aioweb.AppRunner(stub)
        await stub_runner.setup()
        site = aioweb.TCPSite(stub_runner, "127.0.0.1", 0)
        await site.start()
        stub_port = site._server.sockets[0].getsockname()[1]

        saved_ttl = settings.PROXY_ROUTE_CACHE_TTL
        try:
            async with api_server() as api:
                await _seed_bench_service(api.db, "bench-svc", stub_port)
                proxy_port = api.client.server.port
                request_bytes = (
                    b"GET /proxy/services/main/bench-svc/ping HTTP/1.1\r\n"
                    b"Host: 127.0.0.1\r\nConnection: keep-alive\r\n\r\n"
                )

                async def hammer(n: int) -> float:
                    # Raw-socket keep-alive clients: the measurement is the
                    # proxy's cost, not an HTTP client library's.
                    per_worker = n // CONCURRENCY

                    async def worker() -> None:
                        reader, writer = await asyncio.open_connection(
                            "127.0.0.1", proxy_port
                        )
                        try:
                            for _ in range(per_worker):
                                writer.write(request_bytes)
                                await writer.drain()
                                header = await reader.readuntil(b"\r\n\r\n")
                                status = header.split(b" ", 2)[1]
                                assert status == b"200", header[:200]
                                length = 0
                                for line in header.split(b"\r\n"):
                                    if line.lower().startswith(b"content-length:"):
                                        length = int(line.split(b":")[1])
                                await reader.readexactly(length)
                        finally:
                            writer.close()

                    t0 = time.perf_counter()
                    await asyncio.gather(*(worker() for _ in range(CONCURRENCY)))
                    return per_worker * CONCURRENCY / (time.perf_counter() - t0)

                import statistics

                def set_mode(fast: bool) -> None:
                    settings.PROXY_ROUTE_CACHE_TTL = 3600 if fast else 0
                    http_forward.set_pooling(fast)
                    proxy_service.route_table.clear()

                async def measure(fast: bool) -> float:
                    # fast: cached routes + pooled keep-alive connections;
                    # legacy: per-request DB resolution + fresh session.
                    set_mode(fast)
                    await hammer(16)  # warmup (fast: builds route entry + pool)
                    return await hammer(N)

                # Paired design: each round measures both modes back to back
                # (order flipped), and the speedup is the median of PER-ROUND
                # ratios — correlated host-load drift hits both measurements
                # of a pair and cancels out of the ratio.
                legacy_rates, fast_rates, ratios = [], [], []
                for i in range(ROUNDS):
                    pair = {}
                    for fast in ((False, True) if i % 2 == 0 else (True, False)):
                        pair[fast] = await measure(fast)
                    legacy_rates.append(pair[False])
                    fast_rates.append(pair[True])
                    ratios.append(pair[True] / pair[False])
                return {
                    "before": statistics.median(legacy_rates),
                    "after": statistics.median(fast_rates),
                    "speedup": statistics.median(ratios),
                }
        finally:
            settings.PROXY_ROUTE_CACHE_TTL = saved_ttl
            http_forward.set_pooling(True)
            proxy_service.route_table.clear()
            proxy_service.stats.reset()
            await http_forward.close_session()
            await stub_runner.cleanup()

    from dstack_tpu.core import tracing

    tracing.reset()
    r = asyncio.run(run())
    return {
        "metric": "proxy_requests_per_sec",
        "value": round(r["after"], 1),
        "unit": "req/s",
        # Baseline = the legacy per-request-session/per-request-DB path;
        # median of per-round paired ratios (host drift cancels per pair).
        "vs_baseline": round(r["speedup"], 2),
        "extra": {
            "legacy_req_per_sec": round(r["before"], 1),
            "requests": N,
            "concurrency": CONCURRENCY,
            # End-to-end proxied latency distribution across both modes,
            # from the tracer's service-latency histogram.
            "latency": _histogram_summaries(
                "dstack_tpu_service_request_latency_seconds"
            ).get("all"),
        },
    }


def smoke_observability() -> dict:
    """`make smoke-observability`: boot the server in-process, drive one run
    through the full FSM, and assert the events timeline + /metrics histogram
    families are live. Raises (non-zero exit) on any missing piece."""
    import asyncio

    from dstack_tpu.core import tracing
    from dstack_tpu.server.background import tasks
    from tests.common import FakeRunnerClient, api_server, drive, setup_mock_backend, tpu_task_spec

    tracing.reset()

    async def run() -> dict:
        FakeRunnerClient.reset()
        tasks.get_runner_client = FakeRunnerClient.for_jpd
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("smoke-obs", "v5e-8")
            )
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "smoke-obs"})
            assert run["status"] == "done", f"run ended {run['status']}"

            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "smoke-obs"}
            )
            statuses = [e["new_status"] for e in data["events"] if e["job_id"]]
            assert statuses == [
                "submitted", "provisioning", "pulling", "running", "terminating", "done",
            ], statuses
            phases = data["phases"]
            assert all(
                phases[p] is not None for p in ("queue", "provision", "pull", "total")
            ), phases

            resp = await api.client.get("/metrics")
            text = await resp.text()
            for family in (
                "dstack_tpu_run_queue_wait_seconds",
                "dstack_tpu_run_provision_duration_seconds",
                "dstack_tpu_scheduler_pass_duration_seconds",
            ):
                assert f"{family}_bucket{{" in text, f"{family} has no samples"
                assert f"{family}_count" in text, family
            return {
                "metric": "smoke_observability",
                "value": len(data["events"]),
                "unit": "events",
                "phases_ms": {
                    k: round(v * 1000, 1) for k, v in phases.items() if v is not None
                },
            }

    result = asyncio.run(run())
    print(json.dumps(result))
    return result


def main() -> None:
    try:
        import jax

        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:
        on_tpu = False
    result = bench_tpu_train() if on_tpu else bench_scheduler()
    print(json.dumps(result))
    # Regression guard: the north-star floor is 50% MFU (vs_baseline >= 1.0);
    # a workload/geometry change that slides below it must FAIL the bench, not
    # silently record a lower number. The scheduler bench is exempt — its
    # vs_baseline tracks host speed, not a code-regression floor.
    if result["metric"] == "llama_train_step_mfu_1chip" and result["vs_baseline"] < 1.0:
        print(
            f"FAIL: {result['metric']} = {result['value']} {result['unit']} "
            f"is below the baseline floor (vs_baseline "
            f"{result['vs_baseline']} < 1.0)",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
