# dstack-tpu build/test/release entry points.
#
# Parity: the reference ships its runner binaries + server wheel through CI to an
# artifact bucket (base/compute.py:612-628 downloads them). Same shape here:
# `make release` produces everything `gcp` startup scripts fetch — the runner
# binary (runner_url) and the wheel (gateway_wheel_url) — and `make publish`
# pushes them to the artifact bucket with gsutil when available.

ARTIFACT_BUCKET ?= gs://dstack-tpu-artifacts
DIST := dist

.PHONY: all runner wheel image test test-native test-python bench bench-scheduler bench-proxy bench-train bench-serve bench-routing bench-kernels bench-preemption bench-chaos smoke-observability smoke-serve smoke-draft smoke-preemption smoke-chaos smoke-gang smoke-usage release publish clean

all: runner wheel

runner:
	$(MAKE) -C runner

wheel:
	python -m pip wheel --no-deps --no-build-isolation -w $(DIST) . \
	  || python setup.py bdist_wheel -d $(DIST) 2>/dev/null \
	  || python -m build --wheel -o $(DIST) -n

# The docker/tpu base image (libtpu + JAX + sshd) — the default job image.
image:
	docker build -t dstack-tpu/base:latest docker/tpu

test: test-native test-python

test-native:
	$(MAKE) -C runner test

test-python:
	python -m pytest tests/ -q

bench:
	python bench.py

# Control-plane throughput only (forces the CPU path even on a TPU host):
# prints one JSON line — {"metric": "runs_scheduled_to_done_per_min", ...} —
# so a scheduler regression is one command to check.
bench-scheduler:
	JAX_PLATFORMS=cpu python -c "import json, bench; print(json.dumps(bench.bench_scheduler()))"

# Service-proxy data-plane throughput: one JSON line —
# {"metric": "proxy_requests_per_sec", ...} — vs_baseline is the speedup over
# the legacy per-request-session/per-request-DB path.
bench-proxy:
	JAX_PLATFORMS=cpu python -c "import json, bench; print(json.dumps(bench.bench_proxy()))"

# Training-pipeline smoke: the grad-accumulation/prefetch sweep on 8 fake CPU
# devices with bounded steps (DSTACK_TPU_BENCH_TRAIN_STEPS, default 6) — one
# JSON line per run; proves every overlapped-pipeline variant end to end.
bench-train:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  python -c "import json, bench; print(json.dumps(bench.bench_train_pipeline()))"

# Serving-engine bench: open-loop synthetic load against the continuous-
# batching engine on CPU — one JSON line with tokens/s/chip, p50/p99 TTFT and
# inter-token latency; vs_baseline is continuous over static batching.
# Extras attribute the tier-2 levers: shared-prefix tok/s with the prefix
# cache on vs off, injected-long-prompt ITL chunked vs not, and speculative
# decode (which FAILS the bench if it ever diverges from greedy).
bench-serve:
	JAX_PLATFORMS=cpu python -c "import json, bench; print(json.dumps(bench.bench_serve()))"

# Fleet-routing bench: two in-process engine replicas behind the proxy's real
# routing decision code (services/routing.choose), an 80%-shared-prefix mix
# sized past one replica's page pool — cache-aware vs round-robin in paired
# order-flipped rounds. One JSON line; value is the aggregate fleet tok/s
# ratio (prefix over rr), extras carry fleet hit rates, TTFT p99, spill rate.
bench-routing:
	JAX_PLATFORMS=cpu python -c "import json, bench; print(json.dumps(bench.bench_routing()))"

# Kernel smoke: every in-repo Pallas kernel (flash + splash fwd+bwd, paged
# decode), the int8/fp8 quantized matmuls, and both collective-matmul rings
# (tp reduce-scatter + fsdp all-gather), in CPU interpret mode — one JSON
# line with max error vs the XLA references. Exits non-zero past tolerance
# (attention/collective >1e-4, int8 rel >5%, fp8 rel >10%). Run this before
# a TPU submit touching kernel code.
bench-kernels:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  python -c "import json, bench; print(json.dumps(bench.bench_kernels()))"

# Preemption/goodput bench: a live train loop is killed at fixed steps;
# checkpoint+resume vs restart-from-step-0, both through the server's goodput
# ledger. One JSON line — value is the goodput uplift (x); FAILS (non-zero
# exit) if a resumed loss ever diverges from the uninterrupted reference or
# the uplift lands under the 1.5x acceptance floor.
bench-preemption:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  python -c "import json, bench; print(json.dumps(bench.bench_preemption()))"

# Chaos bench: N runs across TWO scheduler replicas (lease-sharded, one DB)
# under an injected fault schedule — agent drops, backend 5xx — with one
# replica killed mid-run. FAILS (non-zero exit) unless 100% of runs reach
# `done`, zero slices are double-booked, and every orphaned run is reclaimed;
# prints recovery-time p50/p90 derived from run_events.
bench-chaos:
	JAX_PLATFORMS=cpu python -c "import json, bench; print(json.dumps(bench.bench_chaos()))"

# Chaos smoke: lease reclaim through the REAL server + native agent. Replica A
# drives an actual local-backend process to RUNNING and dies; replica B must
# reclaim the expired lease, reconcile (probing the live agent), and finish
# the SAME workload without a restart. Non-zero exit on any missing piece.
smoke-chaos:
	JAX_PLATFORMS=cpu python -c "import bench; bench.smoke_chaos()"

# Elastic-training smoke: boots the server, drives a REAL train run through
# the native agent with async checkpointing, kills the workload mid-run, and
# asserts the rescue end to end — gang_retry in run_events, the resumed
# attempt continuing from the last checkpoint (not step 0), restart_s in the
# goodput ledger, and the recovery histogram on /metrics. Non-zero exit on
# any missing piece.
smoke-preemption:
	JAX_PLATFORMS=cpu python -c "import bench; bench.smoke_preemption()"

# Gang-health smoke: a simulated 4-host gang through the real server — real
# TelemetryEmitters (host 3 delayed 2.5x) tailed by scripted agents; asserts
# the straggler run_event within 2 collection passes, the {host} gauge on a
# live /metrics scrape, the per-host CLI table, and that the goodput ledger /
# step histogram stay lead-lineage-only.
smoke-gang:
	JAX_PLATFORMS=cpu python -c "import bench; bench.smoke_gang()"

# Observability smoke: boots the server in-process, drives one run through the
# full FSM, and asserts the events timeline + /metrics histograms are live.
# Then drives a REAL train workload through the native runner agent and
# asserts its telemetry lands end to end: step/MFU/goodput on /metrics (per-run
# gauges scraped while the run is live), workload columns in `dstack-tpu
# metrics`, and a goodput ledger that debits the compile stall.
# Prints one JSON line; a missing surface is a non-zero exit.
smoke-observability:
	JAX_PLATFORMS=cpu python -c "import bench; bench.smoke_observability()"

# Fleet accounting smoke: a real server drives one run end-to-end with a
# slow scripted agent, one metering tick lands ledger chip-seconds within
# 10% of wall x chips, and `dstack-tpu usage` renders the row; then an
# unplaceable run must log a placement_attempt event (reason no_offers),
# carry `waiting: no_offers` for ps -v, and raise the pending-reason gauge.
# Prints one JSON line; a missing surface is a non-zero exit.
smoke-usage:
	JAX_PLATFORMS=cpu python -c "import bench; bench.smoke_usage()"

# Serving smoke: boots the server + a real tier-2 engine replica (prefix
# cache + chunked prefill + speculative decode), streams SSE tokens through
# the proxy, drives shared-prefix + speculative requests and asserts their
# hit/accept ratios land on /metrics, then asserts the latency autoscaler
# scales a service from zero (run_events carries the autoscaler actor +
# cold-start histogram) and back. Then two tp=2-SHARDED replicas (8 fake CPU
# devices, disjoint pairs) serve shared-prefix traffic behind the cache-aware
# router: asserts routing decision counters render on /metrics and the fleet
# prefix hit rate beats a round-robin rerun of the same traffic. One JSON
# line; any missing piece is a non-zero exit.
smoke-serve:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  python -c "import bench; bench.smoke_serve()"

# 30-step CPU convergence smoke for the speculative-decode draft head: rolls
# the target out on the natural-text bench mix, distills the head against the
# frozen target (train.py --draft-head's loss), and fails unless the loss
# actually drops and the trained head honors the [S, k] int32 proposer
# contract the serve engine builds verify rows from.
smoke-draft:
	JAX_PLATFORMS=cpu python -c "import bench; bench.smoke_draft()"

release: runner wheel
	@mkdir -p $(DIST)
	cp runner/build/dstack-tpu-runner $(DIST)/
	@echo "artifacts in $(DIST)/: $$(ls $(DIST))"

publish: release
	gsutil cp $(DIST)/dstack-tpu-runner $(ARTIFACT_BUCKET)/dstack-tpu-runner
	gsutil cp $(DIST)/dstack_tpu-*.whl $(ARTIFACT_BUCKET)/dstack_tpu-latest-py3-none-any.whl

clean:
	rm -rf $(DIST)
	$(MAKE) -C runner clean
