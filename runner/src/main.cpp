// dstack-tpu-runner: the on-host job agent.
//
// Parity: reference runner/cmd/runner + runner/internal/runner/api (http.go:20-122):
// an HTTP API the control plane drives over an SSH tunnel (or directly for the local
// backend): submit -> upload_code -> run -> pull(offset) -> stop, plus health/metrics.
#include <signal.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>

#include "executor.hpp"
#include "http.hpp"
#include "json.hpp"

int main(int argc, char** argv) {
  std::string host = "127.0.0.1";
  int port = 10999;
  std::string base_dir = "/tmp/dstack-tpu-runner";
  // Container execution (the reference shim's role, shim/docker.go): never = host
  // pty exec only; auto = container when the job names an image and an engine
  // answers; always = container or fail the job.
  std::string docker_mode = "never";
  std::string docker_host;  // unix socket path; empty = DOCKER_HOST or the default
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string { return i + 1 < argc ? argv[++i] : ""; };
    if (a == "--host") host = next();
    else if (a == "--port") port = atoi(next().c_str());
    else if (a == "--base-dir") base_dir = next();
    else if (a == "--docker") docker_mode = next();
    else if (a == "--docker-host") docker_host = next();
    else if (a == "--help") {
      printf(
          "usage: dstack-tpu-runner [--host H] [--port P] [--base-dir DIR]\n"
          "                         [--docker never|auto|always] [--docker-host SOCK]\n");
      return 0;
    }
  }
  if (docker_mode != "never" && docker_mode != "auto" && docker_mode != "always") {
    fprintf(stderr, "invalid --docker mode: %s\n", docker_mode.c_str());
    return 2;
  }
  signal(SIGPIPE, SIG_IGN);

  drunner::Executor executor(base_dir, docker_mode, docker_host);
  dhttp::Server server(host, port);

  // Trace propagation: the control plane stamps every call with its current
  // trace id (X-Dstack-Trace-Id, services/runner/client.py). Echoing it on
  // the agent's own log line means a run_event's trace_id greps straight
  // into this host's agent log. Quiet ops (healthcheck, pull, metrics) are
  // polled every second and would drown the log — only state-changing calls
  // are echoed.
  auto trace_log = [](const dhttp::Request& req, const char* op) {
    auto it = req.headers.find("x-dstack-trace-id");
    if (it != req.headers.end() && !it->second.empty()) {
      printf("[trace %s] %s\n", it->second.c_str(), op);
      fflush(stdout);
    }
  };

  server.handle("GET", "/api/healthcheck", [&](const dhttp::Request&) {
    return dhttp::Response{200, "application/json", executor.health().dump()};
  });
  server.handle("POST", "/api/submit", [&](const dhttp::Request& req) {
    trace_log(req, "POST /api/submit");
    return dhttp::Response{200, "application/json",
                           executor.submit(dj::Json::parse(req.body)).dump()};
  });
  server.handle("POST", "/api/upload_code", [&](const dhttp::Request& req) {
    trace_log(req, "POST /api/upload_code");
    return dhttp::Response{200, "application/json", executor.upload_code(req.body).dump()};
  });
  server.handle("POST", "/api/run", [&](const dhttp::Request& req) {
    trace_log(req, "POST /api/run");
    return dhttp::Response{200, "application/json", executor.run().dump()};
  });
  server.handle("GET", "/api/pull", [&](const dhttp::Request& req) {
    int64_t offset = 0;
    auto it = req.query.find("offset");
    if (it != req.query.end()) offset = atoll(it->second.c_str());
    return dhttp::Response{200, "application/json", executor.pull(offset).dump()};
  });
  server.handle("POST", "/api/stop", [&](const dhttp::Request& req) {
    trace_log(req, "POST /api/stop");
    bool abort = false;
    if (!req.body.empty()) abort = dj::Json::parse(req.body)["abort"].as_bool();
    return dhttp::Response{200, "application/json", executor.stop(abort).dump()};
  });
  server.handle("GET", "/api/metrics", [&](const dhttp::Request&) {
    return dhttp::Response{200, "application/json", executor.metrics().dump()};
  });
  // On-demand profiler capture: {"seconds": N} -> control file the live
  // workload's telemetry emitter polls; the trace artifact path comes back in
  // the response and in the workload's profile_end telemetry mark.
  server.handle("POST", "/api/profile", [&](const dhttp::Request& req) {
    trace_log(req, "POST /api/profile");
    dj::Json body = req.body.empty() ? dj::Json::object() : dj::Json::parse(req.body);
    return dhttp::Response{200, "application/json", executor.profile(body).dump()};
  });

  // Port 0 resolves to an ephemeral port; print it so the spawner can read it.
  printf("dstack-tpu-runner listening on %s:%d\n", host.c_str(), server.port());
  fflush(stdout);
  server.serve_forever();
  return 0;
}
