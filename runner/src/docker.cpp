#include "docker.hpp"

#include <dirent.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#include <cctype>
#include <cstdlib>
#include <cstring>
#include <sstream>

namespace ddocker {

DockerClient::DockerClient(std::string socket_path) : socket_path_(std::move(socket_path)) {}

std::string DockerClient::default_socket() {
  const char* host = getenv("DOCKER_HOST");
  if (host && strncmp(host, "unix://", 7) == 0) return host + 7;
  return "/var/run/docker.sock";
}

std::string url_escape(const std::string& s) {
  std::string out;
  for (unsigned char c : s) {
    if (isalnum(c) || c == '-' || c == '_' || c == '.' || c == '~') {
      out += static_cast<char>(c);
    } else {
      char buf[4];
      snprintf(buf, sizeof(buf), "%%%02X", c);
      out += buf;
    }
  }
  return out;
}

// URL-safe alphabet: the engine decodes X-Registry-Auth as base64url (the Go
// daemon uses base64.URLEncoding), so +/ would corrupt credentials whose JSON
// happens to encode to those positions.
static const char kB64[] = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_";

static std::string b64encode(const std::string& in) {
  std::string out;
  size_t i = 0;
  while (i + 2 < in.size()) {
    uint32_t v = (static_cast<unsigned char>(in[i]) << 16) |
                 (static_cast<unsigned char>(in[i + 1]) << 8) |
                 static_cast<unsigned char>(in[i + 2]);
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63];
    out += kB64[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = static_cast<unsigned char>(in[i]) << 16;
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = (static_cast<unsigned char>(in[i]) << 16) |
                 (static_cast<unsigned char>(in[i + 1]) << 8);
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63];
    out += '=';
  }
  return out;
}

std::string encode_registry_auth(const std::string& username, const std::string& password) {
  if (username.empty() && password.empty()) return "";
  dj::Json auth = dj::Json::object();
  auth.set("username", username);
  auth.set("password", password);
  return b64encode(auth.dump());
}

std::vector<std::string> host_tpu_devices() {
  std::vector<std::string> devices;
  DIR* dev = opendir("/dev");
  if (dev) {
    while (dirent* e = readdir(dev)) {
      if (strncmp(e->d_name, "accel", 5) == 0) {
        devices.push_back(std::string("/dev/") + e->d_name);
      }
    }
    closedir(dev);
  }
  DIR* vfio = opendir("/dev/vfio");
  if (vfio) {
    while (dirent* e = readdir(vfio)) {
      if (e->d_name[0] == '.') continue;
      devices.push_back(std::string("/dev/vfio/") + e->d_name);
    }
    closedir(vfio);
  }
  return devices;
}

// ---------------------------------------------------------------------------
// HTTP/1.1 over AF_UNIX

namespace {

// Reads exactly up to n bytes with a poll-based deadline; returns bytes read
// (0 on orderly EOF), -1 on error/timeout.
ssize_t read_some(int fd, char* buf, size_t n, int timeout_sec) {
  pollfd pfd{fd, POLLIN, 0};
  int pr = poll(&pfd, 1, timeout_sec * 1000);
  if (pr <= 0) return -1;
  return read(fd, buf, n);
}

struct Conn {
  int fd = -1;
  std::string buffered;  // bytes read past what the caller consumed

  ~Conn() {
    if (fd >= 0) close(fd);
  }

  // Reads until `delim` appears; returns content before delim, consumes it.
  // `max_bytes` bounds buffering: a daemon that streams endless bytes with no
  // delimiter (hostile or broken) must not balloon memory — fail instead.
  bool read_until(const std::string& delim, std::string* out, int timeout_sec,
                  size_t max_bytes = 1 << 20) {
    size_t pos;
    while ((pos = buffered.find(delim)) == std::string::npos) {
      if (buffered.size() > max_bytes) return false;
      char buf[8192];
      ssize_t n = read_some(fd, buf, sizeof(buf), timeout_sec);
      if (n <= 0) return false;
      buffered.append(buf, static_cast<size_t>(n));
    }
    *out = buffered.substr(0, pos);
    buffered.erase(0, pos + delim.size());
    return true;
  }

  // Reads exactly n bytes (from buffer + socket) into sink/out.
  bool read_n(size_t n, std::string* out, const StreamSink* sink, int timeout_sec) {
    while (n > 0) {
      if (!buffered.empty()) {
        size_t take = std::min(n, buffered.size());
        if (sink) (*sink)(buffered.data(), take);
        if (out) out->append(buffered, 0, take);
        buffered.erase(0, take);
        n -= take;
        continue;
      }
      char buf[8192];
      ssize_t r = read_some(fd, buf, std::min(n, sizeof(buf)), timeout_sec);
      if (r <= 0) return false;
      if (sink) (*sink)(buf, static_cast<size_t>(r));
      if (out) out->append(buf, static_cast<size_t>(r));
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  // Reads to EOF. `max_capture` bounds what is appended to `out` (streaming
  // sinks are unbounded by design); excess buffered bytes are discarded.
  void read_all(std::string* out, const StreamSink* sink, int timeout_sec,
                long max_capture = -1) {
    if (!buffered.empty()) {
      if (sink) (*sink)(buffered.data(), buffered.size());
      if (out) out->append(buffered);
      buffered.clear();
    }
    char buf[8192];
    ssize_t n;
    while ((n = read_some(fd, buf, sizeof(buf), timeout_sec)) > 0) {
      if (sink) (*sink)(buf, static_cast<size_t>(n));
      if (out && (max_capture < 0 ||
                  out->size() < static_cast<size_t>(max_capture))) {
        out->append(buf, static_cast<size_t>(n));
      }
    }
  }
};

std::string lower(std::string s) {
  for (auto& c : s) c = static_cast<char>(tolower(c));
  return s;
}

}  // namespace

HttpResult DockerClient::request(const std::string& method, const std::string& path,
                                 const std::string& body,
                                 const std::vector<std::string>& extra_headers,
                                 const StreamSink* sink, int timeout_sec) {
  Conn conn;
  conn.fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (conn.fd < 0) throw DockerError("socket() failed");
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (socket_path_.size() >= sizeof(addr.sun_path)) throw DockerError("socket path too long");
  strncpy(addr.sun_path, socket_path_.c_str(), sizeof(addr.sun_path) - 1);
  if (connect(conn.fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    throw DockerError("cannot connect to docker daemon at " + socket_path_ + ": " +
                      strerror(errno));
  }

  std::ostringstream req;
  req << method << " " << path << " HTTP/1.1\r\n"
      << "Host: docker\r\nConnection: close\r\n";
  for (const auto& h : extra_headers) req << h << "\r\n";
  if (!body.empty() || method == "POST" || method == "DELETE") {
    req << "Content-Type: application/json\r\nContent-Length: " << body.size() << "\r\n";
  }
  req << "\r\n" << body;
  std::string payload = req.str();
  size_t off = 0;
  while (off < payload.size()) {
    ssize_t n = write(conn.fd, payload.data() + off, payload.size() - off);
    if (n <= 0) throw DockerError("write to docker daemon failed");
    off += static_cast<size_t>(n);
  }

  std::string status_line;
  if (!conn.read_until("\r\n", &status_line, timeout_sec)) {
    throw DockerError("no response from docker daemon");
  }
  int status = 0;
  {
    auto sp = status_line.find(' ');
    if (sp != std::string::npos) status = atoi(status_line.c_str() + sp + 1);
  }
  std::string header_block;
  if (!conn.read_until("\r\n\r\n", &header_block, timeout_sec)) {
    throw DockerError("truncated response headers from docker daemon");
  }
  bool chunked = false;
  long content_length = -1;
  std::istringstream hs(header_block);
  std::string line;
  while (std::getline(hs, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    auto colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = lower(line.substr(0, colon));
    std::string val = line.substr(colon + 1);
    while (!val.empty() && val.front() == ' ') val.erase(0, 1);
    if (key == "transfer-encoding" && lower(val).find("chunked") != std::string::npos) {
      chunked = true;
    } else if (key == "content-length") {
      content_length = atol(val.c_str());
    }
  }

  HttpResult out;
  out.status = status;
  // Error statuses carry a small JSON body we want intact, not streamed.
  const StreamSink* body_sink = (status >= 300) ? nullptr : sink;
  std::string* capture = (body_sink != nullptr) ? nullptr : &out.body;
  // Buffered (non-streamed) bodies are bounded: a hostile/corrupt daemon must
  // not balloon memory through ANY body path — chunk sizes, Content-Length,
  // or read-to-EOF. Streaming sinks stay unbounded (logs/pull progress).
  const long kMaxCapture = 64L * 1024 * 1024;
  if (chunked) {
    long captured = 0;
    while (true) {
      std::string size_line;
      if (!conn.read_until("\r\n", &size_line, timeout_sec)) break;
      long chunk = strtol(size_line.c_str(), nullptr, 16);
      if (chunk <= 0) break;
      // A hostile/corrupt size line (e.g. "FFFFFFFFFFFFFFF") must not turn
      // into an exabyte read_n that buffers until timeout.
      if (chunk > (1L << 30)) break;
      captured += chunk;
      if (capture != nullptr && captured > kMaxCapture) break;
      if (!conn.read_n(static_cast<size_t>(chunk), capture, body_sink, timeout_sec)) break;
      std::string crlf;
      conn.read_until("\r\n", &crlf, timeout_sec);
    }
  } else if (content_length >= 0) {
    if (capture == nullptr || content_length <= kMaxCapture) {
      conn.read_n(static_cast<size_t>(content_length), capture, body_sink, timeout_sec);
    }
  } else if (status != 204) {
    conn.read_all(capture, body_sink, timeout_sec, kMaxCapture);
  }
  return out;
}

// Daemon bytes are untrusted input: a malformed body must surface as the
// client's own error type, not leak the JSON parser's runtime_error upward.
static dj::Json parse_engine_json(const std::string& body, const std::string& what) {
  try {
    return dj::Json::parse(body);
  } catch (const std::exception&) {
    throw DockerError(what + ": malformed JSON from engine");
  }
}

static std::string api_error(const HttpResult& r, const std::string& what) {
  std::string msg = what + " failed (HTTP " + std::to_string(r.status) + ")";
  try {
    dj::Json err = dj::Json::parse(r.body);
    if (err["message"].is_string()) msg += ": " + err["message"].as_string();
  } catch (...) {
    if (!r.body.empty() && r.body.size() < 300) msg += ": " + r.body;
  }
  return msg;
}

bool DockerClient::ping() {
  try {
    return request("GET", "/_ping", "", {}, nullptr, 5).status == 200;
  } catch (const DockerError&) {
    return false;
  }
}

bool DockerClient::image_exists(const std::string& image) {
  HttpResult r = request("GET", "/images/" + url_escape(image) + "/json", "", {}, nullptr, 30);
  return r.status == 200;
}

void DockerClient::pull_image(const std::string& image, const std::string& registry_auth_b64,
                              const std::function<void(const std::string&)>& progress,
                              const std::function<bool()>& abort_check) {
  // Digest-pinned refs (repo@sha256:...) go out whole; tagged refs split on the
  // last colon after the last slash.
  std::string query;
  if (image.find('@') != std::string::npos) {
    query = "/images/create?fromImage=" + url_escape(image);
  } else {
    std::string name = image, tag = "latest";
    auto colon = image.rfind(':');
    auto slash = image.rfind('/');
    if (colon != std::string::npos && (slash == std::string::npos || colon > slash)) {
      name = image.substr(0, colon);
      tag = image.substr(colon + 1);
    }
    query = "/images/create?fromImage=" + url_escape(name) + "&tag=" + url_escape(tag);
  }
  std::vector<std::string> headers;
  if (!registry_auth_b64.empty()) headers.push_back("X-Registry-Auth: " + registry_auth_b64);

  // The engine streams NDJSON progress rows; surface statuses + collect errors
  // (reference parses the same rows, docker.go:700-733).
  std::string partial;
  std::string pull_error;
  StreamSink sink = [&](const char* data, size_t n) {
    if (abort_check && abort_check()) throw DockerError("image pull aborted by stop request");
    partial.append(data, n);
    size_t nl;
    while ((nl = partial.find('\n')) != std::string::npos) {
      std::string line = partial.substr(0, nl);
      partial.erase(0, nl + 1);
      if (line.empty()) continue;
      try {
        dj::Json row = dj::Json::parse(line);
        if (row["error"].is_string()) {
          pull_error = row["error"].as_string();
        } else if (row["status"].is_string()) {
          const std::string& st = row["status"].as_string();
          // Only the coarse phases, not per-layer byte counts.
          if (st.rfind("Status:", 0) == 0 || st.rfind("Pulling from", 0) == 0) {
            if (progress) progress(st);
          }
        }
      } catch (...) {
      }
    }
  };
  HttpResult r = request("POST", query, "", headers, &sink, 1800);
  if (!pull_error.empty()) throw DockerError("pulling " + image + ": " + pull_error);
  if (r.status != 200) throw DockerError(api_error(r, "pulling " + image));
}

std::string DockerClient::create_container(const dj::Json& config, const std::string& name) {
  HttpResult r = request("POST", "/containers/create?name=" + url_escape(name), config.dump());
  if (r.status != 201) throw DockerError(api_error(r, "creating container " + name));
  return dj::Json::parse(r.body)["Id"].as_string();
}

void DockerClient::start_container(const std::string& id) {
  HttpResult r = request("POST", "/containers/" + id + "/start", "");
  // 304 = already started (restart recovery re-attach).
  if (r.status != 204 && r.status != 304) throw DockerError(api_error(r, "starting container"));
}

int DockerClient::wait_container(const std::string& id) {
  // No practical deadline: jobs run for hours. 7 days as an absurd upper bound.
  HttpResult r = request("POST", "/containers/" + id + "/wait", "", {}, nullptr, 7 * 24 * 3600);
  if (r.status != 200) throw DockerError(api_error(r, "waiting for container"));
  return static_cast<int>(dj::Json::parse(r.body)["StatusCode"].as_int());
}

void DockerClient::kill_container(const std::string& id, const std::string& sig) {
  HttpResult r = request("POST", "/containers/" + id + "/kill?signal=" + url_escape(sig), "");
  // 409 = not running; both fine for a stop path.
  if (r.status != 204 && r.status != 404 && r.status != 409) {
    throw DockerError(api_error(r, "killing container"));
  }
}

void DockerClient::remove_container(const std::string& id, bool force) {
  HttpResult r =
      request("DELETE", "/containers/" + id + (force ? "?force=1" : ""), "");
  if (r.status != 204 && r.status != 404) throw DockerError(api_error(r, "removing container"));
}

void DockerClient::stream_logs(const std::string& id, bool follow, const StreamSink& sink) {
  std::string path = "/containers/" + id + "/logs?stdout=1&stderr=1";
  if (follow) path += "&follow=1";
  HttpResult r = request("GET", path, "", {}, &sink, 7 * 24 * 3600);
  if (r.status != 200) throw DockerError(api_error(r, "streaming logs"));
}

dj::Json DockerClient::list_containers(const std::string& label) {
  dj::Json filters = dj::Json::object();
  dj::Json labels = dj::Json::array();
  labels.push_back(label);
  filters.set("label", std::move(labels));
  HttpResult r = request(
      "GET", "/containers/json?all=1&filters=" + url_escape(filters.dump()), "");
  if (r.status != 200) throw DockerError(api_error(r, "listing containers"));
  return parse_engine_json(r.body, "listing containers");
}

dj::Json DockerClient::inspect_container(const std::string& id) {
  HttpResult r = request("GET", "/containers/" + id + "/json", "");
  if (r.status != 200) throw DockerError(api_error(r, "inspecting container"));
  return parse_engine_json(r.body, "inspecting container");
}

dj::Json DockerClient::container_stats(const std::string& id) {
  HttpResult r = request("GET", "/containers/" + id + "/stats?stream=false", "", {}, nullptr, 30);
  if (r.status != 200) throw DockerError(api_error(r, "reading container stats"));
  return parse_engine_json(r.body, "reading container stats");
}

}  // namespace ddocker
