// Docker Engine API client over the local unix socket (no external deps).
//
// Parity: the reference shim drives containers through the Docker Go SDK
// (runner/internal/shim/docker.go:63-875 — pull with registry auth, create with
// device mapping, start/wait, label-based state restore). Here the same engine
// REST API is spoken directly over /var/run/docker.sock with a small HTTP/1.1
// client: the runner is the host agent, so the container lifecycle lives next to
// the executor instead of in a separate shim process.
#pragma once

#include <functional>
#include <stdexcept>
#include <string>
#include <vector>

#include "json.hpp"

namespace ddocker {

struct DockerError : std::runtime_error {
  explicit DockerError(const std::string& msg) : std::runtime_error(msg) {}
};

struct HttpResult {
  int status = 0;
  std::string body;
};

// Streaming sink for chunk-decoded response bodies (image pull progress, logs).
using StreamSink = std::function<void(const char*, size_t)>;

class DockerClient {
 public:
  // socket_path: AF_UNIX path of the engine API. default_socket() honors
  // DOCKER_HOST=unix:///... and falls back to /var/run/docker.sock.
  explicit DockerClient(std::string socket_path);
  static std::string default_socket();

  // GET /_ping — true when an engine is reachable on the socket.
  bool ping();

  bool image_exists(const std::string& image);

  // POST /images/create — streams progress JSON lines; `registry_auth_b64` (may be
  // empty) goes out as X-Registry-Auth (docker.go:877-893 encodeRegistryAuth).
  // `progress` receives human-readable status lines. `abort_check` (optional) is
  // polled per received chunk; returning true aborts the transfer mid-stream.
  // Throws on engine errors, error lines in the progress stream, or abort.
  void pull_image(const std::string& image, const std::string& registry_auth_b64,
                  const std::function<void(const std::string&)>& progress,
                  const std::function<bool()>& abort_check = nullptr);

  // POST /containers/create?name=... — returns the container id.
  std::string create_container(const dj::Json& config, const std::string& name);

  void start_container(const std::string& id);

  // POST /containers/{id}/wait — blocks until exit, returns StatusCode.
  int wait_container(const std::string& id);

  void kill_container(const std::string& id, const std::string& sig);
  void remove_container(const std::string& id, bool force = true);

  // GET /containers/{id}/logs — raw byte stream for Tty containers. With
  // follow=true the call blocks until the container stops.
  void stream_logs(const std::string& id, bool follow, const StreamSink& sink);

  // GET /containers/json?all=1 filtered by label ("key=value").
  dj::Json list_containers(const std::string& label);

  dj::Json inspect_container(const std::string& id);

  // GET /containers/{id}/stats?stream=false — one-shot resource usage sample.
  dj::Json container_stats(const std::string& id);

 private:
  HttpResult request(const std::string& method, const std::string& path,
                     const std::string& body,
                     const std::vector<std::string>& extra_headers = {},
                     const StreamSink* sink = nullptr, int timeout_sec = 600);

  std::string socket_path_;
};

// Percent-encode one path segment (image names contain '/' and ':').
std::string url_escape(const std::string& s);

// base64 of {"username":...,"password":...} for X-Registry-Auth.
std::string encode_registry_auth(const std::string& username, const std::string& password);

// Host TPU device files to map into containers: /dev/accel* plus /dev/vfio/*
// (the PCI-attached v5e/v6e path), mirroring the reference's GPU device wiring
// (shim/docker.go:1008-1019, shim/host/gpu.go:44-58) for TPU hardware.
std::vector<std::string> host_tpu_devices();

}  // namespace ddocker
