#include "http.hpp"

#include "json.hpp"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <sstream>
#include <stdexcept>
#include <thread>

namespace dhttp {

Server::Server(const std::string& host, int port) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) throw std::runtime_error("socket() failed");
  int one = 1;
  setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    throw std::runtime_error("bad bind host " + host);
  }
  if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    throw std::runtime_error("bind() failed on " + host + ":" + std::to_string(port));
  }
  if (listen(listen_fd_, 64) != 0) throw std::runtime_error("listen() failed");
  socklen_t len = sizeof(addr);
  getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
  port_ = ntohs(addr.sin_port);
}

Server::~Server() {
  if (listen_fd_ >= 0) close(listen_fd_);
}

void Server::handle(const std::string& method, const std::string& path, Handler h) {
  routes_[method + " " + path] = std::move(h);
}

void Server::stop() { stopping_ = true; }

void Server::serve_forever() {
  while (!stopping_) {
    pollfd pfd{listen_fd_, POLLIN, 0};
    int r = poll(&pfd, 1, 200);  // wake periodically to observe stop()
    if (r <= 0) continue;
    int fd = accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(&Server::handle_connection, this, fd).detach();
  }
}

static bool read_exact(int fd, std::string& buf, size_t want) {
  char tmp[8192];
  while (buf.size() < want) {
    ssize_t n = recv(fd, tmp, std::min(sizeof(tmp), want - buf.size()), 0);
    if (n <= 0) return false;
    buf.append(tmp, static_cast<size_t>(n));
  }
  return true;
}

static std::string url_decode(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '%' && i + 2 < s.size() && isxdigit(static_cast<unsigned char>(s[i + 1])) &&
        isxdigit(static_cast<unsigned char>(s[i + 2]))) {
      out += static_cast<char>(std::stoi(s.substr(i + 1, 2), nullptr, 16));
      i += 2;
    } else if (s[i] == '+') {
      out += ' ';
    } else {
      out += s[i];
    }
  }
  return out;
}

void Server::handle_connection(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::string buf;
  char tmp[8192];
  // Serve keep-alive requests until the peer closes or an error occurs.
  while (true) {
    size_t header_end;
    while ((header_end = buf.find("\r\n\r\n")) == std::string::npos) {
      ssize_t n = recv(fd, tmp, sizeof(tmp), 0);
      if (n <= 0) {
        close(fd);
        return;
      }
      buf.append(tmp, static_cast<size_t>(n));
      if (buf.size() > 64 * 1024 * 1024) {  // runaway header
        close(fd);
        return;
      }
    }

    Request req;
    {
      std::istringstream hs(buf.substr(0, header_end));
      std::string line;
      std::getline(hs, line);
      if (!line.empty() && line.back() == '\r') line.pop_back();
      std::istringstream rl(line);
      std::string target, version;
      rl >> req.method >> target >> version;
      auto qpos = target.find('?');
      req.path = qpos == std::string::npos ? target : target.substr(0, qpos);
      if (qpos != std::string::npos) {
        std::string qs = target.substr(qpos + 1);
        size_t start = 0;
        while (start < qs.size()) {
          size_t amp = qs.find('&', start);
          std::string pair = qs.substr(start, amp == std::string::npos ? amp : amp - start);
          size_t eq = pair.find('=');
          if (eq != std::string::npos) {
            req.query[url_decode(pair.substr(0, eq))] = url_decode(pair.substr(eq + 1));
          }
          if (amp == std::string::npos) break;
          start = amp + 1;
        }
      }
      while (std::getline(hs, line)) {
        if (!line.empty() && line.back() == '\r') line.pop_back();
        auto colon = line.find(':');
        if (colon == std::string::npos) continue;
        std::string key = line.substr(0, colon);
        for (auto& c : key) c = static_cast<char>(tolower(c));
        size_t vstart = line.find_first_not_of(' ', colon + 1);
        req.headers[key] = vstart == std::string::npos ? "" : line.substr(vstart);
      }
    }

    size_t content_length = 0;
    auto cl = req.headers.find("content-length");
    if (cl != req.headers.end()) {
      // A malformed or absurd Content-Length must not take the agent down.
      try {
        content_length = std::stoul(cl->second);
      } catch (const std::exception&) {
        close(fd);
        return;
      }
      if (content_length > 1024ull * 1024 * 1024) {
        close(fd);
        return;
      }
    }
    std::string rest = buf.substr(header_end + 4);
    if (!read_exact(fd, rest, content_length)) {
      close(fd);
      return;
    }
    req.body = rest.substr(0, content_length);
    buf = rest.substr(content_length);  // pipelined next request, if any

    Response resp;
    auto it = routes_.find(req.method + " " + req.path);
    if (it == routes_.end()) {
      resp.status = 404;
      resp.body = "{\"error\":\"not found\"}";
    } else {
      try {
        resp = it->second(req);
      } catch (const std::exception& e) {
        resp.status = 500;
        resp.body = dj::Json::object().set("error", e.what()).dump();
      }
    }

    std::ostringstream out;
    out << "HTTP/1.1 " << resp.status << (resp.status == 200 ? " OK" : " ERR") << "\r\n"
        << "Content-Type: " << resp.content_type << "\r\n"
        << "Content-Length: " << resp.body.size() << "\r\n"
        << "Connection: keep-alive\r\n\r\n"
        << resp.body;
    std::string data = out.str();
    size_t sent = 0;
    while (sent < data.size()) {
      ssize_t n = send(fd, data.data() + sent, data.size() - sent, MSG_NOSIGNAL);
      if (n <= 0) {
        close(fd);
        return;
      }
      sent += static_cast<size_t>(n);
    }
  }
}

}  // namespace dhttp
