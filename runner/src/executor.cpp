#include "executor.hpp"

#include <cerrno>
#include <fcntl.h>
#include <poll.h>
#include <pty.h>
#include <sys/ioctl.h>
#include <signal.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>
#include <ctime>
#include <fstream>
#include <sstream>
#include <vector>

namespace drunner {

static std::string iso_now() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  tm tmv;
  gmtime_r(&ts.tv_sec, &tmv);
  char buf[64];
  size_t n = strftime(buf, sizeof(buf), "%Y-%m-%dT%H:%M:%S", &tmv);
  snprintf(buf + n, sizeof(buf) - n, ".%06ld+00:00", ts.tv_nsec / 1000);
  return buf;
}

Executor::Executor(std::string base_dir) : base_dir_(std::move(base_dir)) {
  mkdir(base_dir_.c_str(), 0755);
}

Executor::~Executor() {
  stop_requested_ = true;
  pid_t pid = child_pid_.load();
  if (pid > 0) kill(-pid, SIGKILL);
  if (worker_.joinable()) worker_.join();
}

dj::Json Executor::health() const {
  std::lock_guard<std::mutex> lk(mu_);
  dj::Json out = dj::Json::object();
  out.set("status", "ok");
  out.set("state", current_state_);
  out.set("service", "dstack-tpu-runner");
  return out;
}

static bool state_is_terminal(const std::string& s) {
  return s == "done" || s == "failed" || s == "terminated" || s == "aborted";
}

dj::Json Executor::submit(const dj::Json& body) {
  std::lock_guard<std::mutex> lk(mu_);
  // While a started job is live (anywhere between run() and its terminal state) the
  // spec MUST NOT be mutated: exec_thread reads it without the lock. A retried
  // submit of the same job (the control plane retries when a submit/run response is
  // lost) is answered idempotently; a different job is a real conflict.
  if (job_started_ && !state_is_terminal(current_state_)) {
    if (body["job_spec"]["job_name"].as_string() == job_spec_["job_name"].as_string()) {
      return dj::Json::object();
    }
    throw std::runtime_error("a different job is already running");
  }
  job_spec_ = body["job_spec"];
  cluster_info_ = body["cluster_info"];
  secrets_ = body["secrets"];
  has_job_ = true;
  job_started_ = false;
  stop_requested_ = false;
  abort_requested_ = false;
  code_path_.clear();
  current_state_ = "submitted";
  return dj::Json::object();
}

dj::Json Executor::upload_code(const std::string& bytes) {
  std::lock_guard<std::mutex> lk(mu_);
  if (!has_job_) throw std::runtime_error("no job submitted");
  code_path_ = base_dir_ + "/code.tar.gz";
  std::ofstream f(code_path_, std::ios::binary | std::ios::trunc);
  f.write(bytes.data(), static_cast<std::streamsize>(bytes.size()));
  if (!f.good()) throw std::runtime_error("failed to write code archive");
  return dj::Json::object();
}

dj::Json Executor::run() {
  // Reap a previous worker OUTSIDE the lock: the fresh worker's first action takes
  // mu_, so joining under mu_ could deadlock the whole agent.
  std::thread prev;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!has_job_) throw std::runtime_error("no job submitted");
    if (job_started_) return dj::Json::object();  // idempotent re-run
    job_started_ = true;
    prev = std::move(worker_);
  }
  if (prev.joinable()) prev.join();
  {
    std::lock_guard<std::mutex> lk(mu_);
    ++job_generation_;
    worker_ = std::thread(&Executor::exec_thread, this);
  }
  return dj::Json::object();
}

dj::Json Executor::pull(int64_t offset) {
  std::lock_guard<std::mutex> lk(mu_);
  dj::Json states = dj::Json::array();
  dj::Json logs = dj::Json::array();
  int64_t max_seq = offset;
  // seq is strictly monotonic: binary-search the resume point instead of scanning the
  // whole window, and cap the page so a chatty job can't blow the client's timeout.
  const size_t kMaxEvents = 5000;
  auto it = std::lower_bound(
      events_.begin(), events_.end(), offset,
      [](const Event& ev, int64_t off) { return ev.seq <= off; });
  bool has_more = false;
  size_t taken = 0;
  for (; it != events_.end(); ++it) {
    const Event& ev = *it;
    if (++taken > kMaxEvents) {
      has_more = true;
      break;
    }
    if (ev.is_state) {
      dj::Json s = dj::Json::object();
      s.set("state", ev.state);
      s.set("exit_status", ev.exit_status);
      s.set("message", ev.message);
      s.set("ts", ev.ts);
      states.push_back(std::move(s));
    } else {
      dj::Json l = dj::Json::object();
      l.set("message", ev.message);
      l.set("ts", ev.ts);
      l.set("source", "stdout");
      logs.push_back(std::move(l));
    }
    if (ev.seq > max_seq) max_seq = ev.seq;
  }
  dj::Json out = dj::Json::object();
  out.set("job_states", std::move(states));
  out.set("logs", std::move(logs));
  out.set("offset", max_seq);
  out.set("has_more", has_more);
  out.set("state", current_state_);
  return out;
}

dj::Json Executor::stop(bool abort) {
  stop_requested_ = true;
  abort_requested_ = abort;
  pid_t pid = child_pid_.load();
  if (pid > 0) {
    kill(-pid, abort ? SIGKILL : SIGTERM);
  } else {
    std::lock_guard<std::mutex> lk(mu_);
    if (current_state_ == "submitted" || current_state_ == "idle") {
      current_state_ = "terminated";
    }
  }
  return dj::Json::object();
}

dj::Json Executor::metrics() const {
  pid_t pid = child_pid_.load();
  dj::Json out = dj::Json::object();
  int64_t cpu_micro = 0, rss_bytes = 0;
  if (pid > 0) {
    // utime+stime from /proc/<pid>/stat (fields 14,15, in clock ticks).
    std::ifstream stat("/proc/" + std::to_string(pid) + "/stat");
    std::string line;
    if (std::getline(stat, line)) {
      auto rparen = line.rfind(')');
      std::istringstream rest(line.substr(rparen + 2));
      std::string tok;
      long utime = 0, stime = 0;
      for (int i = 3; i <= 15 && rest >> tok; ++i) {
        if (i == 14) utime = atol(tok.c_str());
        if (i == 15) stime = atol(tok.c_str());
      }
      long ticks = sysconf(_SC_CLK_TCK);
      if (ticks > 0) cpu_micro = (utime + stime) * (1000000L / ticks);
    }
    std::ifstream statm("/proc/" + std::to_string(pid) + "/statm");
    long pages = 0, rss_pages = 0;
    if (statm >> pages >> rss_pages) rss_bytes = rss_pages * sysconf(_SC_PAGESIZE);
  }
  out.set("timestamp", iso_now());
  out.set("cpu_usage_micro", cpu_micro);
  out.set("memory_usage_bytes", rss_bytes);
  // TPU duty-cycle/HBM come from the shim's libtpu monitor on TPU hosts; the runner
  // reports null so the server knows to ask the shim (reference: DCGM relay split).
  out.set("tpu", dj::Json());
  return out;
}

void Executor::add_state(const std::string& state, int exit_status, const std::string& msg) {
  std::lock_guard<std::mutex> lk(mu_);
  current_state_ = state;
  events_.push_back(Event{next_seq_++, true, state, exit_status, msg, iso_now()});
  trim_events_locked();
}

void Executor::add_log(const std::string& line) {
  std::lock_guard<std::mutex> lk(mu_);
  events_.push_back(Event{next_seq_++, false, "", 0, line, iso_now()});
  trim_events_locked();
}

void Executor::trim_events_locked() {
  // Bound memory; seq numbers stay monotonic so already-handed-out offsets survive.
  const size_t kMax = 200000;
  while (events_.size() > kMax) events_.pop_front();
}

std::string Executor::extract_code() {
  std::string repo_dir = base_dir_ + "/repo";
  mkdir(repo_dir.c_str(), 0755);
  if (!code_path_.empty()) {
    std::string cmd = "tar -xzf '" + code_path_ + "' -C '" + repo_dir + "' 2>/dev/null";
    if (system(cmd.c_str()) != 0) {
      add_log("warning: failed to extract code archive\n");
    }
  }
  return repo_dir;
}

// Flat env from the submitted cluster_info (the TPU cluster contract; parity:
// reference executor.go:262-274 but JAX/MegaScale instead of MPI/NCCL).
static std::vector<std::string> cluster_env(const dj::Json& ci) {
  std::vector<std::string> env;
  auto add = [&env](const std::string& k, const std::string& v) { env.push_back(k + "=" + v); };
  if (!ci.is_object()) return env;
  add("DSTACK_NODE_RANK", std::to_string(ci["node_rank"].as_int()));
  add("DSTACK_NODES_NUM", std::to_string(ci["nodes_num"].as_int(1)));
  add("DSTACK_MASTER_NODE_IP", ci["master_node_ip"].as_string());
  std::string ips;
  for (const auto& ip : ci["node_ips"].as_array()) {
    if (!ips.empty()) ips += "\n";
    ips += ip.as_string();
  }
  add("DSTACK_NODES_IPS", ips);
  add("TPU_WORKER_ID", std::to_string(ci["tpu_worker_id"].as_int()));
  std::string hostnames;
  for (const auto& h : ci["tpu_worker_hostnames"].as_array()) {
    if (!hostnames.empty()) hostnames += ",";
    hostnames += h.as_string();
  }
  add("TPU_WORKER_HOSTNAMES", hostnames);
  if (!ci["tpu_topology"].is_null()) add("TPU_TOPOLOGY", ci["tpu_topology"].as_string());
  if (!ci["tpu_generation"].is_null())
    add("DSTACK_TPU_GENERATION", ci["tpu_generation"].as_string());
  if (ci["chips_per_host"].as_int() > 0)
    add("DSTACK_TPU_CHIPS_PER_HOST", std::to_string(ci["chips_per_host"].as_int()));
  if (!ci["coordinator_address"].is_null())
    add("DSTACK_JAX_COORDINATOR", ci["coordinator_address"].as_string());
  int64_t num_slices = ci["num_slices"].as_int(1);
  if (num_slices > 1) {
    add("MEGASCALE_NUM_SLICES", std::to_string(num_slices));
    add("MEGASCALE_SLICE_ID", std::to_string(ci["slice_id"].as_int()));
    if (!ci["megascale_coordinator_address"].is_null())
      add("MEGASCALE_COORDINATOR_ADDRESS", ci["megascale_coordinator_address"].as_string());
  }
  return env;
}

void Executor::exec_thread() {
  uint64_t generation = job_generation_.load();
  if (stop_requested_) {  // stopped before we ever started
    add_state(abort_requested_ ? "aborted" : "terminated", -1, "stopped before start");
    return;
  }
  add_state("running");
  std::string repo_dir = extract_code();

  // Join commands into one shell script (reference joins with && semantics via sh -c;
  // we use strict mode so any failing command fails the job).
  std::string script = "set -e\n";
  for (const auto& cmd : job_spec_["commands"].as_array()) {
    script += cmd.as_string();
    script += "\n";
  }

  std::string workdir = repo_dir;
  if (!job_spec_["working_dir"].is_null() && !job_spec_["working_dir"].as_string().empty()) {
    workdir = job_spec_["working_dir"].as_string();
    if (workdir[0] != '/') workdir = repo_dir + "/" + workdir;
  }

  std::vector<std::string> env_strings;
  for (char** e = environ; *e; ++e) env_strings.push_back(*e);
  for (const auto& kv : job_spec_["env"].as_object()) {
    env_strings.push_back(kv.first + "=" + kv.second.as_string());
  }
  for (const auto& kv : secrets_.as_object()) {
    env_strings.push_back(kv.first + "=" + kv.second.as_string());
  }
  for (auto& kv : cluster_env(cluster_info_)) env_strings.push_back(kv);
  env_strings.push_back("DSTACK_REPO_DIR=" + repo_dir);

  // Manual openpty+fork instead of forkpty: glibc's forkpty child _exit(1)s when
  // TIOCSCTTY fails, which happens when the kernel recycles a pty index that is still
  // the controlling tty of a lingering older session (intermittent silent exit-1 under
  // job churn). We don't need job control -- a failed TIOCSCTTY is fine.
  int master_fd, slave_fd;
  if (openpty(&master_fd, &slave_fd, nullptr, nullptr, nullptr) != 0) {
    add_state("failed", -1, "openpty failed");
    return;
  }
  pid_t pid = fork();
  if (pid < 0) {
    close(master_fd);
    close(slave_fd);
    add_state("failed", -1, "fork failed");
    return;
  }
  if (pid == 0) {
    // Child: new session + own process group so stop() can signal the whole tree.
    setsid();
    (void)ioctl(slave_fd, TIOCSCTTY, 0);  // best-effort; see above
    dup2(slave_fd, 0);
    dup2(slave_fd, 1);
    dup2(slave_fd, 2);
    if (slave_fd > 2) close(slave_fd);
    close(master_fd);
    if (chdir(workdir.c_str()) != 0) {
      int rc = chdir("/");
      (void)rc;
    }
    std::vector<char*> envp;
    for (auto& s : env_strings) envp.push_back(const_cast<char*>(s.c_str()));
    envp.push_back(nullptr);
    execle("/bin/sh", "sh", "-c", script.c_str(), static_cast<char*>(nullptr), envp.data());
    _exit(127);
  }
  close(slave_fd);
  setpgid(pid, pid);
  child_pid_ = pid;
  // Close the stop() race: a stop that arrived while we were extracting code (before
  // child_pid_ was set) found nothing to signal — honor it now.
  if (stop_requested_) kill(-pid, abort_requested_ ? SIGKILL : SIGTERM);

  // Parent: stream pty output into the log buffer, line-buffered.
  std::string partial;
  char buf[4096];
  while (true) {
    pollfd pfd{master_fd, POLLIN, 0};
    int pr = poll(&pfd, 1, 200);
    if (pr > 0) {
      ssize_t n = read(master_fd, buf, sizeof(buf));
      if (n <= 0) break;
      partial.append(buf, static_cast<size_t>(n));
      size_t nl;
      while ((nl = partial.find('\n')) != std::string::npos) {
        add_log(partial.substr(0, nl + 1));
        partial.erase(0, nl + 1);
      }
    }
    int status;
    pid_t done = waitpid(pid, &status, WNOHANG);
    if (done == pid) {
      // Drain remaining pty output (non-blocking).
      fcntl(master_fd, F_SETFL, O_NONBLOCK);
      ssize_t n;
      while ((n = read(master_fd, buf, sizeof(buf))) > 0) partial.append(buf, static_cast<size_t>(n));
      if (!partial.empty()) add_log(partial);
      close(master_fd);
      child_pid_ = 0;
      if (job_generation_.load() != generation) return;  // superseded
      if (stop_requested_) {
        add_state(abort_requested_ ? "aborted" : "terminated",
                  WIFEXITED(status) ? WEXITSTATUS(status) : -1, "stopped by request");
      } else if (WIFEXITED(status) && WEXITSTATUS(status) == 0) {
        add_state("done", 0);
      } else {
        int code = WIFEXITED(status) ? WEXITSTATUS(status) : 128 + WTERMSIG(status);
        add_state("failed", code, "exit status " + std::to_string(code));
      }
      return;
    }
  }
  // Pty EOF before exit; wait for the child.
  int status;
  waitpid(pid, &status, 0);
  if (!partial.empty()) add_log(partial);
  close(master_fd);
  child_pid_ = 0;
  if (stop_requested_) {
    add_state(abort_requested_ ? "aborted" : "terminated",
              WIFEXITED(status) ? WEXITSTATUS(status) : -1, "stopped by request");
  } else if (WIFEXITED(status) && WEXITSTATUS(status) == 0) {
    add_state("done", 0);
  } else {
    int code = WIFEXITED(status) ? WEXITSTATUS(status) : 128 + WTERMSIG(status);
    add_state("failed", code, "exit status " + std::to_string(code));
  }
}

}  // namespace drunner
