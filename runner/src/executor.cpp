#include "executor.hpp"

#include "docker.hpp"
#include "tpu_metrics.hpp"

#include <cerrno>
#include <fcntl.h>
#include <poll.h>
#include <pty.h>
#include <sys/ioctl.h>
#include <signal.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <map>
#include <ctime>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <vector>

namespace drunner {

static std::string iso_now() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  tm tmv;
  gmtime_r(&ts.tv_sec, &tmv);
  char buf[64];
  size_t n = strftime(buf, sizeof(buf), "%Y-%m-%dT%H:%M:%S", &tmv);
  snprintf(buf + n, sizeof(buf) - n, ".%06ld+00:00", ts.tv_nsec / 1000);
  return buf;
}

// Run a command via fork/execvp with an argv — no shell, so spec-derived strings
// (clone URLs, volume names, device paths) can never be interpreted as shell
// syntax. Combined stdout+stderr is captured into *output when non-null.
// Returns the exit code, or -1 on fork/exec/signal failure.
int run_argv(const std::vector<std::string>& argv, std::string* output) {
  if (argv.empty()) return -1;
  int fds[2];
  if (pipe(fds) != 0) return -1;
  pid_t pid = fork();
  if (pid < 0) {
    close(fds[0]);
    close(fds[1]);
    return -1;
  }
  if (pid == 0) {
    close(fds[0]);
    dup2(fds[1], 1);
    dup2(fds[1], 2);
    if (fds[1] > 2) close(fds[1]);
    std::vector<char*> cargv;
    cargv.reserve(argv.size() + 1);
    for (const auto& a : argv) cargv.push_back(const_cast<char*>(a.c_str()));
    cargv.push_back(nullptr);
    execvp(cargv[0], cargv.data());
    _exit(127);
  }
  close(fds[1]);
  char buf[4096];
  ssize_t n;
  while ((n = read(fds[0], buf, sizeof(buf))) > 0) {
    if (output) output->append(buf, static_cast<size_t>(n));
  }
  close(fds[0]);
  int status = 0;
  if (waitpid(pid, &status, 0) < 0) return -1;
  if (WIFEXITED(status)) return WEXITSTATUS(status);
  return -1;
}

Executor::Executor(std::string base_dir, std::string docker_mode, std::string docker_socket)
    : base_dir_(std::move(base_dir)),
      docker_mode_(std::move(docker_mode)),
      docker_socket_(docker_socket.empty() ? ddocker::DockerClient::default_socket()
                                           : std::move(docker_socket)) {
  mkdir(base_dir_.c_str(), 0755);
  // World-writable telemetry dir: container jobs may run as a non-root user
  // but must still be able to append their sidecar (and profile artifacts).
  mkdir(telemetry_dir().c_str(), 0777);
  chmod(telemetry_dir().c_str(), 0777);
}

Executor::~Executor() {
  stop_requested_ = true;
  pid_t pid = child_pid_.load();
  if (pid > 0) kill(-pid, SIGKILL);
  if (worker_.joinable()) worker_.join();
}

dj::Json Executor::health() const {
  std::lock_guard<std::mutex> lk(mu_);
  dj::Json out = dj::Json::object();
  out.set("status", "ok");
  out.set("state", current_state_);
  out.set("service", "dstack-tpu-runner");
  return out;
}

static bool state_is_terminal(const std::string& s) {
  return s == "done" || s == "failed" || s == "terminated" || s == "aborted";
}

dj::Json Executor::submit(const dj::Json& body) {
  std::lock_guard<std::mutex> lk(mu_);
  // While a started job is live (anywhere between run() and its terminal state) the
  // spec MUST NOT be mutated: exec_thread reads it without the lock. A retried
  // submit of the same job (the control plane retries when a submit/run response is
  // lost) is answered idempotently; a different job is a real conflict.
  if (job_started_ && !state_is_terminal(current_state_)) {
    if (body["job_spec"]["job_name"].as_string() == job_spec_["job_name"].as_string()) {
      return dj::Json::object();
    }
    throw std::runtime_error("a different job is already running");
  }
  job_spec_ = body["job_spec"];
  cluster_info_ = body["cluster_info"];
  repo_data_ = body["run_spec"]["repo_data"];
  secrets_ = body["secrets"];
  has_job_ = true;
  job_started_ = false;
  stop_requested_ = false;
  abort_requested_ = false;
  code_path_.clear();
  current_state_ = "submitted";
  // Fresh job, fresh telemetry stream: the previous job's sidecar (and any
  // stale profile request) must not leak into the new job's samples.
  telemetry_offset_ = 0;
  unlink(telemetry_file().c_str());
  unlink((telemetry_file() + ".ctl").c_str());
  return dj::Json::object();
}

dj::Json Executor::upload_code(const std::string& bytes) {
  std::lock_guard<std::mutex> lk(mu_);
  if (!has_job_) throw std::runtime_error("no job submitted");
  code_path_ = base_dir_ + "/code.tar.gz";
  std::ofstream f(code_path_, std::ios::binary | std::ios::trunc);
  f.write(bytes.data(), static_cast<std::streamsize>(bytes.size()));
  if (!f.good()) throw std::runtime_error("failed to write code archive");
  return dj::Json::object();
}

dj::Json Executor::run() {
  // Reap a previous worker OUTSIDE the lock: the fresh worker's first action takes
  // mu_, so joining under mu_ could deadlock the whole agent.
  std::thread prev;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!has_job_) throw std::runtime_error("no job submitted");
    if (job_started_) return dj::Json::object();  // idempotent re-run
    job_started_ = true;
    prev = std::move(worker_);
  }
  if (prev.joinable()) prev.join();
  {
    std::lock_guard<std::mutex> lk(mu_);
    ++job_generation_;
    worker_ = std::thread(&Executor::exec_thread, this);
  }
  return dj::Json::object();
}

dj::Json Executor::pull(int64_t offset) {
  std::lock_guard<std::mutex> lk(mu_);
  dj::Json states = dj::Json::array();
  dj::Json logs = dj::Json::array();
  int64_t max_seq = offset;
  // seq is strictly monotonic: binary-search the resume point instead of scanning the
  // whole window, and cap the page so a chatty job can't blow the client's timeout.
  const size_t kMaxEvents = 5000;
  auto it = std::lower_bound(
      events_.begin(), events_.end(), offset,
      [](const Event& ev, int64_t off) { return ev.seq <= off; });
  bool has_more = false;
  size_t taken = 0;
  for (; it != events_.end(); ++it) {
    const Event& ev = *it;
    if (++taken > kMaxEvents) {
      has_more = true;
      break;
    }
    if (ev.is_state) {
      dj::Json s = dj::Json::object();
      s.set("state", ev.state);
      s.set("exit_status", ev.exit_status);
      s.set("message", ev.message);
      s.set("ts", ev.ts);
      states.push_back(std::move(s));
    } else {
      dj::Json l = dj::Json::object();
      l.set("message", ev.message);
      l.set("ts", ev.ts);
      l.set("source", "stdout");
      logs.push_back(std::move(l));
    }
    if (ev.seq > max_seq) max_seq = ev.seq;
  }
  dj::Json out = dj::Json::object();
  out.set("job_states", std::move(states));
  out.set("logs", std::move(logs));
  out.set("offset", max_seq);
  out.set("has_more", has_more);
  out.set("state", current_state_);
  return out;
}

dj::Json Executor::stop(bool abort) {
  stop_requested_ = true;
  abort_requested_ = abort;
  std::string cid;
  {
    std::lock_guard<std::mutex> lk(mu_);
    cid = container_id_;
  }
  pid_t pid = child_pid_.load();
  if (!cid.empty()) {
    try {
      ddocker::DockerClient(docker_socket_).kill_container(cid, abort ? "SIGKILL" : "SIGTERM");
    } catch (const ddocker::DockerError&) {
      // exec_container's wait/stream path will surface the outcome either way.
    }
  } else if (pid > 0) {
    kill(-pid, abort ? SIGKILL : SIGTERM);
  } else {
    std::lock_guard<std::mutex> lk(mu_);
    if (current_state_ == "submitted" || current_state_ == "idle") {
      current_state_ = "terminated";
    }
  }
  return dj::Json::object();
}

dj::Json Executor::metrics() {
  pid_t pid = child_pid_.load();
  dj::Json out = dj::Json::object();
  int64_t cpu_micro = 0, rss_bytes = 0;
  std::string cid;
  {
    std::lock_guard<std::mutex> lk(mu_);
    cid = container_id_;
  }
  if (!cid.empty()) {
    // Container job: sample the engine's stats endpoint (ref relays DCGM/cAdvisor
    // equivalents; CPU total is reported in ns).
    try {
      dj::Json st = ddocker::DockerClient(docker_socket_).container_stats(cid);
      cpu_micro = st["cpu_stats"]["cpu_usage"]["total_usage"].as_int() / 1000;
      rss_bytes = st["memory_stats"]["usage"].as_int();
    } catch (const ddocker::DockerError&) {
    }
  } else if (pid > 0) {
    // utime+stime from /proc/<pid>/stat (fields 14,15, in clock ticks).
    std::ifstream stat("/proc/" + std::to_string(pid) + "/stat");
    std::string line;
    if (std::getline(stat, line)) {
      auto rparen = line.rfind(')');
      std::istringstream rest(line.substr(rparen + 2));
      std::string tok;
      long utime = 0, stime = 0;
      for (int i = 3; i <= 15 && rest >> tok; ++i) {
        if (i == 14) utime = atol(tok.c_str());
        if (i == 15) stime = atol(tok.c_str());
      }
      long ticks = sysconf(_SC_CLK_TCK);
      if (ticks > 0) cpu_micro = (utime + stime) * (1000000L / ticks);
    }
    std::ifstream statm("/proc/" + std::to_string(pid) + "/statm");
    long pages = 0, rss_pages = 0;
    if (statm >> pages >> rss_pages) rss_bytes = rss_pages * sysconf(_SC_PAGESIZE);
  }
  out.set("timestamp", iso_now());
  out.set("cpu_usage_micro", cpu_micro);
  out.set("memory_usage_bytes", rss_bytes);
  // TPU duty-cycle/HBM scraped from the runtime metrics endpoint when
  // DSTACK_TPU_RUNTIME_METRICS_URL is set (the DCGM-exporter analog); null
  // otherwise (src/tpu_metrics.cpp). Scraped ONCE per sample, outside mu_ —
  // the host point below reuses it (a slow/unreachable endpoint must not
  // stall submit/stop behind the lock, nor double the scrape load).
  dj::Json tpu = dtpu::sample_tpu_metrics();
  // Workload telemetry points appended by the job's emitter since the last
  // sample ride the same response (at-most-once: the offset advances on read),
  // plus one agent-side host hardware sample per pull — the same stream, so
  // per-host cpu/mem/net land in workload_metrics_points next to the step
  // points they explain (gang-health per-host attribution).
  {
    std::lock_guard<std::mutex> lk(mu_);
    dj::Json workload = tail_telemetry_locked();
    workload.push_back(host_sample_locked(tpu));
    out.set("workload", std::move(workload));
  }
  out.set("tpu", std::move(tpu));
  return out;
}

static double monotonic_seconds() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + static_cast<double>(ts.tv_nsec) / 1e9;
}

dj::Json Executor::host_sample_locked(const dj::Json& tpu) {
  dj::Json p = dj::Json::object();
  p.set("ts", iso_now());
  p.set("kind", "host");
  char hn[256] = {0};
  if (gethostname(hn, sizeof(hn) - 1) == 0) p.set("host", std::string(hn));

  // CPU: /proc/stat aggregate line. busy = delta(total) - delta(idle+iowait).
  int64_t total = 0, idle_all = 0;
  {
    std::ifstream stat("/proc/stat");
    std::string label;
    if (stat >> label && label == "cpu") {
      int64_t v;
      for (int i = 0; i < 10 && (stat >> v); ++i) {
        total += v;
        if (i == 3 || i == 4) idle_all += v;  // idle + iowait
      }
    }
  }
  if (host_cpu_total_ > 0 && total > host_cpu_total_) {
    double window = static_cast<double>(total - host_cpu_total_);
    double busy = window - static_cast<double>(idle_all - host_cpu_idle_);
    double pct = 100.0 * busy / window;
    if (pct < 0) pct = 0;
    if (pct > 100) pct = 100;
    p.set("cpu_percent", pct);
  }
  host_cpu_total_ = total;
  host_cpu_idle_ = idle_all;

  // Memory: MemTotal - MemAvailable (kB) — what the kernel says is actually
  // committed, unlike free(1)'s cache-inflated "used".
  {
    std::ifstream mem("/proc/meminfo");
    std::string line;
    int64_t total_kb = 0, avail_kb = 0;
    while (std::getline(mem, line)) {
      if (line.rfind("MemTotal:", 0) == 0) total_kb = atoll(line.c_str() + 9);
      else if (line.rfind("MemAvailable:", 0) == 0) avail_kb = atoll(line.c_str() + 13);
      if (total_kb && avail_kb) break;
    }
    if (total_kb > 0) {
      p.set("mem_total_bytes", total_kb * 1024);
      p.set("mem_used_bytes", (total_kb - (avail_kb > 0 ? avail_kb : 0)) * 1024);
    }
  }

  // Network: sum rx/tx bytes over non-loopback interfaces; rates via delta.
  int64_t rx = 0, tx = 0;
  {
    std::ifstream net("/proc/net/dev");
    std::string line;
    while (std::getline(net, line)) {
      size_t colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string ifname = line.substr(0, colon);
      ifname.erase(0, ifname.find_first_not_of(' '));
      if (ifname == "lo") continue;
      std::istringstream fields(line.substr(colon + 1));
      int64_t v, if_rx = 0, if_tx = 0;
      for (int i = 0; i < 16 && (fields >> v); ++i) {
        if (i == 0) if_rx = v;   // rx bytes
        if (i == 8) if_tx = v;   // tx bytes
      }
      rx += if_rx;
      tx += if_tx;
    }
  }
  double now_mono = monotonic_seconds();
  if (host_sample_at_ > 0 && now_mono > host_sample_at_ && rx >= host_net_rx_ &&
      tx >= host_net_tx_) {
    double dt = now_mono - host_sample_at_;
    p.set("net_rx_bytes_per_s", static_cast<double>(rx - host_net_rx_) / dt);
    p.set("net_tx_bytes_per_s", static_cast<double>(tx - host_net_tx_) / dt);
  }
  host_net_rx_ = rx;
  host_net_tx_ = tx;
  host_sample_at_ = now_mono;

  // TPU runtime metrics: the sample metrics() already took (null when the
  // endpoint is absent/unreachable).
  if (!tpu.is_null()) p.set("tpu", tpu);
  return p;
}

dj::Json Executor::tail_telemetry_locked() {
  dj::Json points = dj::Json::array();
  std::ifstream f(telemetry_file(), std::ios::binary);
  if (!f) return points;
  f.seekg(0, std::ios::end);
  int64_t size = f.tellg();
  if (size < telemetry_offset_) telemetry_offset_ = 0;  // truncated / replaced
  if (size <= telemetry_offset_) return points;
  // Bound the per-sample payload: a chatty emitter is drained over successive
  // samples instead of blowing one response (the offset only advances past
  // what was actually taken).
  const int64_t kMaxBytes = 256 * 1024;
  const size_t kMaxPoints = 1000;
  int64_t want = std::min<int64_t>(size - telemetry_offset_, kMaxBytes);
  std::string chunk(static_cast<size_t>(want), '\0');
  f.seekg(telemetry_offset_);
  f.read(&chunk[0], want);
  chunk.resize(static_cast<size_t>(f.gcount()));
  // Only complete lines: a line still being appended must wait for the next
  // sample, or its tail would parse as garbage AND be skipped forever.
  size_t last_nl = chunk.rfind('\n');
  if (last_nl == std::string::npos) {
    // A full window with no newline is a single line larger than kMaxBytes
    // (a job writing junk to the sidecar path): it can never complete inside
    // the window, so skip past it — leaving the offset parked would re-read
    // the same window forever and silently drop ALL later telemetry.
    if (static_cast<int64_t>(chunk.size()) >= kMaxBytes) {
      telemetry_offset_ += static_cast<int64_t>(chunk.size());
    }
    return points;
  }
  chunk.resize(last_nl + 1);
  size_t start = 0, consumed = 0, taken = 0;
  while (start < chunk.size() && taken < kMaxPoints) {
    size_t nl = chunk.find('\n', start);
    if (nl == std::string::npos) break;
    std::string line = chunk.substr(start, nl - start);
    consumed = nl + 1;
    start = nl + 1;
    if (!line.empty() && line[0] != '\r') {
      try {
        points.push_back(dj::Json::parse(line));
        ++taken;
      } catch (const std::exception&) {
        // Corrupt line (partial write across a crash): skip it, keep the rest.
      }
    }
  }
  telemetry_offset_ += static_cast<int64_t>(consumed);
  return points;
}

dj::Json Executor::profile(const dj::Json& body) {
  double seconds = body["seconds"].as_number(5.0);
  if (seconds <= 0) seconds = 5.0;
  if (seconds > 600) seconds = 600;
  int64_t id;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (current_state_ != "running") {
      throw std::runtime_error("no running job to profile");
    }
    id = ++profile_seq_;
  }
  // Atomic control-file write (tmp + rename): the emitter polls this path and
  // must never read a half-written command.
  std::string ctl = telemetry_file() + ".ctl";
  std::string tmp = ctl + ".tmp";
  {
    dj::Json cmd = dj::Json::object();
    cmd.set("id", id);
    cmd.set("cmd", "profile");
    cmd.set("seconds", seconds);
    std::ofstream f(tmp, std::ios::trunc);
    f << cmd.dump();
    if (!f.good()) throw std::runtime_error("failed to write profiler control file");
  }
  if (rename(tmp.c_str(), ctl.c_str()) != 0) {
    throw std::runtime_error("failed to publish profiler control file");
  }
  // The artifact dir as seen from THIS host (container jobs see it under the
  // telemetry bind mount, but the path below is where the operator finds it).
  dj::Json out = dj::Json::object();
  out.set("id", id);
  out.set("seconds", seconds);
  out.set("status", "requested");
  out.set("artifact_dir", telemetry_dir() + "/profile/" + std::to_string(id));
  return out;
}

void Executor::add_state(const std::string& state, int exit_status, const std::string& msg) {
  std::lock_guard<std::mutex> lk(mu_);
  current_state_ = state;
  events_.push_back(Event{next_seq_++, true, state, exit_status, msg, iso_now()});
  trim_events_locked();
}

void Executor::add_log(const std::string& line) {
  std::lock_guard<std::mutex> lk(mu_);
  events_.push_back(Event{next_seq_++, false, "", 0, line, iso_now()});
  trim_events_locked();
}

void Executor::trim_events_locked() {
  // Bound memory; seq numbers stay monotonic so already-handed-out offsets survive.
  const size_t kMax = 200000;
  while (events_.size() > kMax) events_.pop_front();
}

std::string Executor::extract_code() {
  std::string repo_dir = base_dir_ + "/repo";
  // Git mode (reference executor/repo.go + repo/{manager,diff}.go): clone the
  // named remote, check out the pinned commit, apply the uploaded working-tree
  // diff. The blob channel carries the DIFF instead of a tarball, so huge repos
  // never hit the code-size cap.
  if (repo_data_["mode"].as_string() == "git" &&
      !repo_data_["clone_url"].as_string().empty()) {
    const std::string& url = repo_data_["clone_url"].as_string();
    const std::string& commit = repo_data_["commit"].as_string();
    std::string out;
    run_argv({"rm", "-rf", "--", repo_dir}, nullptr);
    // `--` stops git/rm from parsing a hostile URL or path as an option. A
    // revision sits BEFORE the `--`, so it cannot be protected that way — reject
    // option-shaped commits outright instead of letting git parse them.
    int rc;
    if (!commit.empty() && commit[0] == '-') {
      out = "invalid commit " + commit;
      rc = 1;
    } else {
      rc = run_argv({"git", "clone", "-q", "--", url, repo_dir}, &out);
      if (rc == 0 && !commit.empty()) {
        rc = run_argv({"git", "-C", repo_dir, "checkout", "-q", commit, "--"}, &out);
      }
    }
    if (rc == 0) {
      add_log("checked out " + url + (commit.empty() ? "" : " @ " + commit.substr(0, 12)) + "\n");
      if (!code_path_.empty()) {
        std::string apply_out;
        if (run_argv({"git", "-C", repo_dir, "apply", "--whitespace=nowarn", "--",
                      code_path_},
                     &apply_out) != 0) {
          add_log("warning: applying the working-tree diff failed: " + apply_out + "\n");
        }
      }
      return repo_dir;
    }
    add_log("warning: git clone/checkout failed (" + out +
            "); falling back to the code archive\n");
  }
  mkdir(repo_dir.c_str(), 0755);
  if (!code_path_.empty()) {
    if (run_argv({"tar", "-xzf", code_path_, "-C", repo_dir}, nullptr) != 0) {
      add_log("warning: failed to extract code archive\n");
    }
  }
  return repo_dir;
}

// Flat env from the submitted cluster_info (the TPU cluster contract; parity:
// reference executor.go:262-274 but JAX/MegaScale instead of MPI/NCCL).
static std::vector<std::string> cluster_env(const dj::Json& ci) {
  std::vector<std::string> env;
  auto add = [&env](const std::string& k, const std::string& v) { env.push_back(k + "=" + v); };
  if (!ci.is_object()) return env;
  add("DSTACK_NODE_RANK", std::to_string(ci["node_rank"].as_int()));
  add("DSTACK_NODES_NUM", std::to_string(ci["nodes_num"].as_int(1)));
  add("DSTACK_MASTER_NODE_IP", ci["master_node_ip"].as_string());
  std::string ips;
  for (const auto& ip : ci["node_ips"].as_array()) {
    if (!ips.empty()) ips += "\n";
    ips += ip.as_string();
  }
  add("DSTACK_NODES_IPS", ips);
  add("TPU_WORKER_ID", std::to_string(ci["tpu_worker_id"].as_int()));
  std::string hostnames;
  for (const auto& h : ci["tpu_worker_hostnames"].as_array()) {
    if (!hostnames.empty()) hostnames += ",";
    hostnames += h.as_string();
  }
  add("TPU_WORKER_HOSTNAMES", hostnames);
  if (!ci["tpu_topology"].is_null()) add("TPU_TOPOLOGY", ci["tpu_topology"].as_string());
  if (!ci["tpu_generation"].is_null())
    add("DSTACK_TPU_GENERATION", ci["tpu_generation"].as_string());
  if (ci["chips_per_host"].as_int() > 0)
    add("DSTACK_TPU_CHIPS_PER_HOST", std::to_string(ci["chips_per_host"].as_int()));
  if (!ci["coordinator_address"].is_null())
    add("DSTACK_JAX_COORDINATOR", ci["coordinator_address"].as_string());
  int64_t num_slices = ci["num_slices"].as_int(1);
  if (num_slices > 1) {
    add("MEGASCALE_NUM_SLICES", std::to_string(num_slices));
    add("MEGASCALE_SLICE_ID", std::to_string(ci["slice_id"].as_int()));
    if (!ci["megascale_coordinator_address"].is_null())
      add("MEGASCALE_COORDINATOR_ADDRESS", ci["megascale_coordinator_address"].as_string());
  }
  return env;
}

// Ready one volume on the host: format-if-empty + mount for block devices
// (reference shim/docker.go:542 formatAndMountVolume), symlink for host-dir
// volumes (local backend). Shell-free — every step is a fork/exec argv, so
// spec-derived names and device paths are never shell-parsed. Returns false
// with *err set when the volume cannot be readied; callers MUST fail the job
// (a missed mount would silently land the job's writes on the boot disk).
static bool prepare_volume(const dj::Json& v, const std::string& mount_path, std::string* err) {
  const std::string& dev = v["device"].as_string();
  const std::string& host_dir = v["host_dir"].as_string();
  if (!dev.empty()) {
    if (run_argv({"blkid", "--", dev}, nullptr) != 0) {
      std::string out;
      if (run_argv({"mkfs.ext4", "-q", "--", dev}, &out) != 0) {
        *err = "mkfs.ext4 " + dev + " failed: " + out;
        return false;
      }
    }
    run_argv({"mkdir", "-p", "--", mount_path}, nullptr);
    if (run_argv({"mountpoint", "-q", "--", mount_path}, nullptr) != 0) {
      std::string out;
      if (run_argv({"mount", "--", dev, mount_path}, &out) != 0) {
        *err = "mount " + dev + " on " + mount_path + " failed: " + out;
        return false;
      }
    }
    return true;
  }
  if (!host_dir.empty()) {
    std::string parent = mount_path;
    size_t slash = parent.find_last_of('/');
    if (slash != std::string::npos && slash > 0) {
      run_argv({"mkdir", "-p", "--", parent.substr(0, slash)}, nullptr);
    }
    // stat() follows symlinks: a dangling link from a recreated volume must be
    // re-pointed, not treated as already-prepared.
    struct stat st, lst;
    if (stat(mount_path.c_str(), &st) != 0) {
      if (lstat(mount_path.c_str(), &lst) == 0 && S_ISLNK(lst.st_mode)) {
        unlink(mount_path.c_str());
      }
      if (symlink(host_dir.c_str(), mount_path.c_str()) != 0) {
        *err = "symlink " + host_dir + " -> " + mount_path + ": " + strerror(errno);
        return false;
      }
    }
    return true;
  }
  return true;
}

std::string Executor::build_script() const {
  // Join commands into one shell script (reference joins with && semantics via sh -c;
  // we use strict mode so any failing command fails the job).
  std::string script = "set -e\n";
  for (const auto& cmd : job_spec_["commands"].as_array()) {
    script += cmd.as_string();
    script += "\n";
  }
  return script;
}

std::vector<std::string> Executor::job_env(const std::string& repo_dir,
                                           const std::string& telemetry_path) const {
  std::vector<std::string> env_strings;
  for (const auto& kv : job_spec_["env"].as_object()) {
    env_strings.push_back(kv.first + "=" + kv.second.as_string());
  }
  for (const auto& kv : secrets_.as_object()) {
    env_strings.push_back(kv.first + "=" + kv.second.as_string());
  }
  for (auto& kv : cluster_env(cluster_info_)) env_strings.push_back(kv);
  env_strings.push_back("DSTACK_REPO_DIR=" + repo_dir);
  // The workload->agent telemetry contract (workloads/telemetry.py): the
  // emitter appends JSONL here, the agent tails it into /api/metrics samples.
  if (!telemetry_path.empty()) {
    env_strings.push_back("DSTACK_TPU_TELEMETRY_PATH=" + telemetry_path);
  }
  return env_strings;
}

void Executor::finish(int code, const std::string& how) {
  if (stop_requested_) {
    add_state(abort_requested_ ? "aborted" : "terminated", code, "stopped by request");
  } else if (code == 0) {
    add_state("done", 0);
  } else {
    add_state("failed", code, how);
  }
}

void Executor::exec_thread() {
  uint64_t generation = job_generation_.load();
  if (stop_requested_) {  // stopped before we ever started
    add_state(abort_requested_ ? "aborted" : "terminated", -1, "stopped before start");
    return;
  }
  bool container = false;
  if (docker_mode_ == "always") {
    container = true;
  } else if (docker_mode_ == "auto" && !job_spec_["image_name"].as_string().empty()) {
    container = ddocker::DockerClient(docker_socket_).ping();
    if (!container) add_log("docker engine unreachable; running the job on the host\n");
  }
  if (container) {
    exec_container(generation);
  } else {
    exec_host(generation);
  }
}

void Executor::exec_container(uint64_t generation) {
  ddocker::DockerClient dc(docker_socket_);
  const std::string image = job_spec_["image_name"].as_string();
  const std::string job_name = job_spec_["job_name"].as_string();
  // The label value is the server's submission id when present (unique per retry,
  // so recovery can't resurrect a previous attempt's container); the container
  // NAME stays per-job so a retry's create replaces the old attempt via the 409
  // path below.
  std::string job_key = job_spec_["job_submission_id"].as_string();
  if (job_key.empty()) job_key = job_name;
  const std::string cname = "dstack-tpu-" + job_name;
  std::string cid;
  try {
    // Restart recovery: a previous agent life may have left this job's container
    // behind (running or exited); re-attach instead of double-running it. Queried
    // by label at exec time, not cached at startup — the engine may come up after
    // the agent (ref shim/docker.go:104 restoreStateFromContainers).
    bool recovered = false;
    dj::Json leftovers = dc.list_containers("dstack-tpu.job=" + job_key);
    if (!leftovers.as_array().empty()) {
      cid = leftovers.as_array()[0]["Id"].as_string();
      recovered = true;
    }
    if (recovered) {
      add_log("re-attaching to container " + cid.substr(0, 12) + " after agent restart\n");
    } else {
      if (!dc.image_exists(image)) {
        add_state("pulling", 0, image);
        std::string auth = ddocker::encode_registry_auth(
            job_spec_["registry_auth"]["username"].as_string(),
            job_spec_["registry_auth"]["password"].as_string());
        dc.pull_image(
            image, auth, [this](const std::string& s) { add_log(s + "\n"); },
            [this] { return stop_requested_.load(); });
      }
      if (stop_requested_) {
        add_state(abort_requested_ ? "aborted" : "terminated", -1, "stopped by request");
        return;
      }

      std::string repo_dir = extract_code();
      dj::Json cfg = dj::Json::object();
      cfg.set("Image", image);
      // No commands => the image's own ENTRYPOINT/CMD runs the job (reference
      // honors image defaults the same way, docker.go DockerShellCommands).
      if (!job_spec_["commands"].as_array().empty()) {
        dj::Json entry = dj::Json::array();
        entry.push_back("/bin/sh");
        entry.push_back("-c");
        cfg.set("Entrypoint", std::move(entry));
        dj::Json cmd = dj::Json::array();
        cmd.push_back(build_script());
        cfg.set("Cmd", std::move(cmd));
      }
      dj::Json env = dj::Json::array();
      // Telemetry rides a dedicated bind (added below) so the sidecar lands
      // in the agent's base dir no matter what the container image mounts.
      for (auto& kv : job_env("/workflow", "/run/dstack-telemetry/workload.jsonl")) {
        env.push_back(kv);
      }
      env.push_back("PJRT_DEVICE=TPU");
      cfg.set("Env", std::move(env));
      std::string workdir = "/workflow";
      if (!job_spec_["working_dir"].as_string().empty()) {
        workdir = job_spec_["working_dir"].as_string();
        if (workdir[0] != '/') workdir = "/workflow/" + workdir;
      }
      cfg.set("WorkingDir", workdir);
      // Raw (unframed) log stream, exactly like the host pty path.
      cfg.set("Tty", true);
      dj::Json labels = dj::Json::object();
      labels.set("dstack-tpu.task", "true");
      labels.set("dstack-tpu.job", job_key);
      cfg.set("Labels", std::move(labels));
      if (!job_spec_["user"].as_string().empty()) cfg.set("User", job_spec_["user"].as_string());

      dj::Json host = dj::Json::object();
      // Host networking: the JAX coordinator / MegaScale ports and ICI transport
      // assume host identity on TPU pods (ref uses host network mode for clusters).
      host.set("NetworkMode", "host");
      host.set("Privileged", job_spec_["privileged"].as_bool());
      dj::Json binds = dj::Json::array();
      binds.push_back(repo_dir + ":/workflow");
      binds.push_back(telemetry_dir() + ":/run/dstack-telemetry");
      // Volume mounts: host dirs bind directly; block devices are readied on the
      // host first (mounted under base_dir), then bound (the shim pattern:
      // docker.go:505-575 prepareVolumes + getVolumeMounts).
      for (const auto& v : job_spec_["volumes"].as_array()) {
        const std::string& vpath = v["path"].as_string();
        if (vpath.empty()) continue;
        const std::string& host_dir = v["host_dir"].as_string();
        if (!host_dir.empty()) {
          binds.push_back(host_dir + ":" + vpath);
        } else if (!v["device"].as_string().empty()) {
          std::string mnt = base_dir_ + "/mnt-" + v["name"].as_string();
          std::string err;
          if (!prepare_volume(v, mnt, &err)) {
            throw std::runtime_error("preparing volume " + v["name"].as_string() +
                                     " failed: " + err);
          }
          binds.push_back(mnt + ":" + vpath);
        }
      }
      for (const auto& im : job_spec_["instance_mounts"].as_array()) {
        if (!im["instance_path"].as_string().empty() && !im["path"].as_string().empty()) {
          binds.push_back(im["instance_path"].as_string() + ":" + im["path"].as_string());
        }
      }
      host.set("Binds", std::move(binds));
      // TPU chips reach the container as device files, the TPU analog of the
      // reference's GPU device requests (shim/docker.go:1008-1102).
      dj::Json devices = dj::Json::array();
      for (const auto& dev : ddocker::host_tpu_devices()) {
        dj::Json d = dj::Json::object();
        d.set("PathOnHost", dev);
        d.set("PathInContainer", dev);
        d.set("CgroupPermissions", "rwm");
        devices.push_back(std::move(d));
      }
      host.set("Devices", std::move(devices));
      host.set("ShmSize", static_cast<int64_t>(1) << 30);
      // Resource caps from the job's requirements (reference shim/docker.go:825
      // NanoCPUs/Memory): upper bound when a range max is set, else the floor.
      const dj::Json& res = job_spec_["requirements"]["resources"];
      double cpus = res["cpu"]["count"]["max"].as_number(
          res["cpu"]["count"]["min"].as_number(0));
      if (cpus > 0) host.set("NanoCpus", static_cast<int64_t>(cpus * 1e9));
      double mem_gb = res["memory"]["max"].as_number(res["memory"]["min"].as_number(0));
      if (mem_gb > 0) {
        host.set("Memory", static_cast<int64_t>(mem_gb * 1024.0 * 1024.0 * 1024.0));
      }
      cfg.set("HostConfig", std::move(host));

      try {
        cid = dc.create_container(cfg, cname);
      } catch (const ddocker::DockerError& e) {
        if (std::string(e.what()).find("HTTP 409") == std::string::npos) throw;
        // Stale same-name container from a crashed run that predates the label
        // scan: replace it.
        dc.remove_container(cname, true);
        cid = dc.create_container(cfg, cname);
      }
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      container_id_ = cid;
    }
    add_state("running");
    bool already_exited = false;
    int recovered_code = 0;
    if (recovered) {
      // NEVER start a recovered container: starting an exited one would re-run
      // the job. Running -> attach; exited -> collect logs + exit code.
      dj::Json info = dc.inspect_container(cid);
      already_exited = !info["State"]["Running"].as_bool();
      recovered_code = static_cast<int>(info["State"]["ExitCode"].as_int());
    } else {
      dc.start_container(cid);
    }
    // Close the stop() race: a stop that arrived before container_id_ was set
    // found nothing to signal — honor it now (wait_container sees the exit).
    if (stop_requested_ && !already_exited) {
      dc.kill_container(cid, abort_requested_ ? "SIGKILL" : "SIGTERM");
    }

    // Stream logs line-buffered; with follow the call returns when the container
    // stops, then wait() yields the exit code.
    std::string partial;
    dc.stream_logs(cid, !already_exited, [&](const char* data, size_t n) {
      partial.append(data, n);
      size_t nl;
      while ((nl = partial.find('\n')) != std::string::npos) {
        add_log(partial.substr(0, nl + 1));
        partial.erase(0, nl + 1);
      }
    });
    if (!partial.empty()) add_log(partial);
    int code = already_exited ? recovered_code : dc.wait_container(cid);
    {
      std::lock_guard<std::mutex> lk(mu_);
      container_id_.clear();
    }
    try {
      dc.remove_container(cid, true);
    } catch (const ddocker::DockerError&) {
    }
    if (job_generation_.load() != generation) return;  // superseded
    finish(code, "exit status " + std::to_string(code));
  } catch (const std::exception& e) {
    // std::exception, not just DockerError: a malformed engine response makes
    // Json::parse throw runtime_error, and an escape here would std::terminate
    // the whole agent.
    {
      std::lock_guard<std::mutex> lk(mu_);
      container_id_.clear();
    }
    if (!cid.empty()) {
      // Don't leak a running workload holding the TPU devices: the job is being
      // marked failed and the slice will return to the pool.
      try {
        ddocker::DockerClient(docker_socket_).remove_container(cid, true);
      } catch (const std::exception&) {
      }
    }
    if (job_generation_.load() != generation) return;
    if (stop_requested_) {
      add_state(abort_requested_ ? "aborted" : "terminated", -1, "stopped by request");
    } else {
      add_state("failed", -1, e.what());
    }
  }
}

void Executor::exec_host(uint64_t generation) {
  add_state("running");
  std::string repo_dir = extract_code();

  // Ready volume mounts before the user's commands run; a volume that cannot be
  // mounted fails the job (writes to an unmounted path would land on the
  // ephemeral boot disk and vanish with the slice).
  for (const auto& v : job_spec_["volumes"].as_array()) {
    if (v["path"].as_string().empty()) continue;
    std::string err;
    if (!prepare_volume(v, v["path"].as_string(), &err)) {
      add_state("failed", -1, "preparing volume " + v["name"].as_string() + " failed: " + err);
      return;
    }
  }
  std::string script = build_script();

  std::string workdir = repo_dir;
  if (!job_spec_["working_dir"].is_null() && !job_spec_["working_dir"].as_string().empty()) {
    workdir = job_spec_["working_dir"].as_string();
    if (workdir[0] != '/') workdir = repo_dir + "/" + workdir;
  }

  // Deduplicate with JOB-env precedence: getenv takes the FIRST matching entry,
  // so naively appending the job env after the inherited environ would make a
  // user's `env:` overrides silently lose to whatever the host agent inherited.
  std::vector<std::string> env_strings;
  {
    std::map<std::string, std::string> merged;
    auto put = [&merged](const std::string& kv) {
      size_t eq = kv.find('=');
      if (eq == std::string::npos) return;
      merged[kv.substr(0, eq)] = kv.substr(eq + 1);
    };
    for (char** e = environ; *e; ++e) put(*e);
    for (auto& kv : job_env(repo_dir, telemetry_file())) put(kv);
    for (auto& kv : merged) env_strings.push_back(kv.first + "=" + kv.second);
  }

  // Manual openpty+fork instead of forkpty: glibc's forkpty child _exit(1)s when
  // TIOCSCTTY fails, which happens when the kernel recycles a pty index that is still
  // the controlling tty of a lingering older session (intermittent silent exit-1 under
  // job churn). We don't need job control -- a failed TIOCSCTTY is fine.
  int master_fd, slave_fd;
  if (openpty(&master_fd, &slave_fd, nullptr, nullptr, nullptr) != 0) {
    add_state("failed", -1, "openpty failed");
    return;
  }
  pid_t pid = fork();
  if (pid < 0) {
    close(master_fd);
    close(slave_fd);
    add_state("failed", -1, "fork failed");
    return;
  }
  if (pid == 0) {
    // Child: new session + own process group so stop() can signal the whole tree.
    setsid();
    (void)ioctl(slave_fd, TIOCSCTTY, 0);  // best-effort; see above
    dup2(slave_fd, 0);
    dup2(slave_fd, 1);
    dup2(slave_fd, 2);
    if (slave_fd > 2) close(slave_fd);
    close(master_fd);
    if (chdir(workdir.c_str()) != 0) {
      int rc = chdir("/");
      (void)rc;
    }
    std::vector<char*> envp;
    for (auto& s : env_strings) envp.push_back(const_cast<char*>(s.c_str()));
    envp.push_back(nullptr);
    execle("/bin/sh", "sh", "-c", script.c_str(), static_cast<char*>(nullptr), envp.data());
    _exit(127);
  }
  close(slave_fd);
  setpgid(pid, pid);
  child_pid_ = pid;
  // Close the stop() race: a stop that arrived while we were extracting code (before
  // child_pid_ was set) found nothing to signal — honor it now.
  if (stop_requested_) kill(-pid, abort_requested_ ? SIGKILL : SIGTERM);

  // Parent: stream pty output into the log buffer, line-buffered.
  std::string partial;
  char buf[4096];
  while (true) {
    pollfd pfd{master_fd, POLLIN, 0};
    int pr = poll(&pfd, 1, 200);
    if (pr < 0 && errno != EINTR) break;
    if (pr > 0) {
      ssize_t n = read(master_fd, buf, sizeof(buf));
      // EINTR/EAGAIN are not EOF: treating them as one silently drops the rest
      // of the job's output (seen under sanitizers, possible with any signal).
      if (n < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      if (n <= 0) break;
      partial.append(buf, static_cast<size_t>(n));
      size_t nl;
      while ((nl = partial.find('\n')) != std::string::npos) {
        add_log(partial.substr(0, nl + 1));
        partial.erase(0, nl + 1);
      }
    }
    int status;
    pid_t done = waitpid(pid, &status, WNOHANG);
    if (done == pid) {
      // Drain remaining pty output (non-blocking; retry EINTR, stop on EAGAIN/EOF).
      fcntl(master_fd, F_SETFL, O_NONBLOCK);
      while (true) {
        ssize_t n = read(master_fd, buf, sizeof(buf));
        if (n > 0) {
          partial.append(buf, static_cast<size_t>(n));
          continue;
        }
        if (n < 0 && errno == EINTR) continue;
        break;
      }
      size_t nl;
      while ((nl = partial.find('\n')) != std::string::npos) {
        add_log(partial.substr(0, nl + 1));
        partial.erase(0, nl + 1);
      }
      if (!partial.empty()) add_log(partial);
      close(master_fd);
      child_pid_ = 0;
      if (job_generation_.load() != generation) return;  // superseded
      int code = WIFEXITED(status) ? WEXITSTATUS(status) : 128 + WTERMSIG(status);
      finish(code, "exit status " + std::to_string(code));
      return;
    }
  }
  // Pty EOF before exit; wait for the child.
  int status;
  waitpid(pid, &status, 0);
  if (!partial.empty()) add_log(partial);
  close(master_fd);
  child_pid_ = 0;
  if (job_generation_.load() != generation) return;  // superseded
  int code = WIFEXITED(status) ? WEXITSTATUS(status) : 128 + WTERMSIG(status);
  finish(code, "exit status " + std::to_string(code));
}

}  // namespace drunner
