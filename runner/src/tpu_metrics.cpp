#include "tpu_metrics.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <sstream>
#include <vector>

namespace dtpu {

namespace {

struct Series {
  double sum = 0;
  int count = 0;
};

// "name{labels} value" / "name value" -> (name, value); false for comments/blank.
bool parse_sample(const std::string& line, std::string* name, double* value) {
  if (line.empty() || line[0] == '#') return false;
  size_t name_end = line.find_first_of("{ ");
  if (name_end == std::string::npos) return false;
  *name = line.substr(0, name_end);
  size_t value_start;
  if (line[name_end] == '{') {
    size_t close = line.find('}', name_end);
    if (close == std::string::npos) return false;
    value_start = close + 1;
  } else {
    value_start = name_end;
  }
  while (value_start < line.size() && line[value_start] == ' ') ++value_start;
  if (value_start >= line.size()) return false;
  char* end = nullptr;
  *value = strtod(line.c_str() + value_start, &end);
  return end != line.c_str() + value_start;
}

bool name_has(const std::string& name, const char* needle) {
  return name.find(needle) != std::string::npos;
}

}  // namespace

dj::Json parse_prometheus_tpu(const std::string& text) {
  // Known exporters name these variously (tpu-device-plugin: duty_cycle,
  // memory_used, memory_total; libtpu monitoring: tensorcore_utilization,
  // hbm_memory_usage_bytes) — match on substrings.
  Series duty, tensorcore, mem_used, mem_total;
  std::istringstream ss(text);
  std::string line;
  while (std::getline(ss, line)) {
    std::string name;
    double value = 0;
    if (!parse_sample(line, &name, &value)) continue;
    if (name_has(name, "tensorcore_util")) {
      tensorcore.sum += value;
      ++tensorcore.count;
    } else if (name_has(name, "duty_cycle")) {
      duty.sum += value;
      ++duty.count;
    } else if (name_has(name, "memory_used") || name_has(name, "memory_usage")) {
      mem_used.sum += value;
      ++mem_used.count;
    } else if (name_has(name, "memory_total") || name_has(name, "memory_capacity")) {
      mem_total.sum += value;
      ++mem_total.count;
    }
  }
  if (duty.count == 0 && tensorcore.count == 0 && mem_used.count == 0) return dj::Json();
  dj::Json out = dj::Json::object();
  if (duty.count > 0) out.set("duty_cycle_percent", duty.sum / duty.count);
  if (tensorcore.count > 0) out.set("tensorcore_util_percent", tensorcore.sum / tensorcore.count);
  if (mem_used.count > 0) out.set("hbm_usage_bytes", mem_used.sum);
  if (mem_total.count > 0) out.set("hbm_total_bytes", mem_total.sum);
  return out;
}

namespace {

// Minimal blocking HTTP GET over TCP with a short deadline; metrics sampling
// must never stall the agent's API thread for long.
std::string http_get(const std::string& host, int port, const std::string& path,
                     int timeout_ms) {
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res) != 0) return "";
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return "";
  std::string req = "GET " + path + " HTTP/1.0\r\nHost: " + host + "\r\n\r\n";
  if (write(fd, req.data(), req.size()) != static_cast<ssize_t>(req.size())) {
    close(fd);
    return "";
  }
  std::string raw;
  char buf[8192];
  while (true) {
    pollfd pfd{fd, POLLIN, 0};
    if (poll(&pfd, 1, timeout_ms) <= 0) break;
    ssize_t n = read(fd, buf, sizeof(buf));
    if (n <= 0) break;
    raw.append(buf, static_cast<size_t>(n));
  }
  close(fd);
  size_t body = raw.find("\r\n\r\n");
  if (body == std::string::npos) return "";
  if (raw.compare(0, 5, "HTTP/") != 0 || raw.find(" 200") > 12) return "";
  return raw.substr(body + 4);
}

}  // namespace

dj::Json sample_tpu_metrics() {
  const char* url = getenv("DSTACK_TPU_RUNTIME_METRICS_URL");
  if (!url || !*url) return dj::Json();
  std::string u = url;
  if (u.compare(0, 7, "http://") != 0) return dj::Json();
  u = u.substr(7);
  std::string path = "/metrics";
  auto slash = u.find('/');
  if (slash != std::string::npos) {
    path = u.substr(slash);
    u = u.substr(0, slash);
  }
  int port = 80;
  auto colon = u.rfind(':');
  if (colon != std::string::npos) {
    port = atoi(u.c_str() + colon + 1);
    u = u.substr(0, colon);
  }
  std::string body = http_get(u, port, path, 2000);
  if (body.empty()) return dj::Json();
  return parse_prometheus_tpu(body);
}

}  // namespace dtpu
