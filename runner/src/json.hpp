// Minimal JSON value: parse + serialize, no external deps.
//
// The runner agent (parity: reference runner/internal/* in Go) needs only plain JSON
// for its HTTP API; this is a small recursive-descent parser with a tagged-union value.
#pragma once

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace dj {

class Json;
using JsonArray = std::vector<Json>;
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Number), num_(v) {}
  Json(int64_t v) : type_(Type::Number), num_(static_cast<double>(v)) {}
  Json(double v) : type_(Type::Number), num_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_bool() const { return type_ == Type::Bool; }

  bool as_bool(bool dflt = false) const { return type_ == Type::Bool ? bool_ : dflt; }
  double as_number(double dflt = 0) const { return type_ == Type::Number ? num_ : dflt; }
  int64_t as_int(int64_t dflt = 0) const {
    return type_ == Type::Number ? static_cast<int64_t>(num_) : dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }
  const JsonArray& as_array() const {
    static const JsonArray empty;
    return type_ == Type::Array ? arr_ : empty;
  }
  const JsonObject& as_object() const {
    static const JsonObject empty;
    return type_ == Type::Object ? obj_ : empty;
  }

  // Object access; returns Null json for missing keys.
  const Json& operator[](const std::string& key) const {
    static const Json null_json;
    if (type_ != Type::Object) return null_json;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_json : it->second;
  }
  Json& set(const std::string& key, Json v) {
    type_ = Type::Object;
    obj_[key] = std::move(v);
    return *this;
  }
  void push_back(Json v) {
    type_ = Type::Array;
    arr_.push_back(std::move(v));
  }
  size_t size() const {
    if (type_ == Type::Array) return arr_.size();
    if (type_ == Type::Object) return obj_.size();
    return 0;
  }

  static Json object() { return Json(JsonObject{}); }
  static Json array() { return Json(JsonArray{}); }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos, 0);
    skip_ws(text, pos);
    if (pos != text.size()) throw std::runtime_error("trailing JSON content");
    return v;
  }

  // Daemon/server bytes are untrusted; recursion must be bounded or a hostile
  // "[[[[..." overflows the stack instead of throwing.
  static constexpr int kMaxDepth = 192;

 private:
  void write(std::ostringstream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == std::floor(num_) && std::fabs(num_) < 1e15) {
          os << static_cast<int64_t>(num_);
        } else {
          os << num_;
        }
        break;
      }
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) os << ',';
          arr_[i].write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& [k, v] : obj_) {
          if (!first) os << ',';
          first = false;
          write_string(os, k);
          os << ':';
          v.write(os);
        }
        os << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        case '\b': os << "\\b"; break;
        case '\f': os << "\\f"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  static void skip_ws(const std::string& t, size_t& p) {
    while (p < t.size() && std::isspace(static_cast<unsigned char>(t[p]))) ++p;
  }

  static Json parse_value(const std::string& t, size_t& p, int depth) {
    if (depth > kMaxDepth) throw std::runtime_error("JSON nesting too deep");
    skip_ws(t, p);
    if (p >= t.size()) throw std::runtime_error("unexpected end of JSON");
    char c = t[p];
    if (c == '{') return parse_object(t, p, depth);
    if (c == '[') return parse_array(t, p, depth);
    if (c == '"') return Json(parse_string(t, p));
    if (c == 't' || c == 'f') return parse_bool(t, p);
    if (c == 'n') {
      expect(t, p, "null");
      return Json();
    }
    return parse_number(t, p);
  }

  static void expect(const std::string& t, size_t& p, const char* word) {
    size_t n = strlen(word);
    if (t.compare(p, n, word) != 0) throw std::runtime_error("invalid JSON literal");
    p += n;
  }

  static Json parse_bool(const std::string& t, size_t& p) {
    if (t[p] == 't') {
      expect(t, p, "true");
      return Json(true);
    }
    expect(t, p, "false");
    return Json(false);
  }

  static Json parse_number(const std::string& t, size_t& p) {
    size_t start = p;
    if (p < t.size() && (t[p] == '-' || t[p] == '+')) ++p;
    while (p < t.size() &&
           (std::isdigit(static_cast<unsigned char>(t[p])) || t[p] == '.' || t[p] == 'e' ||
            t[p] == 'E' || t[p] == '-' || t[p] == '+')) {
      ++p;
    }
    if (p == start) throw std::runtime_error("invalid JSON number");
    // stod throws invalid_argument/out_of_range, which would escape the
    // parser's runtime_error contract on inputs like "-" or "1e999999".
    try {
      return Json(std::stod(t.substr(start, p - start)));
    } catch (const std::exception&) {
      throw std::runtime_error("invalid JSON number");
    }
  }

  static std::string parse_string(const std::string& t, size_t& p) {
    ++p;  // opening quote
    std::string out;
    while (p < t.size() && t[p] != '"') {
      char c = t[p];
      if (c == '\\') {
        ++p;
        if (p >= t.size()) break;
        char e = t[p];
        switch (e) {
          case 'n': out += '\n'; break;
          case 't': out += '\t'; break;
          case 'r': out += '\r'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case '/': out += '/'; break;
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case 'u': {
            if (p + 4 >= t.size()) throw std::runtime_error("bad \\u escape");
            for (size_t h = p + 1; h <= p + 4; ++h) {
              if (!std::isxdigit(static_cast<unsigned char>(t[h]))) {
                throw std::runtime_error("bad \\u escape");  // stoul would
                // otherwise throw invalid_argument or parse a hex prefix
              }
            }
            unsigned long cp = std::stoul(t.substr(p + 1, 4), nullptr, 16);
            p += 4;
            // Combine UTF-16 surrogate pairs (python json.dumps with ensure_ascii
            // emits astral-plane chars this way); lone surrogates fold to U+FFFD.
            if (cp >= 0xD800 && cp <= 0xDBFF) {
              if (p + 6 < t.size() && t[p + 1] == '\\' && t[p + 2] == 'u') {
                unsigned long lo = std::stoul(t.substr(p + 3, 4), nullptr, 16);
                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                  p += 6;
                } else {
                  cp = 0xFFFD;
                }
              } else {
                cp = 0xFFFD;
              }
            } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
              cp = 0xFFFD;  // lone low surrogate
            }
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xF0 | (cp >> 18));
              out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default: throw std::runtime_error("bad escape");
        }
        ++p;
      } else {
        out += c;
        ++p;
      }
    }
    if (p >= t.size()) throw std::runtime_error("unterminated string");
    ++p;  // closing quote
    return out;
  }

  static Json parse_array(const std::string& t, size_t& p, int depth) {
    ++p;
    JsonArray arr;
    skip_ws(t, p);
    if (p < t.size() && t[p] == ']') {
      ++p;
      return Json(std::move(arr));
    }
    while (true) {
      arr.push_back(parse_value(t, p, depth + 1));
      skip_ws(t, p);
      if (p >= t.size()) throw std::runtime_error("unterminated array");
      if (t[p] == ',') {
        ++p;
        continue;
      }
      if (t[p] == ']') {
        ++p;
        break;
      }
      throw std::runtime_error("expected , or ] in array");
    }
    return Json(std::move(arr));
  }

  static Json parse_object(const std::string& t, size_t& p, int depth) {
    ++p;
    JsonObject obj;
    skip_ws(t, p);
    if (p < t.size() && t[p] == '}') {
      ++p;
      return Json(std::move(obj));
    }
    while (true) {
      skip_ws(t, p);
      if (p >= t.size() || t[p] != '"') throw std::runtime_error("expected object key");
      std::string key = parse_string(t, p);
      skip_ws(t, p);
      if (p >= t.size() || t[p] != ':') throw std::runtime_error("expected :");
      ++p;
      obj[key] = parse_value(t, p, depth + 1);
      skip_ws(t, p);
      if (p >= t.size()) throw std::runtime_error("unterminated object");
      if (t[p] == ',') {
        ++p;
        continue;
      }
      if (t[p] == '}') {
        ++p;
        break;
      }
      throw std::runtime_error("expected , or } in object");
    }
    return Json(std::move(obj));
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

}  // namespace dj
