// TPU hardware metrics for the agent's /api/metrics endpoint.
//
// Parity: the reference relays GPU utilization through a DCGM exporter sidecar
// (runner/internal/shim/dcgm/exporter.go). The TPU analog: the runtime exposes a
// Prometheus endpoint (GKE tpu-device-plugin :2112, or libtpu's monitoring
// exporter) with per-chip duty-cycle and HBM gauges; the agent scrapes and
// reduces it to one host-level sample the control plane stores per job.
#pragma once

#include <string>

#include "json.hpp"

namespace dtpu {

// Reduce Prometheus exposition text to {"duty_cycle_percent", "hbm_usage_bytes",
// "hbm_total_bytes", "tensorcore_util_percent"} (keys present only when the
// corresponding series exist). Duty/utilization average across chips; memory sums.
dj::Json parse_prometheus_tpu(const std::string& text);

// Scrape the endpoint named by DSTACK_TPU_RUNTIME_METRICS_URL
// (http://host:port/path). Returns a null Json when unset or unreachable —
// the control plane stores no TPU sample for the point then.
dj::Json sample_tpu_metrics();

}  // namespace dtpu
