// Job executor: spawn the job's shell in a pty, collect logs + state events.
//
// Parity: reference runner/internal/executor/executor.go (execJob:254-418,
// startCommand:614 — pty fork, env contract injection executor.go:262-274). TPU
// re-design: instead of writing an MPI hostfile + SSH mesh, the executor injects the
// JAX coordinator / TPU worker identity / MegaScale env from the cluster_info the
// control plane submits (SURVEY §2.6).
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

#include "json.hpp"

namespace drunner {

struct Event {
  int64_t seq;
  bool is_state;  // state transition vs log line
  std::string state;
  int exit_status;
  std::string message;
  std::string ts;  // ISO-8601 UTC
};

class Executor {
 public:
  explicit Executor(std::string base_dir);
  ~Executor();

  // HTTP API surface (all JSON in/out, thread-safe).
  dj::Json submit(const dj::Json& body);  // {job_spec, cluster_info, run_spec, secrets}
  dj::Json upload_code(const std::string& bytes);
  dj::Json run();
  dj::Json pull(int64_t offset);
  dj::Json stop(bool abort);
  dj::Json metrics() const;
  dj::Json health() const;

 private:
  void exec_thread();
  void add_state(const std::string& state, int exit_status = 0, const std::string& msg = "");
  void add_log(const std::string& line);
  void trim_events_locked();
  std::string extract_code();

  std::string base_dir_;
  dj::Json job_spec_;
  dj::Json cluster_info_;
  dj::Json secrets_;
  std::string code_path_;
  bool has_job_ = false;
  bool job_started_ = false;  // guarded by mu_; reset by submit()

  mutable std::mutex mu_;
  std::deque<Event> events_;
  int64_t next_seq_ = 1;
  std::string current_state_ = "idle";

  std::thread worker_;
  std::atomic<pid_t> child_pid_{0};
  std::atomic<bool> stop_requested_{false};
  std::atomic<bool> abort_requested_{false};
  std::atomic<uint64_t> job_generation_{0};
};

}  // namespace drunner
