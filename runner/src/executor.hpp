// Job executor: run the job in a pty (host mode) or a docker container
// (container mode), collect logs + state events.
//
// Parity: reference runner/internal/executor/executor.go (execJob:254-418,
// startCommand:614 — pty fork, env contract injection executor.go:262-274) plus the
// shim's container lifecycle (shim/docker.go:240-875 — pull with registry auth,
// create with device mapping, start/wait, label-based restart recovery;
// shim/task.go:31-145). TPU re-design: one agent owns both roles, the JAX
// coordinator / TPU worker identity / MegaScale env comes from the cluster_info the
// control plane submits (SURVEY §2.6), and TPU chips reach containers as
// /dev/accel* + /dev/vfio/* device mappings with PJRT_DEVICE=TPU.
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "json.hpp"

namespace drunner {

// fork/execvp with an argv (no shell). Captures combined stdout+stderr into
// *output when non-null; returns the exit code or -1 on fork/exec failure.
int run_argv(const std::vector<std::string>& argv, std::string* output);

struct Event {
  int64_t seq;
  bool is_state;  // state transition vs log line
  std::string state;
  int exit_status;
  std::string message;
  std::string ts;  // ISO-8601 UTC
};

class Executor {
 public:
  // docker_mode: "never" (host pty exec only), "auto" (container when the job has
  // an image and an engine answers on the socket), "always" (container or fail).
  // docker_socket empty = DockerClient::default_socket().
  explicit Executor(std::string base_dir, std::string docker_mode = "never",
                    std::string docker_socket = "");
  ~Executor();

  // HTTP API surface (all JSON in/out, thread-safe).
  dj::Json submit(const dj::Json& body);  // {job_spec, cluster_info, run_spec, secrets}
  dj::Json upload_code(const std::string& bytes);
  dj::Json run();
  dj::Json pull(int64_t offset);
  dj::Json stop(bool abort);
  // Non-const: tailing the workload telemetry sidecar advances a read offset.
  dj::Json metrics();
  dj::Json health() const;
  // On-demand profiler capture: writes the telemetry control file the live
  // workload's emitter polls (workloads/telemetry.py); {"seconds": N} in.
  dj::Json profile(const dj::Json& body);

 private:
  void exec_thread();
  void exec_host(uint64_t generation);
  void exec_container(uint64_t generation);
  void finish(int code, const std::string& how);
  void add_state(const std::string& state, int exit_status = 0, const std::string& msg = "");
  void add_log(const std::string& line);
  void trim_events_locked();
  std::string extract_code();
  std::string build_script() const;
  std::vector<std::string> job_env(const std::string& repo_dir,
                                   const std::string& telemetry_path) const;
  // Workload telemetry sidecar (written by workloads/telemetry.py inside the
  // job, tailed here into the /api/metrics sample).
  std::string telemetry_dir() const { return base_dir_ + "/telemetry"; }
  std::string telemetry_file() const { return telemetry_dir() + "/workload.jsonl"; }
  dj::Json tail_telemetry_locked();
  // Host hardware sample (/proc cpu/mem/net + the TPU runtime sample the
  // caller already scraped) shipped as a kind="host" point in the same
  // workload stream — the per-host half of the control plane's gang-health
  // view (services/gang_health.py).
  dj::Json host_sample_locked(const dj::Json& tpu);

  std::string base_dir_;
  std::string docker_mode_;
  std::string docker_socket_;
  dj::Json repo_data_;  // run_spec.repo_data: git clone/checkout/diff contract
  dj::Json job_spec_;
  dj::Json cluster_info_;
  dj::Json secrets_;
  std::string code_path_;
  bool has_job_ = false;
  bool job_started_ = false;  // guarded by mu_; reset by submit()

  mutable std::mutex mu_;
  std::deque<Event> events_;
  int64_t next_seq_ = 1;
  std::string current_state_ = "idle";

  std::string container_id_;  // guarded by mu_; non-empty while a container runs

  std::thread worker_;
  std::atomic<pid_t> child_pid_{0};
  std::atomic<bool> stop_requested_{false};
  std::atomic<bool> abort_requested_{false};
  std::atomic<uint64_t> job_generation_{0};

  // Guarded by mu_. The offset is how far into the sidecar the control plane
  // has already been shipped (reset by submit, rewound on truncation).
  // Profile ids are monotonic within THIS agent process (enough for the
  // emitter's per-job replay guard — a job's emitter starts at 0); a
  // restarted agent restarts at 1, so consumers matching marks by id must
  // also discount marks that predate their request (cli cmd_profile does).
  int64_t telemetry_offset_ = 0;
  int64_t profile_seq_ = 0;

  // Host-sample deltas (guarded by mu_): cpu percent and net byte rates need
  // the previous /proc counters; zero until the second sample.
  int64_t host_cpu_total_ = 0;
  int64_t host_cpu_idle_ = 0;
  int64_t host_net_rx_ = 0;
  int64_t host_net_tx_ = 0;
  double host_sample_at_ = 0.0;  // CLOCK_MONOTONIC seconds of the last sample
};

}  // namespace drunner
