// Minimal threaded HTTP/1.1 server (no external deps).
//
// Parity: the reference runner serves its API with Go's net/http (runner/api/http.go);
// here a small accept-loop + thread-per-connection server is enough: the only clients
// are the control plane (one poll every few seconds) and the shim.
#pragma once

#include <functional>
#include <map>
#include <string>

namespace dhttp {

struct Request {
  std::string method;
  std::string path;                          // without query string
  std::map<std::string, std::string> query;  // parsed query params
  std::map<std::string, std::string> headers;
  std::string body;
};

struct Response {
  int status = 200;
  std::string content_type = "application/json";
  std::string body;
};

using Handler = std::function<Response(const Request&)>;

class Server {
 public:
  // Binds immediately; port 0 picks an ephemeral port (readable via port()).
  explicit Server(const std::string& host, int port);
  ~Server();

  void handle(const std::string& method, const std::string& path, Handler h);
  int port() const { return port_; }

  // Blocks serving requests until stop() is called from a handler/another thread.
  void serve_forever();
  void stop();

 private:
  void handle_connection(int fd);

  int listen_fd_ = -1;
  int port_ = 0;
  volatile bool stopping_ = false;
  std::map<std::string, Handler> routes_;  // "METHOD path" -> handler
};

}  // namespace dhttp
