// Unit tests for the runner agent's core: pty exec, stop/abort races, pull
// pagination, idempotent submit, env contract, JSON, docker helpers, TPU
// metrics parsing. No framework — a tiny CHECK harness (the reference covers the
// same surface with 1,957 LoC of Go tests, runner/internal/executor/executor_test.go).
//
// Build + run: `make test` in runner/.
#include <csignal>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <thread>
#include <vector>

#include "docker.hpp"
#include "executor.hpp"
#include "json.hpp"
#include "tpu_metrics.hpp"

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                              \
  do {                                                                           \
    ++g_checks;                                                                  \
    if (!(cond)) {                                                               \
      ++g_failures;                                                              \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);            \
    }                                                                            \
  } while (0)

#define CHECK_EQ(a, b)                                                           \
  do {                                                                           \
    ++g_checks;                                                                  \
    auto va = (a);                                                               \
    auto vb = (b);                                                               \
    if (!(va == vb)) {                                                           \
      ++g_failures;                                                              \
      fprintf(stderr, "FAIL %s:%d: %s != %s\n", __FILE__, __LINE__, #a, #b);     \
    }                                                                            \
  } while (0)

namespace {

std::string temp_dir() {
  char tmpl[] = "/tmp/drunner-test-XXXXXX";
  char* d = mkdtemp(tmpl);
  return d ? d : "/tmp";
}

dj::Json make_submit(const std::string& job_name, const std::vector<std::string>& commands) {
  dj::Json spec = dj::Json::object();
  spec.set("job_name", job_name);
  dj::Json cmds = dj::Json::array();
  for (const auto& c : commands) cmds.push_back(c);
  spec.set("commands", std::move(cmds));
  spec.set("image_name", "");
  dj::Json env = dj::Json::object();
  env.set("MY_TEST_VAR", "var-value");
  spec.set("env", std::move(env));

  dj::Json ci = dj::Json::object();
  ci.set("node_rank", static_cast<int64_t>(3));
  ci.set("nodes_num", static_cast<int64_t>(4));
  ci.set("tpu_worker_id", static_cast<int64_t>(1));
  ci.set("num_slices", static_cast<int64_t>(2));
  ci.set("slice_id", static_cast<int64_t>(1));
  ci.set("megascale_coordinator_address", "10.0.0.1:8081");

  dj::Json secrets = dj::Json::object();
  secrets.set("MY_SECRET", "s3cret");

  dj::Json body = dj::Json::object();
  body.set("job_spec", std::move(spec));
  body.set("cluster_info", std::move(ci));
  body.set("secrets", std::move(secrets));
  return body;
}

// Drains pull until a terminal state or timeout; returns (state, all_logs, pulls).
struct RunResult {
  std::string state;
  int exit_status = 0;
  std::string logs;
  int64_t final_offset = 0;
  int pages = 0;
  bool saw_has_more = false;
};

// Generous default deadline: the suite may run on a heavily-loaded 1-CPU box
// (the full pytest run spawns servers and agents concurrently).
RunResult pump_until_terminal(drunner::Executor& ex, int timeout_ms = 90000,
                              int64_t start_offset = 0) {
  RunResult r;
  int64_t offset = start_offset;
  auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  while (std::chrono::steady_clock::now() < deadline) {
    dj::Json page = ex.pull(offset);
    ++r.pages;
    // Offsets must be monotonic: the returned offset resumes the stream.
    int64_t next = page["offset"].as_int();
    if (next < offset) {
      fprintf(stderr, "FAIL: offset went backwards %lld -> %lld\n",
              static_cast<long long>(offset), static_cast<long long>(next));
      ++g_failures;
      return r;
    }
    offset = next;
    if (page["has_more"].as_bool()) r.saw_has_more = true;
    for (const auto& l : page["logs"].as_array()) r.logs += l["message"].as_string();
    for (const auto& s : page["job_states"].as_array()) {
      const std::string& st = s["state"].as_string();
      if (st == "done" || st == "failed" || st == "terminated" || st == "aborted") {
        r.state = st;
        r.exit_status = static_cast<int>(s["exit_status"].as_int());
        r.final_offset = offset;
        return r;
      }
    }
    if (!page["has_more"].as_bool()) usleep(50 * 1000);
  }
  r.state = "timeout";
  return r;
}

// The agent appends one kind="host" hardware sample (cpu/mem/net from /proc)
// to EVERY metrics response — the last workload element. Returns the sidecar
// points only (everything before it) after validating the host point.
static dj::Json sidecar_points(const dj::Json& m) {
  const dj::Json& workload = m["workload"];
  CHECK(!workload.is_null());
  size_t n = workload.as_array().size();
  CHECK(n >= 1);
  const dj::Json& host = workload.as_array()[n - 1];
  CHECK_EQ(host["kind"].as_string(), std::string("host"));
  CHECK(!host["ts"].as_string().empty());
  CHECK(!host["host"].as_string().empty());          // hostname
  CHECK(host["mem_total_bytes"].as_int() > 0);       // /proc/meminfo parsed
  dj::Json rest = dj::Json::array();
  for (size_t i = 0; i + 1 < n; ++i) rest.push_back(workload.as_array()[i]);
  return rest;
}

void test_telemetry_tail() {
  // The workload->agent sidecar protocol: complete JSONL lines ride the
  // metrics sample exactly once; partial lines wait; corrupt lines skip.
  // Every sample additionally carries the agent's own host hardware point.
  std::string dir = temp_dir();
  drunner::Executor ex(dir);
  std::string tfile = dir + "/telemetry/workload.jsonl";

  dj::Json m = ex.metrics();
  CHECK_EQ(sidecar_points(m).as_array().size(), static_cast<size_t>(0));  // no sidecar yet

  {
    std::ofstream f(tfile, std::ios::app);
    f << "{\"kind\": \"step\", \"step\": 1, \"step_time_s\": 0.5}\n";
    f << "{\"kind\": \"ma";  // a line mid-append — must NOT be consumed
  }
  m = ex.metrics();
  dj::Json pts = sidecar_points(m);
  CHECK_EQ(pts.as_array().size(), static_cast<size_t>(1));
  CHECK_EQ(pts.as_array()[0]["kind"].as_string(), std::string("step"));

  {
    std::ofstream f(tfile, std::ios::app);
    f << "rk\", \"event\": \"compile_end\"}\n";  // completes the partial line
    f << "this is not json\n";                     // corrupt: skipped, not fatal
    f << "{\"kind\": \"engine\", \"queue_depth\": 3}\n";
  }
  m = ex.metrics();
  pts = sidecar_points(m);
  CHECK_EQ(pts.as_array().size(), static_cast<size_t>(2));
  CHECK_EQ(pts.as_array()[0]["event"].as_string(), std::string("compile_end"));
  CHECK_EQ(pts.as_array()[1]["queue_depth"].as_int(), static_cast<int64_t>(3));

  m = ex.metrics();  // nothing new -> host sample only
  CHECK_EQ(sidecar_points(m).as_array().size(), static_cast<size_t>(0));

  // A single line larger than the per-sample window (a job writing junk to
  // the sidecar path) must be skipped, not wedge the tail forever.
  {
    std::ofstream f(tfile, std::ios::app);
    f << std::string(300 * 1024, 'x');  // 300KiB, no newline yet
  }
  m = ex.metrics();
  CHECK_EQ(sidecar_points(m).as_array().size(), static_cast<size_t>(0));  // window full, no newline -> skipped
  {
    std::ofstream f(tfile, std::ios::app);
    f << "\n{\"kind\": \"step\", \"step\": 9}\n";
  }
  m = ex.metrics();  // remnant of the junk line parses as garbage and skips;
  pts = sidecar_points(m);
  CHECK_EQ(pts.as_array().size(), static_cast<size_t>(1));
  CHECK_EQ(pts.as_array()[0]["step"].as_int(), static_cast<int64_t>(9));
}

void test_profile_control_file() {
  std::string dir = temp_dir();
  drunner::Executor ex(dir);
  // Not running yet: the request must be refused.
  bool threw = false;
  dj::Json req = dj::Json::object();
  req.set("seconds", 1.0);
  try {
    ex.profile(req);
  } catch (const std::exception&) {
    threw = true;
  }
  CHECK(threw);

  ex.submit(make_submit("prof", {"echo telemetry=$DSTACK_TPU_TELEMETRY_PATH", "sleep 5"}));
  ex.run();
  // Wait until the job reports running.
  auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(30);
  bool running = false;
  std::string logs;
  while (std::chrono::steady_clock::now() < deadline && !running) {
    dj::Json page = ex.pull(0);
    for (const auto& l : page["logs"].as_array()) logs += l["message"].as_string();
    for (const auto& s : page["job_states"].as_array()) {
      if (s["state"].as_string() == "running") running = true;
    }
    if (!running) usleep(50 * 1000);
  }
  CHECK(running);

  dj::Json ack = ex.profile(req);
  CHECK_EQ(ack["id"].as_int(), static_cast<int64_t>(1));
  CHECK(ack["artifact_dir"].as_string().find("/telemetry/profile/1") != std::string::npos);
  // The control file is published atomically with the command the emitter polls.
  std::ifstream ctl(dir + "/telemetry/workload.jsonl.ctl");
  CHECK(ctl.good());
  std::string content((std::istreambuf_iterator<char>(ctl)), std::istreambuf_iterator<char>());
  dj::Json cmd = dj::Json::parse(content);
  CHECK_EQ(cmd["cmd"].as_string(), std::string("profile"));
  CHECK_EQ(cmd["id"].as_int(), static_cast<int64_t>(1));

  ex.stop(true);
  RunResult r = pump_until_terminal(ex);
  CHECK_EQ(r.state, std::string("aborted"));
  // The env contract reached the job before it died.
  CHECK((logs + r.logs).find("telemetry=" + dir + "/telemetry/workload.jsonl")
        != std::string::npos);
}

void test_pty_exec_and_env() {
  drunner::Executor ex(temp_dir());
  ex.submit(make_submit("j1", {
      "echo marker-$((40+2))",
      "echo var=$MY_TEST_VAR secret=$MY_SECRET",
      "echo rank=$DSTACK_NODE_RANK slice=$MEGASCALE_SLICE_ID of=$MEGASCALE_NUM_SLICES",
  }));
  ex.run();
  RunResult r = pump_until_terminal(ex);
  CHECK_EQ(r.state, std::string("done"));
  CHECK_EQ(r.exit_status, 0);
  CHECK(r.logs.find("marker-42") != std::string::npos);
  CHECK(r.logs.find("var=var-value") != std::string::npos);
  CHECK(r.logs.find("secret=s3cret") != std::string::npos);
  // The TPU cluster contract reached the job (executor.cpp cluster_env).
  CHECK(r.logs.find("rank=3 slice=1 of=2") != std::string::npos);
}

void test_job_env_overrides_inherited_env() {
  // getenv returns the FIRST matching envp entry, so the agent must dedupe
  // with job-side precedence — otherwise a user's `env:` override silently
  // loses to whatever the host agent happened to inherit.
  setenv("DSTACK_ENV_PRECEDENCE_PROBE", "inherited", 1);
  drunner::Executor ex(temp_dir());
  dj::Json spec = dj::Json::object();
  spec.set("job_name", "jenv");
  dj::Json cmds = dj::Json::array();
  cmds.push_back("echo probe=$DSTACK_ENV_PRECEDENCE_PROBE");
  spec.set("commands", std::move(cmds));
  spec.set("image_name", "");
  dj::Json env = dj::Json::object();
  env.set("DSTACK_ENV_PRECEDENCE_PROBE", "from-job");
  spec.set("env", std::move(env));
  dj::Json body = dj::Json::object();
  body.set("job_spec", std::move(spec));
  ex.submit(body);
  ex.run();
  RunResult r = pump_until_terminal(ex);
  unsetenv("DSTACK_ENV_PRECEDENCE_PROBE");
  CHECK_EQ(r.state, std::string("done"));
  CHECK(r.logs.find("probe=from-job") != std::string::npos);
}

void test_failure_exit_status() {
  drunner::Executor ex(temp_dir());
  ex.submit(make_submit("j2", {"echo before", "exit 7", "echo after"}));
  ex.run();
  RunResult r = pump_until_terminal(ex);
  CHECK_EQ(r.state, std::string("failed"));
  CHECK_EQ(r.exit_status, 7);
  CHECK(r.logs.find("before") != std::string::npos);
  // set -e: nothing runs after the failing command.
  CHECK(r.logs.find("after") == std::string::npos);
}

void test_idempotent_submit_and_conflict() {
  drunner::Executor ex(temp_dir());
  dj::Json body = make_submit("j3", {"sleep 5"});
  ex.submit(body);
  ex.run();
  usleep(150 * 1000);
  // Re-submit of the SAME job while live: idempotent no-op (lost-response retry).
  ex.submit(body);
  // Re-run: idempotent too.
  ex.run();
  // A DIFFERENT job while one is live: hard conflict.
  bool threw = false;
  try {
    ex.submit(make_submit("other-job", {"true"}));
  } catch (const std::exception&) {
    threw = true;
  }
  CHECK(threw);
  ex.stop(true);
  RunResult r = pump_until_terminal(ex);
  CHECK_EQ(r.state, std::string("aborted"));
}

void test_stop_graceful_vs_abort() {
  {
    drunner::Executor ex(temp_dir());
    // Trap TERM so graceful stop is observable (handler exits 0).
    ex.submit(make_submit("j4", {"trap 'echo got-term; exit 0' TERM", "sleep 30"}));
    ex.run();
    usleep(300 * 1000);
    ex.stop(false);
    RunResult r = pump_until_terminal(ex);
    CHECK_EQ(r.state, std::string("terminated"));
  }
  {
    drunner::Executor ex(temp_dir());
    ex.submit(make_submit("j5", {"sleep 30"}));
    ex.run();
    usleep(300 * 1000);
    ex.stop(true);
    RunResult r = pump_until_terminal(ex);
    CHECK_EQ(r.state, std::string("aborted"));
  }
}

void test_stop_before_start_race() {
  // Stop landing between submit and the exec thread's first breath must still
  // terminate the job (executor.cpp stop()/exec_thread early-stop handshake).
  drunner::Executor ex(temp_dir());
  ex.submit(make_submit("j6", {"sleep 30"}));
  ex.run();
  ex.stop(false);  // no sleep: race the thread start
  RunResult r = pump_until_terminal(ex);
  CHECK(r.state == "terminated" || r.state == "aborted");
}

void test_pull_pagination() {
  drunner::Executor ex(temp_dir());
  // > kMaxEvents (5000) lines forces paging.
  ex.submit(make_submit("j7", {"for i in $(seq 1 6000); do echo line-$i; done"}));
  ex.run();
  // Wait for the terminal state WITHOUT consuming the stream (pull from past the
  // end reports state only), so the full 6000-line backlog is buffered and the
  // subsequent pump must page — deterministic regardless of host speed.
  auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(120);
  while (std::chrono::steady_clock::now() < deadline) {
    dj::Json probe = ex.pull((int64_t)1 << 60);
    const std::string& st = probe["state"].as_string();
    if (st == "done" || st == "failed") break;
    usleep(100 * 1000);
  }
  RunResult r = pump_until_terminal(ex, 120000);
  CHECK_EQ(r.state, std::string("done"));
  CHECK(r.saw_has_more);
  CHECK(r.logs.find("line-1\r\n") != std::string::npos || r.logs.find("line-1\n") != std::string::npos);
  CHECK(r.logs.find("line-6000") != std::string::npos);
  // No duplicates: count occurrences of a middle line.
  size_t count = 0, pos = 0;
  while ((pos = r.logs.find("line-3000\r", pos)) != std::string::npos) {
    ++count;
    pos += 1;
  }
  CHECK_EQ(count, static_cast<size_t>(1));
}

void test_submit_resets_after_terminal() {
  drunner::Executor ex(temp_dir());
  ex.submit(make_submit("j8", {"echo one"}));
  ex.run();
  RunResult first = pump_until_terminal(ex);
  CHECK_EQ(first.state, std::string("done"));
  // A new job after a terminal state is accepted (slice reuse); the event stream
  // continues — the server resumes from its stored offset, so the second job's
  // events live past the first's terminal marker.
  ex.submit(make_submit("j9", {"echo two"}));
  ex.run();
  RunResult r = pump_until_terminal(ex, 15000, first.final_offset);
  CHECK_EQ(r.state, std::string("done"));
  CHECK(r.logs.find("two") != std::string::npos);
}

void test_json_roundtrip() {
  const char* text = R"({"a": [1, 2.5, "x\ny", true, null], "nested": {"k": -3}})";
  dj::Json v = dj::Json::parse(text);
  CHECK_EQ(v["a"].as_array().size(), static_cast<size_t>(5));
  CHECK_EQ(v["a"].as_array()[2].as_string(), std::string("x\ny"));
  CHECK_EQ(v["nested"]["k"].as_int(), static_cast<int64_t>(-3));
  dj::Json round = dj::Json::parse(v.dump());
  CHECK_EQ(round.dump(), v.dump());
  bool threw = false;
  try {
    dj::Json::parse("{broken");
  } catch (const std::exception&) {
    threw = true;
  }
  CHECK(threw);
}

// Every malformed input must surface as std::runtime_error — never a
// different exception type (stod/stoul leak invalid_argument), never a
// crash (unbounded recursion), never silent acceptance.
#define CHECK_JSON_REJECTED(text)                                                \
  do {                                                                           \
    ++g_checks;                                                                  \
    bool ok = false;                                                             \
    try {                                                                        \
      dj::Json::parse(text);                                                     \
      fprintf(stderr, "FAIL %s:%d: %s accepted\n", __FILE__, __LINE__, #text);   \
    } catch (const std::runtime_error&) {                                        \
      ok = true;                                                                 \
    } catch (const std::exception& e) {                                          \
      fprintf(stderr, "FAIL %s:%d: %s threw %s, not runtime_error\n", __FILE__,  \
              __LINE__, #text, e.what());                                        \
    }                                                                            \
    if (!ok) ++g_failures;                                                       \
  } while (0)

void test_json_adversarial() {
  // Truncation at every structural point.
  CHECK_JSON_REJECTED("");
  CHECK_JSON_REJECTED("{");
  CHECK_JSON_REJECTED("[");
  CHECK_JSON_REJECTED("{\"a\"");
  CHECK_JSON_REJECTED("{\"a\":");
  CHECK_JSON_REJECTED("[1,");
  CHECK_JSON_REJECTED("\"unterminated");
  CHECK_JSON_REJECTED("\"ends with backslash\\");
  // Bad escapes — including non-hex \u, which stoul would mis-handle.
  CHECK_JSON_REJECTED("\"\\x\"");
  CHECK_JSON_REJECTED("\"\\u12\"");
  CHECK_JSON_REJECTED("\"\\uzzzz\"");
  CHECK_JSON_REJECTED("\"\\u12g4\"");
  // Numbers that break std::stod's contract.
  CHECK_JSON_REJECTED("-");
  CHECK_JSON_REJECTED("+");
  CHECK_JSON_REJECTED("1e999999");
  CHECK_JSON_REJECTED("--5");
  // Structure garbage.
  CHECK_JSON_REJECTED("{\"a\" 1}");
  CHECK_JSON_REJECTED("{1: 2}");
  CHECK_JSON_REJECTED("[1 2]");
  CHECK_JSON_REJECTED("{} trailing");
  CHECK_JSON_REJECTED("tru");
  CHECK_JSON_REJECTED("nul");
  // Hostile nesting: must throw, not overflow the stack.
  std::string deep(100000, '[');
  CHECK_JSON_REJECTED(deep);
  std::string deep_obj;
  for (int i = 0; i < 50000; ++i) deep_obj += "{\"a\":";
  CHECK_JSON_REJECTED(deep_obj);
  // Near the limit is still fine.
  std::string ok_nest;
  for (int i = 0; i < 100; ++i) ok_nest += "[";
  ok_nest += "1";
  for (int i = 0; i < 100; ++i) ok_nest += "]";
  dj::Json v = dj::Json::parse(ok_nest);
  CHECK(v.is_array());
  // Lone surrogates fold to U+FFFD instead of emitting invalid UTF-8.
  CHECK_EQ(dj::Json::parse("\"\\ud800\"").as_string(), std::string("\xEF\xBF\xBD"));
  CHECK_EQ(dj::Json::parse("\"\\udc00x\"").as_string(), std::string("\xEF\xBF\xBDx"));
  // And a valid pair still decodes.
  CHECK_EQ(dj::Json::parse("\"\\ud83d\\ude00\"").as_string(), std::string("\xF0\x9F\x98\x80"));
}

void test_docker_helpers() {
  CHECK_EQ(ddocker::url_escape("repo/img:1.0"), std::string("repo%2Fimg%3A1.0"));
  // base64 of the credentials object (dj::Json orders keys alphabetically).
  std::string auth = ddocker::encode_registry_auth("u", "p");
  CHECK_EQ(auth, std::string("eyJwYXNzd29yZCI6InAiLCJ1c2VybmFtZSI6InUifQ=="));
  CHECK_EQ(ddocker::encode_registry_auth("", ""), std::string(""));
  // The engine decodes X-Registry-Auth as base64url: a credential whose JSON
  // hits the 62nd code point must encode with '-' (url alphabet), never '+'.
  CHECK_EQ(ddocker::encode_registry_auth("u", "p>?~"),
           std::string("eyJwYXNzd29yZCI6InA-P34iLCJ1c2VybmFtZSI6InUifQ=="));
}

// A scripted Docker-Engine stand-in: accepts one AF_UNIX connection, reads
// the request head, writes `response` verbatim, closes. Lets the chunked
// transfer decoder in DockerClient::request face hostile daemon bytes.
struct FakeEngine {
  std::string sock_path;
  int listen_fd = -1;
  std::thread th;

  explicit FakeEngine(std::string response) {
    sock_path = temp_dir() + "/engine.sock";
    listen_fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, sock_path.c_str(), sizeof(addr.sun_path) - 1);
    bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    listen(listen_fd, 1);
    th = std::thread([fd = listen_fd, response = std::move(response)] {
      int c = accept(fd, nullptr, nullptr);
      if (c < 0) return;
      std::string req;
      char buf[4096];
      while (req.find("\r\n\r\n") == std::string::npos) {
        ssize_t n = read(c, buf, sizeof(buf));
        if (n <= 0) break;
        req.append(buf, static_cast<size_t>(n));
      }
      size_t off = 0;
      while (off < response.size()) {
        ssize_t n = write(c, response.data() + off, response.size() - off);
        if (n <= 0) break;
        off += static_cast<size_t>(n);
      }
      close(c);
    });
  }

  ~FakeEngine() {
    th.join();
    close(listen_fd);
    unlink(sock_path.c_str());
  }
};

// Streams logs from a scripted response; returns (ok, collected, error).
struct StreamResult {
  bool ok = false;
  std::string data;
  std::string error;
};

StreamResult stream_from(const std::string& response) {
  FakeEngine engine(response);
  ddocker::DockerClient client(engine.sock_path);
  StreamResult out;
  ddocker::StreamSink sink = [&out](const char* p, size_t n) { out.data.append(p, n); };
  try {
    client.stream_logs("c1", false, sink);
    out.ok = true;
  } catch (const ddocker::DockerError& e) {
    out.error = e.what();
  }
  return out;
}

void test_chunked_adversarial() {
  const std::string head = "HTTP/1.1 200 OK\r\nTransfer-Encoding: chunked\r\n\r\n";

  // Baseline: two well-formed chunks decode in order.
  StreamResult r = stream_from(head + "5\r\nhello\r\n6\r\n world\r\n0\r\n\r\n");
  CHECK(r.ok);
  CHECK_EQ(r.data, std::string("hello world"));

  // Truncated chunk: declares 10 bytes, delivers 3, closes. Must return
  // promptly with the partial data — no hang, no crash.
  r = stream_from(head + "A\r\nhel");
  CHECK(r.ok);
  CHECK_EQ(r.data, std::string("hel"));

  // Absurd chunk length must not buffer-until-timeout.
  r = stream_from(head + "FFFFFFFFFFFFFFF\r\nx");
  CHECK(r.ok);
  CHECK_EQ(r.data, std::string(""));

  // Garbage size line ends the stream instead of crashing.
  r = stream_from(head + "zz!!\r\nwhatever");
  CHECK(r.ok);
  CHECK_EQ(r.data, std::string(""));

  // Negative size.
  r = stream_from(head + "-5\r\nhello\r\n");
  CHECK(r.ok);
  CHECK_EQ(r.data, std::string(""));

  // Missing CRLF between chunks: first chunk lands, stream then ends.
  r = stream_from(head + "5\r\nhelloGARBAGE-NO-CRLF");
  CHECK(r.ok);
  CHECK_EQ(r.data, std::string("hello"));

  // Chunk size with trailing junk on the line (strtol prefix) still delivers.
  r = stream_from(head + "5;ext=1\r\nhello\r\n0\r\n\r\n");
  CHECK(r.ok);
  CHECK_EQ(r.data, std::string("hello"));

  // Oversized headers (2 MiB, no terminator) trip the buffering cap and fail
  // with the client's own error instead of ballooning memory.
  std::string huge = "HTTP/1.1 200 OK\r\n";
  huge.append(2 * 1024 * 1024, 'A');
  r = stream_from(huge);
  CHECK(!r.ok);
  CHECK(r.error.find("truncated") != std::string::npos);

  // No response at all.
  r = stream_from("");
  CHECK(!r.ok);

  // Malformed JSON body on a parsed endpoint surfaces as DockerError.
  {
    FakeEngine engine(
        "HTTP/1.1 200 OK\r\nContent-Length: 9\r\n\r\n{not json");
    ddocker::DockerClient client(engine.sock_path);
    bool threw = false;
    try {
      client.inspect_container("c1");
    } catch (const ddocker::DockerError& e) {
      threw = std::string(e.what()).find("malformed JSON") != std::string::npos;
    }
    CHECK(threw);
  }
}

void test_tpu_metrics_parse() {
  std::string text =
      "# HELP duty_cycle x\n"
      "duty_cycle{chip=\"0\"} 90\n"
      "duty_cycle{chip=\"1\"} 70\n"
      "memory_used{chip=\"0\"} 100\n"
      "memory_used{chip=\"1\"} 200\n"
      "memory_total{chip=\"0\"} 1000\n"
      "unrelated_metric 5\n";
  dj::Json m = dtpu::parse_prometheus_tpu(text);
  CHECK_EQ(m["duty_cycle_percent"].as_number(), 80.0);
  CHECK_EQ(m["hbm_usage_bytes"].as_number(), 300.0);
  CHECK_EQ(m["hbm_total_bytes"].as_number(), 1000.0);
  CHECK(dtpu::parse_prometheus_tpu("nothing_useful 1\n").is_null());
}

}  // namespace

int main() {
  // The agent proper ignores SIGPIPE (main.cpp); the fake engine's scripted
  // writes against an early-closing client need the same here.
  signal(SIGPIPE, SIG_IGN);
  test_json_roundtrip();
  test_json_adversarial();
  test_docker_helpers();
  test_chunked_adversarial();
  test_tpu_metrics_parse();
  test_telemetry_tail();
  test_profile_control_file();
  test_pty_exec_and_env();
  test_job_env_overrides_inherited_env();
  test_failure_exit_status();
  test_idempotent_submit_and_conflict();
  test_stop_graceful_vs_abort();
  test_stop_before_start_race();
  test_pull_pagination();
  test_submit_resets_after_terminal();
  if (g_failures == 0) {
    printf("OK: %d checks passed\n", g_checks);
    return 0;
  }
  fprintf(stderr, "FAILED: %d of %d checks\n", g_failures, g_checks);
  return 1;
}
