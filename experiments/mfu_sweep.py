"""MFU sweep harness (round 3): times train-step variants on the real chip.

Usage: python experiments/mfu_sweep.py [variant ...]

Each variant is timed over `STEPS` individually-dispatched steps with a final
device sync per step (float(loss) — block_until_ready is unreliable through the
PJRT relay). Reports per-step median and best, and counted MFU
(flops_per_token * tokens / time / peak).

Findings land in BASELINE.md.
"""

from __future__ import annotations

import statistics
import sys
import time

import jax
import jax.numpy as jnp

from dstack_tpu.workloads import train as train_lib
from dstack_tpu.workloads.config import LlamaConfig

PEAK = 197e12  # v5e bf16


def time_variant(name: str, cfg: LlamaConfig, batch: int, steps: int = 10) -> dict:
    seq = cfg.max_seq_len
    optimizer = train_lib.make_optimizer()
    state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer)
    step_fn = train_lib.make_train_step(cfg, optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0, cfg.vocab_size)

    t_compile = time.perf_counter()
    state, m = step_fn(state, tokens, targets)
    loss0 = float(m["loss"])
    compile_s = time.perf_counter() - t_compile

    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        state, m = step_fn(state, tokens, targets)
        _ = float(m["loss"])
        times.append(time.perf_counter() - t0)

    med = statistics.median(times)
    best = min(times)
    n_tok = batch * seq
    fpt = cfg.flops_per_token(seq)
    out = {
        "variant": name,
        "compile_s": round(compile_s, 1),
        "med_ms": round(med * 1e3, 1),
        "best_ms": round(best * 1e3, 1),
        "mfu_med": round(fpt * n_tok / med / PEAK * 100, 2),
        "mfu_best": round(fpt * n_tok / best / PEAK * 100, 2),
        "tok_s_med": round(n_tok / med),
        "loss0": round(loss0, 3),
    }
    print(out, flush=True)
    return out


BASE = dict(
    vocab_size=32000, d_model=1536, n_layers=12, n_heads=12, n_kv_heads=12,
    d_ff=4096, max_seq_len=2048,
)

VARIANTS = {
    # round-2 baseline: full remat, blockwise attention
    "r2_baseline": (LlamaConfig(**BASE, remat=True, remat_policy="full"), 8),
    "plain_attn_b8": (LlamaConfig(**BASE, remat=True, remat_policy="full",
                                  attn_impl="plain"), 8),
    "plain_chunkce_b8": (LlamaConfig(**BASE, remat=True, remat_policy="full",
                                     attn_impl="plain", loss_chunk=512), 8),
    "plain_dots_chunkce_b8": (LlamaConfig(**BASE, remat=True, remat_policy="dots",
                                          attn_impl="plain", loss_chunk=512), 8),
    "plain_noremat_chunkce_b8": (LlamaConfig(**BASE, remat=False,
                                             attn_impl="plain", loss_chunk=512), 8),
    "plain_noremat_chunkce_b4": (LlamaConfig(**BASE, remat=False,
                                             attn_impl="plain", loss_chunk=512), 4),
    "saveproj_b8": (LlamaConfig(**BASE, remat=True, remat_policy="save_proj",
                                attn_impl="plain", loss_chunk=512), 8),
    "saveproj_b4": (LlamaConfig(**BASE, remat=True, remat_policy="save_proj",
                                attn_impl="plain", loss_chunk=512), 4),
    "saveproj_block_b8": (LlamaConfig(**BASE, remat=True, remat_policy="save_proj",
                                      attn_impl="blockwise", loss_chunk=512), 8),
    "flash_full_b8": (LlamaConfig(**BASE, remat=True, remat_policy="full",
                                  attn_impl="flash", loss_chunk=512), 8),
    "flash_saveproj_b8": (LlamaConfig(**BASE, remat=True, remat_policy="save_proj",
                                      attn_impl="flash", loss_chunk=512), 8),
    "flash_saveproj_b4": (LlamaConfig(**BASE, remat=True, remat_policy="save_proj",
                                      attn_impl="flash", loss_chunk=512), 4),
    "flash_full_b16": (LlamaConfig(**BASE, remat=True, remat_policy="full",
                                   attn_impl="flash", loss_chunk=512), 16),
    "flash_full_b32_lc256": (LlamaConfig(**BASE, remat=True, remat_policy="full",
                                         attn_impl="flash", loss_chunk=256), 32),
    # wider geometry: MXU prefers K,N >= 2048 (mm sweep: 191 vs 178 TF/s)
    "wide_d2048_b8": (LlamaConfig(vocab_size=32000, d_model=2048, n_layers=8,
                                  n_heads=16, n_kv_heads=16, d_ff=8192, max_seq_len=2048,
                                  remat=True, remat_policy="full", attn_impl="flash",
                                  loss_chunk=512), 8),
    "wide_d2048_b16": (LlamaConfig(vocab_size=32000, d_model=2048, n_layers=8,
                                   n_heads=16, n_kv_heads=16, d_ff=8192, max_seq_len=2048,
                                   remat=True, remat_policy="full", attn_impl="flash",
                                   loss_chunk=512), 16),
}


if __name__ == "__main__":
    names = sys.argv[1:] or list(VARIANTS)
    for n in names:
        cfg, b = VARIANTS[n]
        try:
            time_variant(n, cfg, b)
        except Exception as e:  # HBM OOM arrives as opaque compile failure via relay
            print({"variant": n, "error": str(e)[:200]}, flush=True)
