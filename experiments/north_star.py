"""North-star measurements the reference cannot make: apply -> first-training-step
latency (with a budget breakdown) and a model served through the in-server proxy,
both against a REAL server process + the REAL native agent on this host's
accelerator (BASELINE.md "North-star targets").

Run:  python experiments/north_star.py [--skip-serve] [--skip-cpu]
Emits one JSON object per measurement and a summary block for BASELINE.md.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import re
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The job-side training script: prints wall-clock MARK lines the measurement
# parses out of the run's logs (same clock as the client: one host).
TRAIN_SNIPPET = r"""
import time
print("MARK py_start %.6f" % time.time(), flush=True)
import jax, jax.numpy as jnp
print("MARK jax_imported %.6f" % time.time(), flush=True)
from dstack_tpu.workloads import train as train_lib
from dstack_tpu.workloads.config import get_config
dev = jax.devices()[0]
print("MARK devices_ready %.6f %s" % (time.time(), dev.device_kind), flush=True)
cfg = get_config("{config}")
opt = train_lib.make_optimizer()
state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt)
step = train_lib.make_train_step(cfg, opt)
toks = jax.random.randint(jax.random.PRNGKey(1), (2, 256), 0, cfg.vocab_size)
print("MARK init_done %.6f" % time.time(), flush=True)
state, m = step(state, toks, toks)
loss = float(m["loss"])
print("MARK step1_done %.6f loss=%.4f" % (time.time(), loss), flush=True)
for _ in range({extra_steps}):
    state, m = step(state, toks, toks)
float(m["loss"])
print("MARK steps_done %.6f" % time.time(), flush=True)
"""

SERVE_SNIPPET = r"""
import json, threading, time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
import jax, jax.numpy as jnp
from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads.config import get_config

cfg = get_config("test")
params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
fwd = jax.jit(lambda p, t: model_lib.forward(p, t, cfg))
warm = jnp.zeros((1, 128), jnp.int32)
fwd(params, warm).block_until_ready()  # compile before accepting traffic
lock = threading.Lock()

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        t0 = time.perf_counter()
        toks = jnp.zeros((1, 128), jnp.int32)
        with lock:  # one chip; serialize device work
            out = fwd(params, toks)
            nxt = int(jnp.argmax(out[0, -1]))
        body = json.dumps({"next_token": nxt,
                           "device_ms": round(1e3 * (time.perf_counter() - t0), 2),
                           "device": jax.devices()[0].device_kind}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
    def log_message(self, *a):
        pass

import os
# Services bind the port the control plane assigns: DSTACK_SERVICE_PORT (equal
# to the configured port on dedicated hosts, ephemeral on shared-host local).
ThreadingHTTPServer(("0.0.0.0", int(os.environ.get("DSTACK_SERVICE_PORT", "8199"))), H).serve_forever()
"""


def start_server(workdir: str, port: int) -> tuple[subprocess.Popen, str, str]:
    env = dict(os.environ)
    env["HOME"] = workdir
    env["DSTACK_TPU_SERVER_DIR"] = os.path.join(workdir, "server")
    env["JAX_PLATFORMS"] = "cpu"  # the SERVER never needs the chip; jobs do
    proc = subprocess.Popen(
        [sys.executable, "-m", "dstack_tpu.cli.main", "server",
         "--host", "127.0.0.1", "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, cwd=workdir,
    )
    token = None
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stdout.readline().decode(errors="replace")
        m = re.search(r"admin token: (\w+)", line)
        if m:
            token = m.group(1)
        if "Running on" in line:
            break
    assert token, "server did not print a token"
    threading_drain(proc)
    return proc, f"http://127.0.0.1:{port}", token


def threading_drain(proc):
    import threading

    def drain():
        for _ in iter(proc.stdout.readline, b""):
            pass

    threading.Thread(target=drain, daemon=True).start()


def measure_apply_latency(client, config: str, job_env: dict, extra_steps: int = 4) -> dict:
    """Submit a task and decompose submit -> first-step into its budget."""
    code = TRAIN_SNIPPET.replace("{config}", config).replace(
        "{extra_steps}", str(extra_steps)
    )
    name = f"ns-apply-{config.replace('_', '-')}"
    spec = {
        "run_name": name,
        "configuration": {
            "type": "task",
            "commands": [f"python3 - <<'EOF'\n{code}\nEOF"],
            "env": job_env,
        },
    }
    t0 = time.time()
    client.runs.submit(spec)
    transitions = {}
    status = "submitted"
    deadline = time.time() + 600
    while time.time() < deadline:
        run = client.runs.get(name)
        if run.status.value != status:
            transitions[run.status.value] = time.time()
            status = run.status.value
        if status in ("done", "failed", "terminated"):
            break
        time.sleep(0.05)
    assert status == "done", f"run ended {status}"
    logs = client.logs.poll(name, start_line=0)
    text = "".join(ev.message for ev in logs.logs)
    marks = dict(re.findall(r"MARK (\w+) ([0-9.]+)", text))
    marks = {k: float(v) for k, v in marks.items()}
    device = (re.search(r"devices_ready [0-9.]+ (.+)", text) or [None, "unknown"])[1]
    total = marks["step1_done"] - t0
    out = {
        "metric": "apply_to_first_train_step_seconds",
        "config": config,
        "device": device.strip(),
        "total_s": round(total, 2),
        "budget_s": {
            # One clock (same host): submit -> the job's python running covers
            # queue + scheduling + slice provision + agent spawn + code sync.
            "orchestration_submit_to_job_python": round(marks["py_start"] - t0, 2),
            "jax_import": round(marks["jax_imported"] - marks["py_start"], 2),
            "device_init": round(marks["devices_ready"] - marks["jax_imported"], 2),
            "param_init_compile": round(marks["init_done"] - marks["devices_ready"], 2),
            "step_compile_plus_step1": round(marks["step1_done"] - marks["init_done"], 2),
        },
        "steady_step_s": round((marks["steps_done"] - marks["step1_done"]) / extra_steps, 3),
    }
    client.runs.delete([name])
    return out


def measure_served_model(client, url: str, token: str, n_requests: int = 200,
                         concurrency: int = 8) -> dict:
    import urllib.request

    name = "ns-serve"
    spec = {
        "run_name": name,
        "configuration": {
            "type": "service",
            "port": 8199,
            "commands": [f"python3 - <<'EOF'\n{SERVE_SNIPPET}\nEOF"],
        },
    }
    client.runs.submit(spec)
    proxy = f"{url}/proxy/services/main/{name}/"
    req = urllib.request.Request(proxy, headers={"Authorization": f"Bearer {token}"})
    deadline = time.time() + 300
    up = False
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                if r.status == 200:
                    body = json.loads(r.read())
                    up = True
                    break
        except Exception:
            time.sleep(1.0)
    assert up, "service never answered through the proxy"

    def one(_):
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            r.read()
        return time.perf_counter() - t0

    for _ in range(5):  # warm the tunnel/proxy path
        one(0)
    t_start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(concurrency) as ex:
        lat = list(ex.map(one, range(n_requests)))
    wall = time.perf_counter() - t_start
    lat.sort()
    out = {
        "metric": "served_model_through_proxy",
        "device": body.get("device", "unknown"),
        "requests": n_requests,
        "concurrency": concurrency,
        "rps": round(n_requests / wall, 1),
        "p50_ms": round(1e3 * lat[len(lat) // 2], 1),
        "p95_ms": round(1e3 * lat[int(len(lat) * 0.95)], 1),
        "device_forward_ms": body.get("device_ms"),
    }
    client.runs.stop([name])
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-serve", action="store_true")
    ap.add_argument("--skip-cpu", action="store_true")
    ap.add_argument("--port", type=int, default=39833)
    args = ap.parse_args()

    from dstack_tpu.api.client import Client

    results = []
    with tempfile.TemporaryDirectory(prefix="north-star-") as workdir:
        proc, url, token = start_server(workdir, args.port)
        try:
            client = Client(url, token, "main", timeout=60.0)
            # 1) apply -> first step on the accelerator (tiny config: the number
            # is the ORCHESTRATION overhead; compile time is reported separately).
            results.append(measure_apply_latency(client, "test", {"JAX_PLATFORMS": ""}))
            print(json.dumps(results[-1]), flush=True)
            # Warm pool: the slice from the first run is idle and gets reused,
            # isolating the scheduler+agent path from cloud provisioning.
            warm = measure_apply_latency(client, "test", {"JAX_PLATFORMS": ""})
            warm["metric"] = "apply_to_first_train_step_seconds_warm_pool"
            results.append(warm)
            print(json.dumps(results[-1]), flush=True)
            if not args.skip_cpu:
                # 2) GPT-2-124M single-node CPU task (north-star row 3).
                # Genuine CPU: JAX_PLATFORMS=cpu, and PALLAS_AXON_POOL_IPS
                # cleared so a TPU-relay sitecustomize (if present) cannot pin
                # the accelerator backend under the job.
                results.append(
                    measure_apply_latency(
                        client,
                        "gpt2_125m",
                        {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
                        extra_steps=2,
                    )
                )
                print(json.dumps(results[-1]), flush=True)
            if not args.skip_serve:
                # 3) model served through the in-server proxy (north-star row 5).
                results.append(measure_served_model(client, url, token))
                print(json.dumps(results[-1]), flush=True)
        finally:
            proc.terminate()
            proc.wait(timeout=10)
    print(json.dumps({"summary": results}))


if __name__ == "__main__":
    main()
