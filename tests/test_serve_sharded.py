"""Multi-chip sharded serving (PR 16): mesh-aware prefill/decode and the
checkpoint re-shard-on-restore path into the engine.

The invariant everything leans on: tensor-parallel sharding is a PLACEMENT
optimization — it must never change a single emitted token. Every sharded
engine here is compared against the meshless engine at the SAME EngineConfig
(itself pinned against a full-context greedy reference by
test_serve_engine.py), in fp32 on CPU so argmax ties can't blur the
comparison. The restore tests pin the other acceptance bar: a checkpoint
saved on a dp/fsdp TRAIN mesh restores into the tp SERVE layout bit-exactly,
including the weight-only int8 layout quantized after restore.

Numerics on the virtual 8-device CPU mesh (conftest). TINY matches
test_serve_engine/test_serve_tier2 so the meshless reference compilations
are shared; the sharded fns compile once per (cfg, mesh)."""

import jax
import numpy as np
import pytest

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import serve as serve_lib
from dstack_tpu.workloads import sharding as sharding_lib
from dstack_tpu.workloads import train as train_lib
from dstack_tpu.workloads.checkpoint import CheckpointManager
from dstack_tpu.workloads.config import get_config
from dstack_tpu.workloads.sharding import make_mesh, make_serve_mesh

TINY = get_config(
    "test", n_layers=2, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
    vocab_size=251, max_seq_len=128, dtype="float32", param_dtype="float32",
    remat=False,
)

PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9], [10, 11, 12, 13, 14, 15, 16]]

# 18 tokens = 2 full pages of 8 + a tail (test_serve_tier2's shared prefix):
# long enough that prefix matching covers whole blocks.
SHARED_PREFIX = [5, 9, 13, 2, 44, 17, 81, 3, 7, 7, 101, 55, 13, 24, 9, 16,
                 31, 8]

# The preemption geometry shared with test_serve_engine/test_serve_tier2:
# pool sized so decode growth forces preemption of the youngest request.
PREEMPT_POOL = dict(page_size=4, num_pages=7, max_batch=3, max_seq=96)
PREEMPT_PROMPTS = [[i + 1, i + 2, i + 3, i + 4, i + 5] for i in (0, 10, 20)]


@pytest.fixture(scope="module")
def params():
    return model_lib.init_params(TINY, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def tp2_mesh():
    # TINY has n_kv_heads=2: tp=2 is the widest tensor-parallel degree it
    # validates at. One mesh object for the whole module so the jitted fns
    # (memoized per (cfg, quant, impl, mesh)) compile exactly once.
    return make_serve_mesh(2, devices=jax.devices()[:2])


def make_engine(params, mesh=None, **overrides) -> serve_lib.ServeEngine:
    kwargs = dict(page_size=8, num_pages=32, max_batch=4, max_seq=128)
    kwargs.update(overrides)
    return serve_lib.ServeEngine(
        TINY, serve_lib.EngineConfig(**kwargs), params=params, mesh=mesh
    )


def drain(engine, limit=3000):
    steps = 0
    while engine.has_work():
        engine.step()
        steps += 1
        assert steps < limit, "engine never drained"
    return steps


def run_pair(params, mesh, prompts, max_new, **cfg):
    """Token streams from a sharded and a meshless engine at the same
    EngineConfig, same submission order."""
    out = []
    for m in (mesh, None):
        engine = make_engine(params, mesh=m, **cfg)
        reqs = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
        drain(engine)
        out.append([r.tokens for r in reqs])
    return out


class TestShardedEquivalence:
    def test_weights_and_pages_actually_sharded(self, params, tp2_mesh):
        """Not a replicated copy: column-parallel projections and the KV page
        pool each live split across the pair of devices."""
        engine = make_engine(params, mesh=tp2_mesh)
        assert engine.mesh_desc == "dd1xtp2"
        wq = engine._serve_params["wq"]
        assert len(wq.sharding.device_set) == 2
        shard_shape = wq.sharding.shard_shape(wq.shape)
        assert shard_shape[-1] == wq.shape[-1] // 2  # heads split over tp
        kp = engine.k_pages
        assert kp.sharding.shard_shape(kp.shape)[3] == kp.shape[3] // 2
        # Logits come back replicated: host-side argmax sees the full vocab.
        assert engine._serve_params["embed"].sharding.is_fully_replicated

    @pytest.mark.parametrize(
        "prefix_cache,spec_tokens",
        [(False, 0), (True, 0), (False, 2), (True, 2)],
        ids=["tier1", "prefix", "spec", "prefix+spec"],
    )
    def test_token_identical_to_meshless(self, params, tp2_mesh, prefix_cache,
                                         spec_tokens):
        """The matrix: sharded == meshless across the tier-2 feature grid.
        Shared-prefix prompts make the prefix-cache variants exercise real
        cross-request hits on the sharded page pool."""
        prompts = PROMPTS + [SHARED_PREFIX + [40 + i] for i in range(3)]
        sharded, meshless = run_pair(
            params, tp2_mesh, prompts, 8,
            prefill_chunk=4, prefix_cache=prefix_cache,
            spec_tokens=spec_tokens,
        )
        assert sharded == meshless

    def test_token_identical_under_preemption(self, params, tp2_mesh):
        """Preempt/resume refolds generated tokens into the prompt and
        re-prefills — on the sharded engine that path must replay through the
        sharded chunk fn to the same streams."""
        sharded, meshless = run_pair(
            params, tp2_mesh, PREEMPT_PROMPTS, 20,
            prefill_chunk=4, prefix_cache=True, **PREEMPT_POOL
        )
        engine = make_engine(params, mesh=tp2_mesh, prefill_chunk=4,
                             prefix_cache=True, **PREEMPT_POOL)
        reqs = [engine.submit(p, max_new_tokens=20) for p in PREEMPT_PROMPTS]
        drain(engine)
        assert max(r.preemptions for r in reqs) >= 1, (
            "pool was sized to force preemption"
        )
        assert sharded == meshless

    def test_int8_token_identical_to_meshless_int8(self, params, tp2_mesh):
        """Weight-only int8 on the sharded engine: quantized layout shards
        over tp and still matches the meshless int8 engine token for token."""
        sharded, meshless = run_pair(
            params, tp2_mesh, PROMPTS, 6, quant="int8"
        )
        assert sharded == meshless
        engine = make_engine(params, mesh=tp2_mesh, quant="int8")
        wq_q = engine._serve_params["wq_q"]
        assert wq_q.sharding.shard_shape(wq_q.shape)[-1] == wq_q.shape[-1] // 2


# tp=4 needs every sharded axis divisible by 4 (validate_serve_mesh):
# n_kv_heads=4 is the one knob TINY lacks.
RESTORE_CFG = get_config(
    "test", n_layers=2, d_model=64, n_heads=4, n_kv_heads=4, d_ff=128,
    vocab_size=251, max_seq_len=32, dtype="float32", param_dtype="float32",
    remat=False,
)


@pytest.fixture(scope="module")
def saved_train_checkpoint(tmp_path_factory):
    """One dp2/fsdp4 TrainState checkpoint shared by the restore tests."""
    ckpt_dir = tmp_path_factory.mktemp("ckpt")
    mesh = make_mesh(dp=2, fsdp=4, devices=jax.devices()[:8])
    optimizer = train_lib.make_optimizer()
    state = train_lib.init_train_state(
        RESTORE_CFG, jax.random.PRNGKey(0), optimizer, mesh
    )
    mgr = CheckpointManager(str(ckpt_dir), process_index=0, process_count=1)
    mgr.save(3, state, mesh_shape=dict(mesh.shape), block=True)
    assert mgr.save_errors == 0, mgr.last_error
    host = {k: np.asarray(v) for k, v in state.params.items()}
    return str(ckpt_dir), host, dict(mesh.shape)


class TestReshardOnRestore:
    def test_dp2_fsdp4_to_tp4_bit_identical(self, saved_train_checkpoint):
        """The tentpole acceptance bar: a train-mesh checkpoint lands in the
        tp4 serve layout with every param leaf bit-identical — and only the
        .params subtree was materialized (the template carries no optimizer
        moments)."""
        ckpt_dir, host, train_shape = saved_train_checkpoint
        serve_mesh = make_serve_mesh(4, devices=jax.devices()[:4])
        params, manifest = serve_lib.load_serve_params(
            ckpt_dir, RESTORE_CFG, mesh=serve_mesh
        )
        assert manifest["mesh"] == train_shape
        assert set(params) == set(host)
        shardings = sharding_lib.serve_param_sharding(serve_mesh, "none")
        for key, leaf in params.items():
            assert np.array_equal(np.asarray(leaf), host[key]), (
                f"{key} diverged across the reshard"
            )
            assert leaf.sharding == shardings[key], key
        # Actually distributed, not 4 replicas: a column-parallel projection
        # holds 1/4 of its last axis per device.
        wq = params["wq"]
        assert wq.sharding.shard_shape(wq.shape)[-1] == wq.shape[-1] // 4

    def test_restore_then_int8_matches_meshless_quantization(
        self, saved_train_checkpoint
    ):
        """quant="int8" on restore: the sharded quantized layout is leaf-wise
        bit-identical to quantizing the original host tree, and the fp
        projections are gone (the layout the engine adopts as-is)."""
        ckpt_dir, host, _ = saved_train_checkpoint
        serve_mesh = make_serve_mesh(4, devices=jax.devices()[:4])
        params, _ = serve_lib.load_serve_params(
            ckpt_dir, RESTORE_CFG, mesh=serve_mesh, quant="int8"
        )
        ref = serve_lib.quantize_serve_params(
            {k: jax.numpy.asarray(v) for k, v in host.items()}
        )
        assert set(params) == set(ref)
        assert "wq" not in params and "lm_head" not in params
        for key in ref:
            assert np.array_equal(np.asarray(params[key]), np.asarray(ref[key])), (
                f"int8 leaf {key} diverged from meshless quantization"
            )

    def test_meshless_restore_matches_host_tree(self, saved_train_checkpoint):
        """mesh=None (single-chip dev serving) reads the same bytes."""
        ckpt_dir, host, _ = saved_train_checkpoint
        params, _ = serve_lib.load_serve_params(ckpt_dir, RESTORE_CFG)
        for key, leaf in params.items():
            assert np.array_equal(np.asarray(leaf), host[key]), key

    def test_restored_params_serve_identically(self, saved_train_checkpoint):
        """End to end: an engine built from the tp4-restored params emits the
        same tokens as one built from the original host tree, meshless."""
        ckpt_dir, host, _ = saved_train_checkpoint
        serve_mesh = make_serve_mesh(4, devices=jax.devices()[:4])
        params, _ = serve_lib.load_serve_params(
            ckpt_dir, RESTORE_CFG, mesh=serve_mesh
        )
        ecfg = serve_lib.EngineConfig(page_size=8, num_pages=16, max_batch=2,
                                      max_seq=32)
        sharded = serve_lib.ServeEngine(
            RESTORE_CFG, ecfg, params=params, mesh=serve_mesh
        )
        meshless = serve_lib.ServeEngine(
            RESTORE_CFG, ecfg,
            params={k: jax.numpy.asarray(v) for k, v in host.items()},
        )
        streams = []
        for engine in (sharded, meshless):
            reqs = [engine.submit(p, max_new_tokens=4) for p in PROMPTS[:2]]
            drain(engine)
            streams.append([r.tokens for r in reqs])
        assert streams[0] == streams[1]
