"""Regression guard for the north-star orchestration-overhead number.

BASELINE.md records apply -> first-train-step with a budget whose
orchestration segment (submit -> the job's python process running: queue +
schedule + slice provision + agent spawn + code sync) measured ~2.8 s on the
local backend (experiments/north_star.py). MFU has a bench floor and scheduler
throughput has a scale guard; this enforces the third north-star the same way
(VERDICT r4 #6): a conservative 10 s ceiling on shared 1-CPU CI hosts, loose
enough to never flake, tight enough that an accidental sleep/poll regression
in the submit path fails loudly."""

import asyncio
import re
import time

from dstack_tpu.server.services import logs as logs_service
from tests.common import api_server
from tests.test_services import _drive

CEILING_S = 10.0


class TestNorthStarGuard:
    async def test_submit_to_job_python_under_ceiling(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                t0 = time.time()
                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "ns-guard",
                            "configuration": {
                                "type": "task",
                                "commands": [
                                    "python3 -c \"import time;"
                                    " print('PYSTART %.6f' % time.time(), flush=True)\""
                                ],
                            },
                        }
                    },
                )
                run = None
                deadline = time.time() + CEILING_S + 20  # let a slow run FINISH
                while time.time() < deadline:
                    await _drive(api)
                    run = await api.post(
                        "/api/project/main/runs/get", {"run_name": "ns-guard"}
                    )
                    if run["status"] in ("done", "failed", "terminated"):
                        break
                    await asyncio.sleep(0.05)
                assert run is not None and run["status"] == "done", run

                logs = await api.post(
                    "/api/project/main/logs/poll", {"run_name": "ns-guard"}
                )
                text = "".join(e["message"] for e in logs["logs"])
                match = re.search(r"PYSTART ([0-9.]+)", text)
                assert match, f"job never printed PYSTART: {text!r}"
                overhead = float(match.group(1)) - t0
                assert 0 < overhead < CEILING_S, (
                    f"submit -> job python took {overhead:.2f}s"
                    f" (north-star budget segment; ceiling {CEILING_S}s)"
                )
        finally:
            logs_service.set_log_storage(None)
