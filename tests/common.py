"""Shared test harness: in-memory server + authed client.

Parity with the reference's test strategy (SURVEY §4): single-process server, real DB
(sqlite in-memory), real services; clouds replaced by the mock TPU backend."""

from __future__ import annotations

import contextlib
import json
from typing import Any, AsyncIterator, Optional

from aiohttp.test_utils import TestClient, TestServer

from dstack_tpu.server.app import create_app


class ApiClient:
    """Thin wrapper: POST json with auth header, parse json, expose raw responses."""

    def __init__(self, client: TestClient, token: str):
        self.client = client
        self.token = token

    async def post(
        self,
        path: str,
        body: Optional[dict] = None,
        token: Optional[str] = None,
        expect: Optional[int] = 200,
    ) -> Any:
        headers = {}
        tok = token if token is not None else self.token
        if tok:
            headers["Authorization"] = f"Bearer {tok}"
        resp = await self.client.post(path, json=body or {}, headers=headers)
        text = await resp.text()
        if expect is not None:
            assert resp.status == expect, f"{path} -> {resp.status}: {text[:500]}"
        return json.loads(text) if text else None


@contextlib.asynccontextmanager
async def api_server(run_background_tasks: bool = False) -> AsyncIterator[ApiClient]:
    app = create_app(db_path=":memory:", run_background_tasks=run_background_tasks)
    server = TestServer(app)
    client = TestClient(server)
    await client.start_server()
    try:
        yield ApiClient(client, app["admin_token"])
    finally:
        await client.close()


TASK_SPEC = {
    "run_spec": {
        "run_name": "test-run",
        "configuration": {
            "type": "task",
            "commands": ["echo hello"],
        },
    }
}


def tpu_task_spec(run_name: str = "tpu-run", tpu: str = "v5p-16", **conf) -> dict:
    return {
        "run_spec": {
            "run_name": run_name,
            "configuration": {
                "type": "task",
                "commands": ["python train.py"],
                "resources": {"tpu": tpu},
                **conf,
            },
        }
    }
