"""Shared test harness: in-memory server + authed client.

Parity with the reference's test strategy (SURVEY §4): single-process server, real DB
(sqlite in-memory), real services; clouds replaced by the mock TPU backend."""

from __future__ import annotations

import contextlib
import json
from typing import Any, AsyncIterator, Optional

from aiohttp.test_utils import TestClient, TestServer

from dstack_tpu.server.app import create_app


class ApiClient:
    """Thin wrapper: POST json with auth header, parse json, expose raw responses."""

    def __init__(self, client: TestClient, token: str, app=None):
        self.client = client
        self.token = token
        self.app = app

    @property
    def db(self):
        return self.app["db"]

    async def post(
        self,
        path: str,
        body: Optional[dict] = None,
        token: Optional[str] = None,
        expect: Optional[int] = 200,
    ) -> Any:
        headers = {}
        tok = token if token is not None else self.token
        if tok:
            headers["Authorization"] = f"Bearer {tok}"
        resp = await self.client.post(path, json=body or {}, headers=headers)
        text = await resp.text()
        if expect is not None:
            assert resp.status == expect, f"{path} -> {resp.status}: {text[:500]}"
        return json.loads(text) if text else None


@contextlib.asynccontextmanager
async def api_server(
    run_background_tasks: bool = False, db_path: str = ":memory:"
) -> AsyncIterator[ApiClient]:
    app = create_app(db_path=db_path, run_background_tasks=run_background_tasks)
    server = TestServer(app)
    client = TestClient(server)
    await client.start_server()
    try:
        yield ApiClient(client, app["admin_token"], app=app)
    finally:
        await client.close()


class FakeRunnerClient:
    """Scripted stand-in for the runner agent (parity: mocked shim/runner HTTP clients in
    reference scheduler tests, test_process_running_jobs.py)."""

    # Class-level registry shared across get_runner_client calls: key -> instance.
    registry: dict = {}
    healthy: bool = True

    def __init__(self, key: str):
        self.key = key
        self.submitted = None
        self.cluster_info = None
        self.code = None
        self.ran = False
        self.stopped = False
        self.aborted = False
        self.pulls = 0
        # Script: list of pull results to return in order; the last repeats.
        self.script = self.default_script()

    @classmethod
    def reset(cls):
        cls.registry = {}
        cls.healthy = True

    @classmethod
    def for_jpd(cls, jpd, jrd) -> "FakeRunnerClient":
        key = f"{jpd.hostname}:{jpd.instance_id}"
        if key not in cls.registry:
            cls.registry[key] = cls(key)
        return cls.registry[key]

    async def healthcheck(self):
        return {"status": "ok"} if type(self).healthy else None

    def default_script(self):
        return [
            {"job_states": [{"state": "running"}], "logs": [], "offset": 1},
            {
                "job_states": [{"state": "done", "exit_status": 0}],
                "logs": [{"ts": "2026-01-01T00:00:00+00:00", "message": "hello\n"}],
                "offset": 2,
            },
        ]

    async def submit(self, job_spec, cluster_info, run_spec=None, secrets=None):
        # A fresh submission restarts the scripted job (pool-reused slices get the same
        # fake; the real runner also resets state on submit).
        if self.submitted is not None:
            self.script = self.default_script()
            self.pulls = 0
        self.submitted = job_spec
        self.cluster_info = cluster_info
        self.secrets = secrets

    async def upload_code(self, code: bytes):
        self.code = code

    async def run_job(self):
        self.ran = True

    async def pull(self, offset: int = 0):
        result = self.script[min(self.pulls, len(self.script) - 1)]
        self.pulls += 1
        return result

    async def stop(self, abort: bool = False):
        self.stopped = True
        self.aborted = abort

    async def metrics(self):
        return None

    async def profile(self, seconds: float = 5.0):
        self.profiled_seconds = seconds
        return {
            "id": 1,
            "seconds": seconds,
            "status": "requested",
            "artifact_dir": "/tmp/fake-profile/1",
        }


async def setup_mock_backend(api: ApiClient, project: str = "main") -> None:
    await api.post(f"/api/project/{project}/backends/create", {"type": "mock"})


async def drive(db, passes: int = 10) -> None:
    """Run all scheduler loops until quiescent (bounded passes)."""
    from dstack_tpu.server.background import tasks

    for _ in range(passes):
        await tasks.process_submitted_jobs(db)
        await tasks.process_running_jobs(db)
        await tasks.process_terminating_jobs(db)
        await tasks.process_runs(db)
        await tasks.process_instances(db)


TASK_SPEC = {
    "run_spec": {
        "run_name": "test-run",
        "configuration": {
            "type": "task",
            "commands": ["echo hello"],
        },
    }
}


def tpu_task_spec(run_name: str = "tpu-run", tpu: str = "v5p-16", **conf) -> dict:
    return {
        "run_spec": {
            "run_name": run_name,
            "configuration": {
                "type": "task",
                "commands": ["python train.py"],
                "resources": {"tpu": tpu},
                **conf,
            },
        }
    }
