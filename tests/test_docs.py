"""Docs-rot guards: the docs tree must keep describing the real surface.

The reference maintains full user docs (ref mkdocs.yml, docs/); this pins the
repo's docs/ to the implementation: every CLI command the CLI reference
names must exist in the parser, every REST path the API reference names must
be a registered route, and the nav must point at real files."""

import re
from pathlib import Path

import yaml

DOCS = Path(__file__).parent.parent / "docs"


class TestDocs:
    def test_nav_points_at_real_files(self):
        nav = yaml.safe_load((DOCS.parent / "mkdocs.yml").read_text())["nav"]

        def walk(node):
            if isinstance(node, str):
                yield node
            elif isinstance(node, dict):
                for v in node.values():
                    yield from walk(v)
            elif isinstance(node, list):
                for item in node:
                    yield from walk(item)

        for page in walk(nav):
            assert (DOCS / page).exists(), f"mkdocs nav names missing page {page}"

    def test_cli_reference_commands_exist(self):
        from dstack_tpu.cli.main import build_parser

        parser = build_parser()
        sub = next(
            a for a in parser._actions
            if a.__class__.__name__ == "_SubParsersAction"
        )
        real = set(sub.choices)
        doc = (DOCS / "reference" / "cli.md").read_text()
        documented = set(re.findall(r"`dstack-tpu (\w[\w-]*)", doc))
        missing = documented - real
        assert not missing, f"CLI docs name unknown commands: {sorted(missing)}"
        undocumented = real - documented - {"stats"}  # alias of metrics
        assert not undocumented, f"CLI commands missing from docs: {sorted(undocumented)}"

    def test_repo_paths_in_docs_exist(self):
        repo = DOCS.parent
        pattern = re.compile(r"`((?:dstack_tpu|runner|tests|docker|examples)/[\w./-]+)`")
        for page in DOCS.rglob("*.md"):
            for match in pattern.finditer(page.read_text()):
                path = match.group(1).rstrip("/.")
                assert (repo / path).exists(), (
                    f"{page.relative_to(repo)} references missing path {path}"
                )

    def test_api_reference_paths_registered(self):
        from dstack_tpu.server.app import create_app

        app = create_app(db_path=":memory:", run_background_tasks=False)
        registered = {r.resource.canonical for r in app.router.routes() if r.resource}
        doc = (DOCS / "reference" / "api.md").read_text()
        checked = 0
        for line in doc.splitlines():
            m = re.match(r"^(?:POST|GET|\*)\s+(/\S+)", line.strip())
            if not m:
                continue
            path = m.group(1).split("?")[0]
            if path.startswith("/proxy/"):
                continue  # data-plane wildcards; covered by proxy tests
            # brace-expansion shorthand: /api/x/{a,b} means /api/x/a + /api/x/b
            expansions = [path]
            brace = re.search(r"\{([\w,/-]+,[\w,/-]+)\}", path)
            if brace:
                expansions = [
                    path[: brace.start()] + part + path[brace.end():]
                    for part in brace.group(1).split(",")
                ]
            for concrete in expansions:
                concrete = (
                    concrete.replace("{p}", "{project_name}")
                    .replace("{run}", "{run_name}")
                )
                checked += 1
                assert concrete in registered, f"api.md names unregistered path {concrete}"
        assert checked >= 25, f"api.md path extraction broke (checked {checked})"
