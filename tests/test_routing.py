"""Cache-aware replica routing (services/routing.py): prefix keys, the
rendezvous ring's ~1/N join/leave stability, sticky-assignment hygiene on
probe flips, queue-depth spill, and the decision counters on /metrics.

The integration tests drive the REAL proxy (router -> route table -> routing
policy -> pooled forward) against local JSON stub replicas, the same shape
test_serving_fast_path.py uses — including the acceptance invariant that
prefix routing adds ZERO DB queries to the steady-state request path."""

import asyncio
import json
import re
import socket

import pytest

from dstack_tpu.server import settings
from dstack_tpu.server.services import proxy as proxy_service
from dstack_tpu.server.services import routing
from tests.common import api_server


def k(i: int) -> bytes:
    return f"t:key-{i}".encode()


EP = [("10.0.0.1", 80), ("10.0.0.2", 80), ("10.0.0.3", 80)]


class _Fixture:
    """Pin the route cache TTL high, force the prefix policy, and reset all
    proxy + routing state around each test."""

    def __enter__(self):
        self._ttl = settings.PROXY_ROUTE_CACHE_TTL
        self._policy = settings.PROXY_ROUTING_POLICY
        settings.PROXY_ROUTE_CACHE_TTL = 3600.0
        settings.PROXY_ROUTING_POLICY = "prefix"
        proxy_service.route_table.clear()
        proxy_service.stats.reset()
        proxy_service._rr.clear()
        routing.state.reset()
        return self

    def __exit__(self, *exc):
        settings.PROXY_ROUTE_CACHE_TTL = self._ttl
        settings.PROXY_ROUTING_POLICY = self._policy
        proxy_service.route_table.clear()
        proxy_service.stats.reset()
        proxy_service._rr.clear()
        routing.state.reset()
        return False


class TestPrefixKey:
    def test_token_prompts_share_key_past_the_window(self):
        base = list(range(1, 70))
        a = json.dumps({"prompt_tokens": base + [900]}).encode()
        b = json.dumps({"prompt_tokens": base + [901, 902]}).encode()
        # Defaults: 64-token window — the differing tails fall outside it.
        assert routing.prefix_key(a) == routing.prefix_key(b) is not None

    def test_token_divergence_inside_window_changes_key(self):
        a = json.dumps({"prompt_tokens": [1, 2, 3]}).encode()
        b = json.dumps({"prompt_tokens": [1, 2, 4]}).encode()
        assert routing.prefix_key(a) != routing.prefix_key(b)

    def test_string_prompts_hash_leading_bytes(self):
        long = "x" * 200
        a = json.dumps({"prompt": long + "tail-one"}).encode()
        b = json.dumps({"prompt": long + "tail-two"}).encode()
        assert routing.prefix_key(a) == routing.prefix_key(b) is not None
        assert routing.prefix_key(
            json.dumps({"prompt": "alpha"}).encode()
        ) != routing.prefix_key(json.dumps({"prompt": "bravo"}).encode())

    def test_unroutable_bodies_return_none(self):
        for body in (
            None,
            b"",
            b"not json",
            b"[1,2,3]",
            json.dumps({"max_tokens": 5}).encode(),
            json.dumps({"prompt_tokens": []}).encode(),
            json.dumps({"prompt_tokens": [1, "a"]}).encode(),
            json.dumps({"prompt_tokens": [True, False]}).encode(),
            json.dumps({"prompt": ""}).encode(),
        ):
            assert routing.prefix_key(body) is None, body

    def test_explicit_window_override(self):
        a = json.dumps({"prompt_tokens": [1, 2, 3]}).encode()
        b = json.dumps({"prompt_tokens": [1, 2, 9]}).encode()
        assert routing.prefix_key(a, prefix_block=2) == routing.prefix_key(
            b, prefix_block=2
        )


class TestRendezvousRing:
    def test_owner_is_deterministic_and_order_independent(self):
        for i in range(50):
            assert routing.rendezvous(k(i), EP) == routing.rendezvous(
                k(i), list(reversed(EP))
            )

    def test_join_moves_about_one_over_n_buckets(self):
        """Adding a 4th endpoint must re-pin roughly 1/4 of the sticky
        buckets — and ONLY buckets the newcomer now wins."""
        ring = routing.PrefixRing(max_assignments=10_000)
        ring.set_endpoints(EP)
        n = 400
        before = {k(i): ring.pick(k(i)) for i in range(n)}
        newcomer = ("10.0.0.4", 80)
        ring.set_endpoints(EP + [newcomer])
        moved = 0
        for i in range(n):
            after = ring.pick(k(i))
            if after != before[k(i)]:
                moved += 1
                assert after == newcomer, (
                    "a join re-pinned a bucket between OLD endpoints"
                )
        assert ring.moved == moved
        # ~1/4 in expectation; generous bounds keep the test hash-stable.
        assert 0.10 < moved / n < 0.45, f"join moved {moved}/{n} buckets"

    def test_leave_redistributes_only_the_dead_endpoints_buckets(self):
        ring = routing.PrefixRing(max_assignments=10_000)
        ring.set_endpoints(EP)
        n = 300
        before = {k(i): ring.pick(k(i)) for i in range(n)}
        dead = EP[1]
        ring.drop_endpoint(dead)
        for i in range(n):
            after = ring.pick(k(i))
            if before[k(i)] == dead:
                assert after != dead
            else:
                assert after == before[k(i)], (
                    "a leave re-pinned a surviving endpoint's bucket"
                )

    def test_sticky_assignments_are_lru_bounded(self):
        ring = routing.PrefixRing(max_assignments=8)
        ring.set_endpoints(EP)
        for i in range(50):
            ring.pick(k(i))
        assert len(ring.assignments) == 8
        # The most recent keys survived.
        assert k(49) in ring.assignments and k(0) not in ring.assignments


class TestChoose:
    RUN = "run-x"
    NAME = "x"

    def setup_method(self):
        routing.state.reset()
        self._policy = settings.PROXY_ROUTING_POLICY
        settings.PROXY_ROUTING_POLICY = "prefix"

    def teardown_method(self):
        settings.PROXY_ROUTING_POLICY = self._policy
        routing.state.reset()

    def test_preferred_owner_takes_keyed_requests(self):
        key = k(1)
        want = routing.rendezvous(key, EP)
        for _ in range(5):
            assert routing.choose(self.RUN, self.NAME, EP, EP, key, 0) == want
        assert routing.state.decisions_for(self.NAME) == {
            ("prefix", "preferred"): 5
        }

    def test_overloaded_owner_spills_to_least_loaded(self):
        key = k(2)
        owner = routing.rendezvous(key, EP)
        others = [ep for ep in EP if ep != owner]
        routing.state.record_queue_depth(
            self.RUN, owner, settings.PROXY_SPILL_QUEUE_DEPTH + 1
        )
        routing.state.record_queue_depth(self.RUN, others[0], 2.0)
        # others[1] never reported: counts as empty, so it wins the spill.
        assert routing.choose(self.RUN, self.NAME, EP, EP, key, 0) == others[1]
        # Depth AT the bound does not spill (strictly-greater semantics).
        routing.state.record_queue_depth(
            self.RUN, owner, settings.PROXY_SPILL_QUEUE_DEPTH
        )
        assert routing.choose(self.RUN, self.NAME, EP, EP, key, 0) == owner
        assert routing.state.decisions_for(self.NAME) == {
            ("prefix", "spilled"): 1,
            ("prefix", "preferred"): 1,
        }

    def test_stale_depth_samples_never_spill(self, monkeypatch):
        key = k(3)
        owner = routing.rendezvous(key, EP)
        routing.state.record_queue_depth(self.RUN, owner, 1e9)
        real = routing.time.monotonic
        monkeypatch.setattr(routing.time, "monotonic", lambda: real() + 31.0)
        assert routing.choose(self.RUN, self.NAME, EP, EP, key, 0) == owner

    def test_retry_and_keyless_and_rr_policy_fall_back_to_cursor(self):
        key = k(4)
        assert routing.choose(
            self.RUN, self.NAME, EP, EP, key, 1, retrying=True
        ) == EP[1]
        assert routing.choose(self.RUN, self.NAME, EP, EP, None, 2) == EP[2]
        settings.PROXY_ROUTING_POLICY = "round_robin"
        assert routing.choose(self.RUN, self.NAME, EP, EP, key, 0) == EP[0]
        assert routing.state.decisions_for(self.NAME) == {
            ("prefix", "fallback"): 2,
            ("round_robin", "fallback"): 1,
        }

    def test_owner_outside_retry_pool_falls_back(self):
        key = k(5)
        owner = routing.rendezvous(key, EP)
        pool = [ep for ep in EP if ep != owner]
        got = routing.choose(self.RUN, self.NAME, pool, EP, key, 0)
        assert got in pool

    def test_forget_run_sweeps_ring_depths_and_counters(self):
        key = k(6)
        routing.choose(self.RUN, self.NAME, EP, EP, key, 0)
        routing.state.record_queue_depth(self.RUN, EP[0], 1.0)
        routing.forget_run(self.RUN, self.NAME)
        assert self.RUN not in routing.state._rings
        assert not routing.state._depths
        assert routing.state.decisions() == {}


async def seed_service(db, run_name: str, *replica_ports: int):
    """A ready service run with one running replica row per port (job_num 0
    each — the shape list_service_replicas returns for scaled services)."""
    proj = await db.fetchone("SELECT * FROM projects LIMIT 1")
    conf = {"type": "service", "commands": ["serve"], "port": 8000,
            "auth": False}
    await db.execute(
        "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at, status,"
        " run_spec) VALUES (?, ?, ?, ?, '2026-01-01', 'running', ?)",
        (f"run-{run_name}", proj["id"], proj["owner_id"], run_name,
         json.dumps({"run_name": run_name, "configuration": conf})),
    )
    for i, port in enumerate(replica_ports):
        job_spec = {
            "job_name": f"{run_name}-0-{i}",
            "image_name": "stub",
            "requirements": {"resources": {}},
            "service_port": 8000,
        }
        jpd = {
            "backend": "local",
            "instance_type": {"name": "local",
                              "resources": {"cpus": 1, "memory_gb": 1, "disk_gb": 1}},
            "instance_id": f"i-{run_name}-{i}",
            "hostname": "127.0.0.1",
            "region": "local",
        }
        jrd = {"ports_mapping": {"8000": port}, "probe_ready": True}
        await db.execute(
            "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, job_spec,"
            " status, submitted_at, job_provisioning_data, job_runtime_data)"
            " VALUES (?, ?, ?, ?, 0, ?, 'running', '2026-01-01', ?, ?)",
            (f"job-{run_name}-{i}", proj["id"], f"run-{run_name}", run_name,
             json.dumps(job_spec), json.dumps(jpd), json.dumps(jrd)),
        )
    return f"run-{run_name}", proj["id"]


class _JsonReplica:
    """Counting JSON stub replica that reports a configurable engine queue
    depth — the spill signal — on every response."""

    def __init__(self, depth: float = 0.0) -> None:
        self.requests = 0
        self.depth = depth
        self.port = None
        self._runner = None

    async def start(self):
        from aiohttp import web as aioweb

        async def handle(request):
            self.requests += 1
            await request.read()
            return aioweb.json_response(
                {"ok": True},
                headers={"X-Dstack-Queue-Depth": str(self.depth)},
            )

        app = aioweb.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        self._runner = aioweb.AppRunner(app)
        await self._runner.setup()
        site = aioweb.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        await self._runner.cleanup()


def _body(tokens):
    return {"prompt_tokens": tokens, "max_tokens": 1, "stream": False}


class TestProxyRouting:
    async def test_shared_prefix_pins_one_replica(self):
        """All requests sharing a prompt prefix land on ONE replica; a
        different prefix may land elsewhere, and the decisions are counted."""
        with _Fixture():
            a, b = await _JsonReplica().start(), await _JsonReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "affine", a.port, b.port)
                    url = "/proxy/services/main/affine/generate"
                    shared = list(range(1, 70))
                    for i in range(6):
                        resp = await api.client.post(url, json=_body(shared + [200 + i]))
                        assert resp.status == 200
                    assert sorted([a.requests, b.requests]) == [0, 6], (
                        f"shared prefix split across replicas: {a.requests}/{b.requests}"
                    )
                    assert routing.state.decisions_for("affine") == {
                        ("prefix", "preferred"): 6
                    }
            finally:
                await a.stop()
                await b.stop()

    async def test_zero_db_queries_with_prefix_routing(self):
        """The PR's acceptance invariant: the cache-aware policy keeps the
        steady-state data plane at ZERO DB queries per request."""
        with _Fixture():
            a, b = await _JsonReplica().start(), await _JsonReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "zerodb", a.port, b.port)
                    url = "/proxy/services/main/zerodb/generate"
                    resp = await api.client.post(url, json=_body([1, 2, 3]))
                    assert resp.status == 200

                    counts = {"queries": 0}
                    orig_all, orig_one = api.db.fetchall, api.db.fetchone

                    async def counted_all(*args, **kw):
                        counts["queries"] += 1
                        return await orig_all(*args, **kw)

                    async def counted_one(*args, **kw):
                        counts["queries"] += 1
                        return await orig_one(*args, **kw)

                    api.db.fetchall, api.db.fetchone = counted_all, counted_one
                    try:
                        for i in range(20):
                            resp = await api.client.post(
                                url, json=_body([i % 3, 5, 9])
                            )
                            assert resp.status == 200
                    finally:
                        api.db.fetchall, api.db.fetchone = orig_all, orig_one
                    assert counts["queries"] == 0, (
                        f"prefix routing hit the DB {counts['queries']} times"
                    )
            finally:
                await a.stop()
                await b.stop()

    async def test_overloaded_replica_spills_through_the_proxy(self):
        """End to end: the preferred replica advertises a queue depth over
        the bound via its response header; the NEXT same-prefix request goes
        to the other replica and the spill is counted."""
        with _Fixture():
            a = await _JsonReplica(depth=settings.PROXY_SPILL_QUEUE_DEPTH + 5).start()
            b = await _JsonReplica(depth=settings.PROXY_SPILL_QUEUE_DEPTH + 5).start()
            try:
                async with api_server() as api:
                    run_id, _ = await seed_service(api.db, "spilly", a.port, b.port)
                    url = "/proxy/services/main/spilly/generate"
                    shared = list(range(1, 70))
                    resp = await api.client.post(url, json=_body(shared))
                    assert resp.status == 200
                    owner = a if a.requests else b
                    other = b if a.requests else a
                    # The owner just reported an over-bound depth; the peer
                    # has never reported, so it counts as idle and attracts
                    # the spill.
                    resp = await api.client.post(url, json=_body(shared))
                    assert resp.status == 200
                    assert owner.requests == 1 and other.requests == 1
                    dec = routing.state.decisions_for("spilly")
                    assert dec[("prefix", "preferred")] == 1
                    assert dec[("prefix", "spilled")] == 1
            finally:
                await a.stop()
                await b.stop()

    async def test_probe_flip_drops_endpoint_from_ring_and_sticky(self):
        """A replica that stops answering its readiness probe is evicted from
        the ring AND its sticky buckets immediately — not after the route
        TTL — so hot prefixes re-pin to live replicas."""
        with _Fixture():
            live = await _JsonReplica().start()
            # A port that is closed the moment we measure it: probe refused.
            probe_sock = socket.socket()
            probe_sock.bind(("127.0.0.1", 0))
            dead_port = probe_sock.getsockname()[1]
            probe_sock.close()
            try:
                async with api_server() as api:
                    run_id, project_id = await seed_service(
                        api.db, "flappy", live.port, dead_port
                    )
                    url = "/proxy/services/main/flappy/generate"
                    # Build the ring over both endpoints (requests that hash
                    # to the dead one 502-retry onto the live one).
                    for i in range(8):
                        resp = await api.client.post(
                            url, json=_body([50 + i, 1, 2])
                        )
                        assert resp.status == 200
                    ring = routing.state.ring(run_id)
                    assert ("127.0.0.1", dead_port) in ring.endpoints

                    await proxy_service.probe_service_replicas(
                        api.db, project_id, "flappy"
                    )
                    assert ("127.0.0.1", dead_port) not in ring.endpoints
                    assert all(
                        ep != ("127.0.0.1", dead_port)
                        for ep in ring.assignments.values()
                    ), "sticky assignment still points at the not-ready replica"
                    # Everything now routes to the live replica, first try.
                    before = live.requests
                    for i in range(4):
                        resp = await api.client.post(
                            url, json=_body([50 + i, 1, 2])
                        )
                        assert resp.status == 200
                    assert live.requests == before + 4
            finally:
                await live.stop()

    async def test_round_robin_policy_still_alternates(self):
        """The configured round_robin policy (non-engine services) keeps the
        pre-PR cursor behavior and is counted as fallback."""
        with _Fixture():
            settings.PROXY_ROUTING_POLICY = "round_robin"
            a, b = await _JsonReplica().start(), await _JsonReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "rrsvc", a.port, b.port)
                    url = "/proxy/services/main/rrsvc/generate"
                    for _ in range(6):
                        resp = await api.client.post(url, json=_body([1, 2]))
                        assert resp.status == 200
                    assert a.requests == 3 and b.requests == 3
                    assert routing.state.decisions_for("rrsvc") == {
                        ("round_robin", "fallback"): 6
                    }
            finally:
                await a.stop()
                await b.stop()


SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*)\})?'
    r' (?P<value>-?(?:\d+(?:\.\d+)?(?:e[+-]?\d+)?|\+?Inf|NaN))$'
)
LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str):
    """Strict line-by-line Prometheus text-format parser: every non-comment
    line must be a well-formed sample; HELP/TYPE must precede their family's
    samples. Returns {family: {"type": ..., "samples": [(labels, value)]}}."""
    families = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            families.setdefault(name, {"type": None, "samples": []})
        elif line.startswith("# TYPE "):
            _, _, name, type_ = line.split(" ", 3)
            assert name in families, f"TYPE before HELP for {name}"
            families[name]["type"] = type_
        else:
            m = SAMPLE_RE.match(line)
            assert m, f"malformed exposition line: {line!r}"
            name = m.group("name")
            base = re.sub(r"_(bucket|sum|count)$", "", name)
            family = families.get(name) or families.get(base)
            assert family is not None, f"sample before HELP/TYPE: {line!r}"
            labels = dict(LABEL_RE.findall(m.group("labels") or ""))
            family["samples"].append((labels, m.group("value")))
    return families


class TestRoutingMetrics:
    async def test_decision_counters_render_and_parse(self):
        """The full /metrics exposition stays strictly parseable, and the new
        family carries exactly the recorded (run, policy, outcome) counts."""
        with _Fixture():
            a, b = await _JsonReplica().start(), await _JsonReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "metered", a.port, b.port)
                    url = "/proxy/services/main/metered/generate"
                    shared = list(range(1, 70))
                    for i in range(5):
                        resp = await api.client.post(
                            url, json=_body(shared + [i])
                        )
                        assert resp.status == 200
                    # One keyless request: counted as fallback.
                    resp = await api.client.post(url, json={"max_tokens": 1})
                    assert resp.status == 200

                    resp = await api.client.get("/metrics")
                    families = parse_exposition(await resp.text())
                    fam = families["dstack_tpu_proxy_routing_decisions_total"]
                    assert fam["type"] == "counter"
                    got = {
                        (ls["run"], ls["policy"], ls["outcome"]): float(v)
                        for ls, v in fam["samples"]
                        if ls.get("run") == "metered"
                    }
                    assert got == {
                        ("metered", "prefix", "preferred"): 5.0,
                        ("metered", "prefix", "fallback"): 1.0,
                    }
            finally:
                await a.stop()
                await b.stop()

    async def test_family_renders_cold(self):
        """HELP/TYPE are advertised before any decision is recorded, so
        scrapers can discover the family from a cold server."""
        with _Fixture():
            async with api_server() as api:
                resp = await api.client.get("/metrics")
                families = parse_exposition(await resp.text())
                fam = families["dstack_tpu_proxy_routing_decisions_total"]
                assert fam["type"] == "counter"
                assert fam["samples"] == []
