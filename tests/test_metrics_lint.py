"""Metrics-exposition lint (ISSUE 18 satellite): scrape the control plane's
GET /metrics from a real test server, strict-parse it, and assert every
``dstack_tpu_*`` series name emitted anywhere in the package appears in the
docs metric reference (docs/guides/observability.md).

The docs-coverage half is the rename tripwire: a metric silently renamed in
code but not in the guide (or a new family added without documentation) fails
here, not in a user's broken dashboard."""

import re
from pathlib import Path

from dstack_tpu.server.services.prometheus import _HISTOGRAM_HELP
from tests.common import api_server
from tests.test_run_events import parse_exposition

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "dstack_tpu"
DOCS = REPO / "docs" / "guides" / "observability.md"

# Identifiers matching the metric-name pattern that are NOT metric families.
NON_METRIC_NAMES = {
    "dstack_tpu_trace_id",  # contextvar names (core/tracing.py)
    "dstack_tpu_span_id",
    "dstack_tpu_replica_id",  # contextvar (server/services/leases.py)
}


def _codebase_metric_names() -> set:
    """Every dstack_tpu_* family name referenced in package source. Names are
    snake_case with >= 2 words after the prefix (filters comment placeholders
    like ``dstack_tpu_service_<name>``, whose capture stops at ``<``)."""
    names = set()
    for path in sorted(PACKAGE.rglob("*.py")):
        for m in re.finditer(
            r"dstack_tpu_[a-z0-9_]*[a-z0-9]", path.read_text(encoding="utf-8")
        ):
            name = m.group(0)
            if name in NON_METRIC_NAMES or name.count("_") < 3:
                continue
            names.add(name)
    return names


class TestMetricsExposition:
    async def test_scrape_strict_parses_and_advertises_families(self):
        """A cold server's /metrics passes the strict format parser and
        advertises every histogram family (discovery must not require
        traffic)."""
        async with api_server() as api:
            resp = await api.client.get("/metrics")
            assert resp.status == 200
            text = await resp.text()
        families = parse_exposition(text)
        for name in _HISTOGRAM_HELP:
            assert name in families, f"advertised family {name} missing"
            assert families[name]["type"] == "histogram"
        # Scraped family names are themselves lintable metric names.
        for name in families:
            assert re.fullmatch(r"dstack_tpu_[a-z0-9_]+", name), name

    async def test_every_emitted_name_is_documented(self):
        """Every dstack_tpu_* series name in the package (tracing.observe
        calls, gauge renders, advertised families) appears in the docs metric
        reference — catches silent renames and undocumented additions."""
        emitted = _codebase_metric_names()
        # Sanity: the scan actually sees the known families from both the
        # control plane and the serving engine.
        assert "dstack_tpu_service_request_latency_seconds" in emitted
        assert "dstack_tpu_serve_ttft_seconds" in emitted
        doc_text = DOCS.read_text(encoding="utf-8")
        missing = sorted(n for n in emitted if n not in doc_text)
        assert not missing, (
            "metric names emitted in code but absent from"
            f" docs/guides/observability.md: {missing}"
        )

    async def test_scraped_families_are_documented(self):
        """The rendered exposition itself (including families composed at
        render time) stays covered by the docs reference."""
        async with api_server() as api:
            resp = await api.client.get("/metrics")
            text = await resp.text()
        doc_text = DOCS.read_text(encoding="utf-8")
        missing = sorted(
            name for name in parse_exposition(text)
            if name not in doc_text
        )
        assert not missing, f"scraped families missing from docs: {missing}"
