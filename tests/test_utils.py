"""Interpolator + locking unit tests."""

import asyncio

import pytest

from dstack_tpu.server.services.locking import Locker
from dstack_tpu.utils.interpolator import (
    InterpolatorError,
    extract_references,
    interpolate,
    interpolate_env,
)


class TestInterpolator:
    def test_extract_references(self):
        env = {
            "A": "${{ secrets.TOKEN }}",
            "B": "prefix-${{secrets.DB_PASS}}-suffix",
            "C": "${{ env.OTHER }}",
            "D": "plain",
        }
        assert extract_references(env.values(), "secrets") == {"TOKEN", "DB_PASS"}

    def test_interpolate_known_and_unknown_namespace(self):
        out = interpolate(
            "x=${{ secrets.A }} y=${{ later.B }}", {"secrets": {"A": "1"}}
        )
        assert out == "x=1 y=${{ later.B }}"

    def test_missing_raises_unless_ok(self):
        with pytest.raises(InterpolatorError):
            interpolate("${{ secrets.NOPE }}", {"secrets": {}})
        assert (
            interpolate("${{ secrets.NOPE }}", {"secrets": {}}, missing_ok=True)
            == "${{ secrets.NOPE }}"
        )

    def test_interpolate_env(self):
        env = {"A": "${{ secrets.X }}", "B": "keep"}
        out = interpolate_env(env, {"secrets": {"X": "v"}})
        assert out == {"A": "v", "B": "keep"}


class TestLockerCancellation:
    def test_cancelled_waiter_does_not_leak(self):
        # Regression (ADVICE r1): cancelling a task awaiting acquire() leaked the
        # waiter refcount, so the per-name entry never dropped from the dict.
        async def scenario():
            locker = Locker()
            async with locker.lock("res"):
                waiter = asyncio.ensure_future(locker.lock("res").__aenter__())
                await asyncio.sleep(0.01)
                waiter.cancel()
                with pytest.raises(asyncio.CancelledError):
                    await waiter
            assert locker._locks == {}
            assert locker._waiters == {}

        asyncio.run(scenario())
