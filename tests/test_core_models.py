"""Core model DSL tests (parity with reference test coverage of resources/configurations:
/root/reference src/tests/_internal/core/models — re-targeted at the TPU slice DSL)."""

import pytest

from dstack_tpu.core.errors import ConfigurationError
from dstack_tpu.core.models.common import (
    MemoryRange,
    Range,
    format_duration,
    parse_duration,
    parse_memory,
)
from dstack_tpu.core.models.configurations import (
    DevEnvironmentConfiguration,
    FleetConfiguration,
    ServiceConfiguration,
    TaskConfiguration,
    parse_configuration,
)
from dstack_tpu.core.models.envs import Env
from dstack_tpu.core.models.profiles import Profile, RetryPolicy, merge_profiles
from dstack_tpu.core.models.resources import ResourcesSpec, TpuSliceSpec, default_topology
from dstack_tpu.core.models.runs import (
    ClusterInfo,
    JobStatus,
    JobTerminationReason,
    RunStatus,
    RunTerminationReason,
)


class TestScalars:
    def test_duration(self):
        assert parse_duration("90s") == 90
        assert parse_duration("15m") == 900
        assert parse_duration("2h") == 7200
        assert parse_duration("1d") == 86400
        assert parse_duration(42) == 42
        assert parse_duration("off") is None
        assert parse_duration(None) is None
        with pytest.raises(ValueError):
            parse_duration("2 fortnights")
        assert format_duration(7200) == "2h"
        assert format_duration(None) == "off"

    def test_memory(self):
        assert parse_memory("16GB") == 16.0
        assert parse_memory("512MB") == 0.5
        assert parse_memory("1TB") == 1024.0
        assert parse_memory(8) == 8.0
        with pytest.raises(ValueError):
            parse_memory("lots")

    def test_range(self):
        r = Range[int].model_validate("4..8")
        assert (r.min, r.max) == (4, 8)
        assert Range[int].model_validate("4..").max is None
        assert Range[int].model_validate("..8").min is None
        assert Range[int].model_validate(4).max == 4
        assert r.contains(5) and not r.contains(9)
        assert r.intersects(Range[int].model_validate("8.."))
        assert not r.intersects(Range[int].model_validate("9.."))
        with pytest.raises(ValueError):
            Range[int].model_validate("8..4")

    def test_memory_range(self):
        mr = MemoryRange.model_validate("16GB..64GB")
        assert (mr.min, mr.max) == (16.0, 64.0)
        assert MemoryRange.model_validate("8GB..").min == 8.0


class TestTpuSliceSpec:
    def test_v5e_names_count_chips(self):
        s = TpuSliceSpec.model_validate("v5e-8")
        assert s.generation == "v5e" and s.chips == 8 and s.hosts == 1
        assert s.accelerator_type == "v5litepod-8"

    def test_v5litepod_alias(self):
        s = TpuSliceSpec.model_validate("v5litepod-16")
        assert s.generation == "v5e" and s.chips == 16 and s.hosts == 2

    def test_v5p_names_count_cores(self):
        s = TpuSliceSpec.model_validate("v5p-16")
        assert s.chips == 8 and s.hosts == 2  # 4 chips/host
        assert s.slice_name == "v5p-16"

    def test_v4(self):
        s = TpuSliceSpec.model_validate("v4-32")
        assert s.chips == 16 and s.hosts == 4

    def test_v6e(self):
        s = TpuSliceSpec.model_validate("v6e-256")
        assert s.chips == 256 and s.hosts == 64

    def test_dict_form(self):
        s = TpuSliceSpec.model_validate({"generation": "v5p", "chips": 8, "count": 2})
        assert s.hosts == 2 and s.count.min == 2

    def test_invalid(self):
        with pytest.raises(ValueError):
            TpuSliceSpec.model_validate("v5p-13")
        with pytest.raises(ValueError):
            TpuSliceSpec.model_validate("h100-8")

    def test_hbm_and_flops(self):
        s = TpuSliceSpec.model_validate("v5p-16")
        assert s.total_hbm_gb == 8 * 95
        assert s.bf16_tflops == 8 * 459

    def test_default_topology(self):
        assert default_topology("v5e", 16) == "4x4"
        assert default_topology("v5p", 8) == "2x2x2"

    def test_default_topology_non_power_of_two(self):
        dims = [int(d) for d in default_topology("v5p", 3072).split("x")]
        assert dims[0] * dims[1] * dims[2] == 3072

    def test_name_conflicts_with_fields(self):
        with pytest.raises(ValueError):
            TpuSliceSpec.model_validate({"name": "v5p-16", "generation": "v5e"})


class TestResourcesSpec:
    def test_defaults(self):
        r = ResourcesSpec()
        assert r.tpu is None and r.cpu.count.min == 2

    def test_full(self):
        r = ResourcesSpec.model_validate(
            {"tpu": "v5p-16", "cpu": "8..", "memory": "32GB..", "disk": "200GB"}
        )
        assert r.tpu.chips == 8
        assert r.cpu.count.min == 8
        assert r.memory.min == 32.0
        assert r.disk.size.min == 200.0


class TestConfigurations:
    def test_task(self):
        c = parse_configuration(
            {
                "type": "task",
                "commands": ["python train.py"],
                "resources": {"tpu": "v5p-16"},
                "env": {"LR": "1e-4"},
            }
        )
        assert isinstance(c, TaskConfiguration)
        assert c.resources.tpu.hosts == 2

    def test_task_requires_commands(self):
        with pytest.raises(ConfigurationError):
            parse_configuration({"type": "task"})

    def test_unknown_key_rejected(self):
        with pytest.raises(ConfigurationError):
            parse_configuration({"type": "task", "commands": ["x"], "gpu": "A100"})

    def test_service(self):
        c = parse_configuration(
            {
                "type": "service",
                "commands": ["python serve.py"],
                "port": 8000,
                "model": "llama-3-8b",
                "replicas": "1..4",
                "scaling": {"metric": "rps", "target": 10},
            }
        )
        assert isinstance(c, ServiceConfiguration)
        assert c.port.container_port == 8000
        assert c.model.name == "llama-3-8b"
        assert (c.replicas.min, c.replicas.max) == (1, 4)

    def test_service_autoscaling_requires_scaling(self):
        with pytest.raises(ConfigurationError):
            parse_configuration(
                {"type": "service", "commands": ["x"], "port": 80, "replicas": "1..4"}
            )

    def test_dev_env(self):
        c = parse_configuration({"type": "dev-environment", "ide": "vscode"})
        assert isinstance(c, DevEnvironmentConfiguration)

    def test_fleet_cloud(self):
        c = parse_configuration(
            {"type": "fleet", "name": "tpu-fleet", "nodes": 2, "resources": {"tpu": "v5e-8"}}
        )
        assert isinstance(c, FleetConfiguration)
        assert c.nodes.min == 2

    def test_fleet_ssh(self):
        c = parse_configuration(
            {
                "type": "fleet",
                "name": "onprem",
                "ssh_config": {"user": "ubuntu", "hosts": ["10.0.0.1", {"hostname": "10.0.0.2"}]},
            }
        )
        assert c.ssh_config.hosts[1].hostname == "10.0.0.2"

    def test_volume(self):
        c = parse_configuration({"type": "volume", "region": "us-central2", "size": "100GB"})
        assert c.size == 100.0

    def test_gateway(self):
        c = parse_configuration({"type": "gateway", "region": "us-central1", "domain": "x.example"})
        assert c.public_ip is True

    def test_ports(self):
        c = parse_configuration({"type": "task", "commands": ["x"], "ports": ["8080", 3000, "127:80"]})
        assert [p.container_port for p in c.ports] == [8080, 3000, 80]

    def test_mounts(self):
        c = parse_configuration(
            {"type": "task", "commands": ["x"], "volumes": ["data:/data", "/mnt/disk:/scratch"]}
        )
        assert c.volumes[0].name == "data"
        assert c.volumes[1].instance_path == "/mnt/disk"


class TestEnv:
    def test_dict(self):
        e = Env.model_validate({"A": "1", "B": 2})
        assert e.as_dict() == {"A": "1", "B": "2"}

    def test_list(self):
        e = Env.model_validate(["A=1", "HOME_TOKEN"])
        assert e.values == {"A": "1", "HOME_TOKEN": None}
        with pytest.raises(ValueError):
            e.as_dict()


class TestProfiles:
    def test_merge(self):
        base = Profile(spot_policy="spot", max_price=10.0)
        over = Profile(max_price=5.0)
        merged = merge_profiles(base, over)
        assert merged.max_price == 5.0
        assert merged.spot_policy.value == "spot"

    def test_retry_parse(self):
        assert RetryPolicy.model_validate(True).duration == 3600
        assert RetryPolicy.model_validate("2h").duration == 7200
        r = RetryPolicy.model_validate({"on_events": ["no-capacity"], "duration": "1d"})
        assert r.duration == 86400

    def test_retry_false_disables(self):
        assert Profile(retry=False).retry is None
        assert Profile.model_validate({"retry": False}).retry is None

    def test_explicit_off_overrides_base(self):
        # A config-level `idle_duration: off` must beat a profile's 1h, not be dropped.
        base = Profile.model_validate({"idle_duration": "1h"})
        cfg = parse_configuration({"type": "task", "commands": ["x"], "idle_duration": "off"})
        merged = merge_profiles(base, cfg.inline_profile())
        assert merged.idle_duration is None
        assert "idle_duration" in merged.model_fields_set

    def test_unset_config_default_does_not_override_profile(self):
        base = Profile.model_validate({"stop_duration": 600})
        cfg = parse_configuration({"type": "task", "commands": ["x"]})
        merged = merge_profiles(base, cfg.inline_profile())
        assert merged.stop_duration == 600


class TestStateMachines:
    def test_job_termination_to_status(self):
        assert JobTerminationReason.DONE_BY_RUNNER.to_status() == JobStatus.DONE
        assert JobTerminationReason.CONTAINER_EXITED_WITH_ERROR.to_status() == JobStatus.FAILED
        assert JobTerminationReason.TERMINATED_BY_USER.to_status() == JobStatus.TERMINATED
        assert JobTerminationReason.ABORTED_BY_USER.to_status() == JobStatus.ABORTED
        assert JobTerminationReason.MAX_DURATION_EXCEEDED.to_status() == JobStatus.TERMINATED

    def test_run_termination(self):
        assert RunTerminationReason.ALL_JOBS_DONE.to_status() == RunStatus.DONE
        assert RunTerminationReason.JOB_FAILED.to_status() == RunStatus.FAILED
        assert RunTerminationReason.STOPPED_BY_USER.to_status() == RunStatus.TERMINATED

    def test_finished(self):
        assert JobStatus.DONE.is_finished()
        assert not JobStatus.RUNNING.is_finished()
        assert RunStatus.FAILED.is_finished()


class TestClusterInfo:
    def test_single_slice_env(self):
        ci = ClusterInfo(
            master_node_ip="10.0.0.1",
            node_ips=["10.0.0.1", "10.0.0.2"],
            nodes_num=2,
            node_rank=1,
            tpu_worker_id=1,
            tpu_worker_hostnames=["w0", "w1"],
            tpu_topology="2x2x2",
            tpu_generation="v5p",
            chips_per_host=4,
            coordinator_address="10.0.0.1:8476",
        )
        env = ci.to_env()
        assert env["TPU_WORKER_ID"] == "1"
        assert env["TPU_TOPOLOGY"] == "2x2x2"
        assert env["DSTACK_JAX_COORDINATOR"] == "10.0.0.1:8476"
        assert "MEGASCALE_NUM_SLICES" not in env

    def test_multislice_env(self):
        ci = ClusterInfo(
            nodes_num=4,
            num_slices=2,
            slice_id=1,
            megascale_coordinator_address="10.0.0.1:8080",
        )
        env = ci.to_env()
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == "1"
        assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "10.0.0.1:8080"
