"""Control-plane scale: the BASELINE capacity claims, exercised.

Reference numbers (BASELINE.md): 150 active jobs/runs/instances per server
replica at <=2 min processing latency, hard-capped at 75 submitted jobs/min
(reference background/__init__.py:44-57 rate limits). This drives 150 real runs
through the real scheduler loops (mock cloud, scripted runners) and requires
comfortably more than the reference's cap even on a loaded 1-CPU host.

The floor locks in the concurrent-scheduler win (async fan-out + query batching
+ offer caching, PR 1): serial passes measured ~740 jobs/min idle, concurrent
passes ~2,000, so 300 keeps 4x the reference cap with generous headroom for a
loaded host."""

import time

import pytest

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from tests.common import FakeRunnerClient, api_server, setup_mock_backend, tpu_task_spec

N_RUNS = 150
MIN_JOBS_PER_MIN = 300  # 4x the reference cap; idle measurement is ~6.6x this floor


@pytest.fixture(autouse=True)
def _fake_runner(monkeypatch):
    FakeRunnerClient.reset()
    backends_service.reset_compute_cache()
    monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
    yield
    FakeRunnerClient.reset()


async def test_150_runs_schedule_within_budget():
    async with api_server() as api:
        await setup_mock_backend(api)
        start = time.monotonic()  # the lifecycle claim includes submission cost
        for i in range(N_RUNS):
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec(f"load-{i}", "v5e-8")
            )
        for _ in range(600):
            await tasks.process_submitted_jobs(api.db, batch=20)
            await tasks.process_running_jobs(api.db, batch=40)
            await tasks.process_terminating_jobs(api.db, batch=40)
            await tasks.process_runs(api.db, batch=40)
            row = await api.db.fetchone(
                "SELECT COUNT(*) AS n FROM runs WHERE status = 'done'"
            )
            if row["n"] >= N_RUNS:
                break
        elapsed = time.monotonic() - start
        assert row["n"] >= N_RUNS, f"only {row['n']}/{N_RUNS} runs finished"
        # The full lifecycle (submit -> place -> run -> done -> teardown) for all
        # 150 runs must sustain at least MIN_JOBS_PER_MIN.
        rate = N_RUNS / elapsed * 60
        assert rate >= MIN_JOBS_PER_MIN, f"{rate:.0f} jobs/min < {MIN_JOBS_PER_MIN}"

        # Strictly fewer instances than runs: slices released by finished runs
        # were pool-reused by later ones (phase-1 reuse engaging under load).
        inst = await api.db.fetchone("SELECT COUNT(*) AS n FROM instances")
        assert 0 < inst["n"] < N_RUNS
        busy = await api.db.fetchone(
            "SELECT COUNT(*) AS n FROM instances WHERE busy_blocks = 1"
        )
        assert busy["n"] == 0  # every slice returned to the pool
