"""GCP TPU backend tests against a faked tpu_v2 REST transport.

Parity with the reference's backend test strategy (stubbed cloud auth,
src/tests/.../test_backends.py) — but one level deeper: the real GcpTpuCompute code
builds real queued-resource requests; only the HTTP transport is scripted. Covers the
headline extension (multi-host v5p-16 via QueuedResources) create -> ready ->
terminate, capacity fallbacks, and the scheduler integration that resolves hostnames
asynchronously."""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import pytest

from dstack_tpu.backends.gcp.client import GcpApiError, Transport
from dstack_tpu.backends.gcp.compute import GcpTpuCompute, ProvisioningError
from dstack_tpu.core.errors import ComputeError, NoCapacityError
from dstack_tpu.core.models.runs import Requirements
from dstack_tpu.core.models.resources import ResourcesSpec
from dstack_tpu.server.services import backends as backends_service
from tests.common import FakeRunnerClient, api_server, drive, tpu_task_spec


class FakeTransport(Transport):
    """Scripted transport: record every request; answer from handlers by (method, suffix)."""

    def __init__(self):
        self.requests: List[Tuple[str, str, Optional[dict], Optional[dict]]] = []
        self.handlers: List[Tuple[str, str, Any]] = []  # (method, url_substr, response|exc)

    def on(self, method: str, url_substr: str, response: Any) -> None:
        self.handlers.append((method, url_substr, response))

    async def request(self, method, url, body=None, params=None):
        self.requests.append((method, url, body, params))
        for m, sub, resp in self.handlers:
            if m == method and sub in url:
                if isinstance(resp, Exception):
                    raise resp
                if callable(resp):
                    return resp(url, body, params)
                return resp
        return {}


def make_requirements(tpu: str = "v5p-16", spot: Optional[bool] = None) -> Requirements:
    return Requirements(resources=ResourcesSpec(tpu=tpu), spot=spot)


def make_gcp(transport=None, **cfg) -> GcpTpuCompute:
    config = {"project_id": "proj-1", **cfg}
    return GcpTpuCompute(config, transport=transport or FakeTransport())


def qr_state(state: str) -> dict:
    return {"name": "qr", "state": {"state": state}}


def ready_node(n_workers: int) -> dict:
    return {
        "state": "READY",
        "networkEndpoints": [
            {
                "ipAddress": f"10.0.0.{i + 1}",
                "accessConfig": {"externalIp": f"34.1.2.{i + 1}"},
            }
            for i in range(n_workers)
        ],
    }


class TestOffers:
    async def test_offers_tpu_only_and_zone_annotated(self):
        gcp = make_gcp()
        offers = await gcp.get_offers(make_requirements("v5p-16"))
        assert offers and all(o.backend == "gcp" for o in offers)
        assert all(o.availability_zones for o in offers)
        assert all(o.instance.name == "v5p-16" for o in offers)
        assert all(o.hosts_per_slice == 2 for o in offers)

    async def test_cpu_only_request_gets_nothing(self):
        gcp = make_gcp()
        offers = await gcp.get_offers(Requirements(resources=ResourcesSpec()))
        assert offers == []

    async def test_region_filter(self):
        gcp = make_gcp(regions=["us-east5"])
        offers = await gcp.get_offers(make_requirements("v5p-16"))
        assert offers and all(o.region == "us-east5" for o in offers)


class TestCreateSlice:
    async def test_multihost_v5p16_create(self):
        t = FakeTransport()
        gcp = make_gcp(t)
        offers = await gcp.get_offers(make_requirements("v5p-16", spot=False))
        offer = [o for o in offers if not o.spot][0]
        jpds = await gcp.create_slice(offer, "run-0-abc", ssh_public_key="ssh-ed25519 AAAA")

        # One queued-resource create; body carries the multi-host node spec.
        creates = [r for r in t.requests if r[0] == "POST" and "queuedResources" in r[1]]
        assert len(creates) == 1
        _, url, body, params = creates[0]
        assert params == {"queuedResourceId": "run-0-abc"}
        node_spec = body["tpu"]["nodeSpec"][0]
        assert node_spec["nodeId"] == "run-0-abc"
        node = node_spec["node"]
        assert node["acceleratorType"] == "v5p-16"
        assert node["runtimeVersion"] == "v2-alpha-tpuv5"
        assert "guaranteed" in body and "spot" not in body
        script = node["metadata"]["startup-script"]
        assert "PJRT_DEVICE=TPU" in script
        assert "dstack-tpu-runner" in script
        assert "ssh-ed25519 AAAA" in script

        # One JPD per worker host, endpoint not yet known.
        assert [j.worker_num for j in jpds] == [0, 1]
        assert all(j.hostname is None for j in jpds)
        assert all(j.slice_id == "run-0-abc" for j in jpds)
        assert all(j.hosts_per_slice == 2 for j in jpds)
        assert json.loads(jpds[0].backend_data)["zone"] in offer.availability_zones

    async def test_spot_flag(self):
        t = FakeTransport()
        gcp = make_gcp(t)
        offers = await gcp.get_offers(make_requirements("v5e-8", spot=True))
        await gcp.create_slice(offers[0], "spot-slice")
        body = [r for r in t.requests if r[0] == "POST"][0][2]
        assert "spot" in body and "guaranteed" not in body
        assert body["tpu"]["nodeSpec"][0]["node"]["acceleratorType"] == "v5litepod-8"

    async def test_capacity_error_falls_through_zones(self):
        t = FakeTransport()
        t.on("POST", "queuedResources", GcpApiError(429, "quota", "RESOURCE_EXHAUSTED"))
        gcp = make_gcp(t)
        offers = await gcp.get_offers(make_requirements("v5p-16"))
        offer = [o for o in offers if o.region == "us-east5"][0]  # 2 zones
        with pytest.raises(NoCapacityError):
            await gcp.create_slice(offer, "no-cap")
        creates = [r for r in t.requests if r[0] == "POST"]
        assert len(creates) == 2  # tried both us-east5 zones

    async def test_quota_403_falls_through_but_bare_403_is_hard_error(self):
        # ADVICE r2: a bare 403 is an IAM misconfiguration, not capacity — it
        # must surface, not dissolve into NoCapacityError after "all zones".
        t = FakeTransport()
        t.on("POST", "queuedResources", GcpApiError(403, "quota exceeded", "QUOTA_EXCEEDED"))
        gcp = make_gcp(t)
        offers = await gcp.get_offers(make_requirements("v5p-16"))
        offer = [o for o in offers if o.region == "us-east5"][0]
        with pytest.raises(NoCapacityError):
            await gcp.create_slice(offer, "q-403")

        t2 = FakeTransport()
        t2.on("POST", "queuedResources", GcpApiError(403, "caller lacks tpu.queuedResources.create", None))
        gcp2 = make_gcp(t2)
        with pytest.raises(ComputeError) as exc_info:
            await gcp2.create_slice(offer, "iam-403")
        assert not isinstance(exc_info.value, NoCapacityError)
        assert len([r for r in t2.requests if r[0] == "POST"]) == 1  # no zone sweep

    async def test_nonroot_login_user_in_startup_and_jpd(self):
        # ADVICE r2: TPU VM images refuse root SSH; keys go to the login user.
        t = FakeTransport()
        gcp = make_gcp(t)
        offers = await gcp.get_offers(make_requirements("v5e-8", spot=False))
        jpds = await gcp.create_slice(offers[0], "u-test", ssh_public_key="ssh-ed25519 KEY")
        assert all(j.username == "ubuntu" for j in jpds)
        script = [r for r in t.requests if r[0] == "POST"][0][2]["tpu"]["nodeSpec"][0][
            "node"
        ]["metadata"]["startup-script"]
        assert "install_keys /root root" in script
        assert "id -u ubuntu" in script


class TestUpdateProvisioningData:
    async def _jpds(self, gcp):
        offers = await gcp.get_offers(make_requirements("v5p-16", spot=False))
        offer = [o for o in offers if not o.spot and o.region == "us-central1"][0]
        return await gcp.create_slice(offer, "slice-x")

    async def test_pending_returns_unchanged(self):
        t = FakeTransport()
        gcp = make_gcp(t)
        jpds = await self._jpds(gcp)
        t.on("GET", "queuedResources/slice-x", qr_state("WAITING_FOR_RESOURCES"))
        out = await gcp.update_provisioning_data(jpds[0])
        assert out.hostname is None

    async def test_ready_resolves_per_worker_endpoints(self):
        t = FakeTransport()
        gcp = make_gcp(t)
        jpds = await self._jpds(gcp)
        t.on("GET", "queuedResources/slice-x", qr_state("ACTIVE"))
        t.on("GET", "nodes/slice-x", ready_node(2))
        out0 = await gcp.update_provisioning_data(jpds[0])
        out1 = await gcp.update_provisioning_data(jpds[1])
        assert out0.hostname == "34.1.2.1" and out0.internal_ip == "10.0.0.1"
        assert out1.hostname == "34.1.2.2" and out1.internal_ip == "10.0.0.2"

    async def test_private_ip_when_no_public(self):
        t = FakeTransport()
        gcp = make_gcp(t, allocate_public_ips=False)
        jpds = await self._jpds(gcp)
        t.on("GET", "queuedResources/slice-x", qr_state("ACTIVE"))
        t.on("GET", "nodes/slice-x", ready_node(2))
        out = await gcp.update_provisioning_data(jpds[0])
        assert out.hostname == "10.0.0.1"

    async def test_failed_qr_raises_no_capacity(self):
        t = FakeTransport()
        gcp = make_gcp(t)
        jpds = await self._jpds(gcp)
        t.on("GET", "queuedResources/slice-x", qr_state("FAILED"))
        with pytest.raises(NoCapacityError):
            await gcp.update_provisioning_data(jpds[0])

    async def test_preempted_node_raises(self):
        t = FakeTransport()
        gcp = make_gcp(t)
        jpds = await self._jpds(gcp)
        t.on("GET", "queuedResources/slice-x", qr_state("ACTIVE"))
        t.on("GET", "nodes/slice-x", {"state": "PREEMPTED"})
        with pytest.raises(ProvisioningError):
            await gcp.update_provisioning_data(jpds[0])


class TestTerminate:
    async def test_terminate_deletes_queued_resource(self):
        t = FakeTransport()
        gcp = make_gcp(t)
        await gcp.terminate_slice(
            "slice-x", "us-central1", backend_data=json.dumps({"zone": "us-central1-a"})
        )
        deletes = [r for r in t.requests if r[0] == "DELETE"]
        assert len(deletes) == 1
        assert "queuedResources/slice-x" in deletes[0][1]
        assert deletes[0][3] == {"force": "true"}

    async def test_terminate_tolerates_gone(self):
        t = FakeTransport()
        t.on("DELETE", "queuedResources", GcpApiError(404, "not found"))
        gcp = make_gcp(t)
        await gcp.terminate_slice(
            "slice-x", "us-central1", backend_data=json.dumps({"zone": "us-central1-a"})
        )

    async def test_terminate_without_zone_sweeps_all_region_zones(self):
        # VERDICT r2 weak #4: with backend_data lost, a one-zone guess + 404
        # swallow would leak slices living in another zone. All zones of the
        # region (across generations) must be tried.
        t = FakeTransport()
        t.on("DELETE", "queuedResources", GcpApiError(404, "not found"))
        gcp = make_gcp(t)
        await gcp.terminate_slice("slice-y", "us-east5", backend_data=None)
        deletes = [r for r in t.requests if r[0] == "DELETE"]
        zones = {d[1].split("/locations/")[1].split("/")[0] for d in deletes}
        assert zones == {"us-east5-a", "us-east5-c"}


class TestBackendRegistration:
    async def test_make_compute_gcp_no_import_error(self):
        compute = backends_service.make_compute("gcp", {"project_id": "p"})
        assert compute.TYPE == "gcp"

    async def test_create_backend_via_api(self):
        async with api_server() as api:
            await api.post(
                "/api/project/main/backends/create",
                {"type": "gcp", "project_id": "proj-1", "creds": {"token": "t"}},
            )
            listed = await api.post("/api/project/main/backends/list")
            assert any(b["type"] == "gcp" for b in listed)


class TestSchedulerIntegration:
    """Full loop: submit a v5p-16 run against the gcp backend with a scripted cloud."""

    @pytest.fixture(autouse=True)
    def _fake_runner(self, monkeypatch):
        from dstack_tpu.server.background import tasks

        FakeRunnerClient.reset()
        backends_service.reset_compute_cache()
        monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
        yield
        FakeRunnerClient.reset()
        backends_service.reset_compute_cache()

    async def test_create_ready_run_terminate(self, monkeypatch):
        t = FakeTransport()
        # QR goes pending -> ACTIVE over successive polls; node READY with 2 workers.
        states = iter(["WAITING_FOR_RESOURCES", "ACTIVE"])
        t.on(
            "GET",
            "queuedResources/",
            lambda url, body, params: qr_state(next(states, "ACTIVE")),
        )
        t.on("GET", "nodes/", ready_node(2))

        real_make = backends_service.make_compute

        def fake_make(backend_type, config=None):
            if backend_type == "gcp":
                return GcpTpuCompute(config, transport=t)
            return real_make(backend_type, config)

        monkeypatch.setattr(backends_service, "make_compute", fake_make)

        async with api_server() as api:
            await api.post(
                "/api/project/main/backends/create",
                {"type": "gcp", "project_id": "proj-1", "creds": {"token": "tok"}},
            )
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("gcp-run", tpu="v5p-16")
            )
            await drive(api.db, passes=12)
            run = await api.post("/api/project/main/runs/get", {"run_name": "gcp-run"})
            assert run["status"] == "done", run
            # Both workers were reached at their resolved endpoints.
            hostnames = {f.key.split(":")[0] for f in FakeRunnerClient.registry.values()}
            assert hostnames == {"34.1.2.1", "34.1.2.2"}
            # The cluster contract must never carry unresolved (empty) endpoints —
            # regression: submission used to read gang rows fetched before resolution.
            for fake in FakeRunnerClient.registry.values():
                info = fake.cluster_info
                assert info.nodes_num == 2
                assert len(info.node_ips) == 2 and all(info.node_ips)
                assert info.master_node_ip in ("10.0.0.1", "34.1.2.1")
            # The slice was released and the cloud QR deleted on teardown.
            await api.post("/api/project/main/fleets/delete", {"names": []}, expect=None)

    async def test_qr_failure_requeues_gang(self, monkeypatch):
        t = FakeTransport()
        t.on("GET", "queuedResources/", qr_state("FAILED"))
        real_make = backends_service.make_compute
        monkeypatch.setattr(
            backends_service,
            "make_compute",
            lambda bt, config=None: (
                GcpTpuCompute(config, transport=t) if bt == "gcp" else real_make(bt, config)
            ),
        )
        async with api_server() as api:
            await api.post(
                "/api/project/main/backends/create",
                {"type": "gcp", "project_id": "proj-1", "creds": {"token": "tok"}},
            )
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("fail-run", tpu="v5p-16")
            )
            await drive(api.db, passes=8)
            run = await api.post("/api/project/main/runs/get", {"run_name": "fail-run"})
            sub = run["jobs"][0]["job_submissions"][-1]
            assert run["status"] == "failed"
            assert sub["termination_reason"] in (
                "interrupted_by_no_capacity",
                "failed_to_start_due_to_no_capacity",
            )


class TestAuth:
    def test_sign_jwt_rs256_roundtrip(self):
        # Signing rides the openssl-CLI shim (gateway/minicrypto.py), same as
        # the gateway TLS tests — no cryptography wheel in the image.
        from dstack_tpu.backends.gcp.auth import sign_jwt_rs256
        from dstack_tpu.gateway import minicrypto

        pem = minicrypto.generate_rsa_key_pem()
        jwt = sign_jwt_rs256({"iss": "x@y", "scope": "s"}, pem)
        header_b64, claims_b64, sig_b64 = jwt.split(".")
        import base64
        import json as _json

        def unb64(s):
            return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))

        assert _json.loads(unb64(header_b64)) == {"alg": "RS256", "typ": "JWT"}
        assert _json.loads(unb64(claims_b64))["iss"] == "x@y"
        assert minicrypto.rsa_verify_sha256(
            pem, f"{header_b64}.{claims_b64}".encode(), unb64(sig_b64)
        )
        # A tampered payload must not verify.
        assert not minicrypto.rsa_verify_sha256(
            pem, f"{header_b64}.{claims_b64}x".encode(), unb64(sig_b64)
        )

    def test_token_provider_selection(self):
        from dstack_tpu.backends.gcp.auth import (
            MetadataTokenProvider,
            ServiceAccountTokenProvider,
            StaticTokenProvider,
            token_provider_from_creds,
        )

        assert isinstance(token_provider_from_creds({"token": "t"}), StaticTokenProvider)
        assert isinstance(token_provider_from_creds(None), MetadataTokenProvider)
        assert isinstance(
            token_provider_from_creds(
                {"type": "service_account", "client_email": "a@b", "private_key": "k"}
            ),
            ServiceAccountTokenProvider,
        )


class TestVolumes:
    """TPU data disks: created via the compute API, attached at QR-create time,
    slice pinned to the disk's zone (reference gcp/compute.py:1003-1016)."""

    async def test_create_volume_calls_disk_api(self):
        from dstack_tpu.core.models.configurations import VolumeConfiguration
        from dstack_tpu.core.models.volumes import Volume, VolumeStatus
        import datetime
        import uuid

        t = FakeTransport()
        gcp = make_gcp(t)
        vol = Volume(
            id=uuid.uuid4(),
            name="data",
            project_name="main",
            configuration=VolumeConfiguration(
                type="volume", name="data", backend="gcp", region="us-east5", size="100GB"
            ),
            created_at=datetime.datetime.now(datetime.timezone.utc),
            status=VolumeStatus.SUBMITTED,
        )
        pd = await gcp.create_volume(vol)
        assert pd.volume_id == "data"
        assert pd.availability_zone == "us-east5-a"
        assert pd.size_gb == 100
        [(method, url, body, _)] = [r for r in t.requests if "/disks" in r[1]]
        assert method == "POST"
        assert "compute.googleapis.com" in url and "zones/us-east5-a/disks" in url
        assert body["name"] == "data" and body["sizeGb"] == "100"

    async def test_create_slice_attaches_data_disks_in_disk_zone(self):
        from dstack_tpu.core.models.configurations import VolumeConfiguration
        from dstack_tpu.core.models.volumes import (
            Volume,
            VolumeProvisioningData,
            VolumeStatus,
        )
        import datetime
        import uuid

        t = FakeTransport()
        gcp = make_gcp(t)
        vol = Volume(
            id=uuid.uuid4(),
            name="data",
            project_name="main",
            configuration=VolumeConfiguration(
                type="volume", name="data", backend="gcp", region="us-east5", size="100GB"
            ),
            created_at=datetime.datetime.now(datetime.timezone.utc),
            status=VolumeStatus.ACTIVE,
            provisioning_data=VolumeProvisioningData(
                backend="gcp", volume_id="data", availability_zone="us-east5-b"
            ),
        )
        offers = await gcp.get_offers(make_requirements("v5p-16"))
        jpds = await gcp.create_slice(offers[0], "vslice", volumes=[vol])
        assert jpds[0].availability_zone == "us-east5-b"  # pinned to the disk's zone
        [(_, _, body, _)] = [r for r in t.requests if "queuedResources" in r[1] and r[0] == "POST"]
        node = body["tpu"]["nodeSpec"][0]["node"]
        assert node["dataDisks"] == [
            {
                "sourceDisk": "projects/proj-1/zones/us-east5-b/disks/data",
                "mode": "READ_WRITE",
            }
        ]
