"""Test configuration.

JAX-facing tests run on a virtual 8-device CPU mesh so multi-host sharding logic is
exercised without TPU hardware (mirrors the reference's fake-Compute strategy,
/root/reference SURVEY §4: real scheduler loops + mocked clouds).
"""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DSTACK_TPU_TEST", "1")

import asyncio
import inspect

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Run `async def` tests with asyncio.run (pytest-asyncio is not available here)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}

        async def _run():
            try:
                return await fn(**kwargs)
            finally:
                # The proxy's pooled upstream session is per event loop; close
                # it before asyncio.run tears the loop down so keep-alive
                # sockets don't leak across tests.
                from dstack_tpu.core.services import http_forward

                await http_forward.close_session()

        asyncio.run(_run())
        return True
    return None
