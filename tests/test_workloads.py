"""Workload tests on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8; devices selected explicitly because the axon
TPU plugin ignores JAX_PLATFORMS)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# The axon TPU plugin ignores JAX_PLATFORMS; pin computation to the CPU backend so
# numerics are fp32 (TPU fp32 matmuls round through the bf16 MXU) and compiles are
# local/fast.
jax.config.update("jax_default_device", jax.devices("cpu")[0])

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import train as train_lib
from dstack_tpu.workloads.attention import (
    blockwise_attention,
    plain_attention,
    ring_attention,
)
from dstack_tpu.workloads.config import get_config
from dstack_tpu.workloads.sharding import (
    PARAM_SPECS,
    batch_sharding,
    make_mesh,
    param_sharding,
)


def cpu_devices(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip(f"need {n} cpu devices, have {len(devs)}")
    return devs[:n]


def naive_attention(q, k, v, causal=True):
    n_rep = q.shape[2] // k.shape[2]
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bthd,bshd->bths", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        t, S = q.shape[1], k.shape[1]
        mask = jnp.arange(S)[None, :] <= jnp.arange(t)[:, None]
        s = jnp.where(mask[None, :, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bths,bshd->bthd", p, v.astype(jnp.float32))


class TestAttention:
    def test_blockwise_matches_naive(self):
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (2, 300, 4, 16))
        k = jax.random.normal(kk, (2, 300, 2, 16))  # GQA 2:1
        v = jax.random.normal(kv, (2, 300, 2, 16))
        out_block = blockwise_attention(q, k, v, block_size=128)
        out_naive = naive_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out_block), np.asarray(out_naive), atol=2e-5)

    def test_plain_matches_naive(self):
        key = jax.random.PRNGKey(3)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (2, 128, 4, 16))
        k = jax.random.normal(kk, (2, 128, 2, 16))  # GQA 2:1
        v = jax.random.normal(kv, (2, 128, 2, 16))
        out_plain = plain_attention(q, k, v)
        out_naive = naive_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out_plain), np.asarray(out_naive), atol=2e-5)

    def test_ring_matches_blockwise(self):
        devs = cpu_devices(8)
        mesh = make_mesh(dp=1, fsdp=2, tp=1, sp=4, devices=devs)
        key = jax.random.PRNGKey(1)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (2, 256, 4, 16))
        k = jax.random.normal(kk, (2, 256, 4, 16))
        v = jax.random.normal(kv, (2, 256, 4, 16))
        with mesh:
            out_ring = ring_attention(q, k, v, mesh)
        out_ref = blockwise_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out_ring, dtype=np.float32), np.asarray(out_ref), atol=2e-5
        )


class TestModel:
    def test_param_count_llama8b(self):
        cfg = get_config("llama3_8b")
        assert 7.5e9 < cfg.num_params() < 8.5e9

    def test_forward_shapes_and_finite(self):
        cfg = get_config("test")
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)
        logits = jax.jit(lambda p, t: model_lib.forward(p, t, cfg))(params, tokens)
        assert logits.shape == (2, 64, cfg.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    def test_chunked_ce_matches_full(self):
        # loss_chunk must not change the loss value (only HBM footprint).
        cfg = get_config("test", dtype="float32")
        cfg_chunk = get_config("test", dtype="float32", loss_chunk=16)
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)
        targets = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0, cfg.vocab_size)
        targets = targets.at[0, :5].set(-1)  # exercise the ignore mask
        full = float(model_lib.loss_fn(params, tokens, targets, cfg))
        chunked = float(model_lib.loss_fn(params, tokens, targets, cfg_chunk))
        np.testing.assert_allclose(chunked, full, rtol=1e-5)

    def test_chunked_ce_grads_match_full(self):
        cfg = get_config("test", dtype="float32")
        cfg_chunk = get_config("test", dtype="float32", loss_chunk=16)
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)
        targets = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0, cfg.vocab_size)
        g_full = jax.grad(model_lib.loss_fn)(params, tokens, targets, cfg)
        g_chunk = jax.grad(model_lib.loss_fn)(params, tokens, targets, cfg_chunk)
        for name in ("lm_head", "embed", "final_norm"):
            np.testing.assert_allclose(
                np.asarray(g_chunk[name]), np.asarray(g_full[name]), atol=1e-5, rtol=1e-4
            )

    def test_flash_impl_falls_back_off_tpu(self):
        # attn_impl="flash" must still work where Mosaic can't run (CPU tests,
        # multichip dryrun) by falling back to the blockwise core.
        cfg = get_config("test", attn_impl="flash")
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)
        logits = model_lib.forward(params, tokens, cfg)
        assert logits.shape == (2, 64, cfg.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    def test_causality(self):
        """Changing a future token must not change past logits."""
        cfg = get_config("test")
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, cfg.vocab_size)
        t2 = t1.at[0, -1].set((t1[0, -1] + 1) % cfg.vocab_size)
        l1 = model_lib.forward(params, t1, cfg)
        l2 = model_lib.forward(params, t2, cfg)
        np.testing.assert_allclose(
            np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]), atol=1e-5
        )
        assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))


class TestShardedTraining:
    def test_train_step_loss_decreases_on_mesh(self):
        devs = cpu_devices(8)
        mesh = make_mesh(dp=2, fsdp=2, tp=2, sp=1, devices=devs)
        cfg = get_config("test")
        optimizer = train_lib.make_optimizer(learning_rate=1e-3)
        with mesh:
            state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
            # Params landed with the declared shardings.
            shardings = param_sharding(mesh)
            for name, arr in state.params.items():
                assert arr.sharding == shardings[name], name
            step = train_lib.make_train_step(cfg, optimizer, mesh)
            bspec = batch_sharding(mesh)
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (4, 128), 0, cfg.vocab_size), bspec
            )
            targets = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(2), (4, 128), 0, cfg.vocab_size), bspec
            )
            losses = []
            for _ in range(4):
                state, metrics = step(state, tokens, targets)
                losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses

    def test_sp_mesh_with_ring_attention_trains(self):
        devs = cpu_devices(8)
        mesh = make_mesh(dp=1, fsdp=2, tp=2, sp=2, devices=devs)
        cfg = get_config("test")
        optimizer = train_lib.make_optimizer()
        with mesh:
            state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
            step = train_lib.make_train_step(cfg, optimizer, mesh)
            bspec = batch_sharding(mesh)
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (2, 256), 0, cfg.vocab_size), bspec
            )
            targets = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(2), (2, 256), 0, cfg.vocab_size), bspec
            )
            state, metrics = step(state, tokens, targets)
            loss = float(metrics["loss"])
        assert np.isfinite(loss) and loss > 0

    def test_sharded_forward_matches_single_device(self):
        devs = cpu_devices(8)
        # fp32 compute so differences measure sharding correctness, not bf16 noise.
        cfg = get_config("test", dtype="float32")
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, cfg.vocab_size)
        ref = model_lib.forward(params, tokens, cfg)

        mesh = make_mesh(dp=1, fsdp=2, tp=2, sp=2, devices=devs)
        from dstack_tpu.workloads.sharding import shard_params

        with mesh:
            sp = shard_params(params, mesh)
            tok_sharded = jax.device_put(tokens, batch_sharding(mesh))
            out = jax.jit(lambda p, t: model_lib.forward(p, t, cfg, mesh))(sp, tok_sharded)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-4, rtol=1e-3)


class TestNonCausalPadding:
    def test_noncausal_multiblock_matches_naive(self):
        # Regression (ADVICE r1): the multi-block scan path hardcoded causal=True and
        # masked padding via the causal comparison; causal=False with S > block_size
        # must not apply a causal mask, and padded tail keys must stay masked.
        key = jax.random.PRNGKey(7)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (2, 100, 4, 16))
        k = jax.random.normal(kk, (2, 300, 4, 16))  # 300 = 3 blocks of 128 w/ padding
        v = jax.random.normal(kv, (2, 300, 4, 16))
        out_block = blockwise_attention(q, k, v, causal=False, block_size=128)
        out_naive = naive_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out_block), np.asarray(out_naive), atol=2e-5)
