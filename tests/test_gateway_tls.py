"""Gateway TLS: SNI cert store + ACME http-01 issuance against a fake ACME CA.

The fake server implements enough of RFC 8555 to exercise the real client:
JWS-posted account/order/challenge flow, http-01 validation performed by
actually fetching /.well-known/acme-challenge/ from the gateway's HTTP app,
CSR-based finalize signed by an in-test CA. Done = a service registered with a
domain gets a cert and the HTTPS listener serves it under SNI (VERDICT #6)."""

import asyncio
import base64
import hashlib
import json
import socket
import ssl

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from dstack_tpu.gateway import minicrypto
from dstack_tpu.gateway.app import create_app
from dstack_tpu.gateway.tls import CertStore, self_signed_cert
from dstack_tpu.gateway.tls_manager import TlsManager


def _b64u_decode(s):
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class TestCa:
    """In-test CA that signs CSRs (what the fake ACME finalize uses)."""

    def __init__(self):
        self.ca_pem, self.ca_key_pem = minicrypto.self_signed_cert(
            "fake-acme-ca", days=30, is_ca=True
        )

    def sign_csr(self, csr_der: bytes) -> str:
        leaf = minicrypto.sign_csr(csr_der, self.ca_pem, self.ca_key_pem, days=30)
        return leaf + self.ca_pem


class FakeAcme:
    """Enough of RFC 8555 for the client: nonces, JWS parsing (signatures are
    not verified — the protocol flow is what's under test), http-01 validation
    against the real gateway HTTP port."""

    def __init__(self, ca: TestCa, challenge_host: str):
        self.ca = ca
        self.challenge_host = challenge_host  # host:port serving the gateway app
        self.base = ""
        self.jwk = None
        self.order_status = "pending"
        self.authz_status = "pending"
        self.cert_pem = None
        self.validated_tokens = []
        self.badnonce_remaining = 0  # inject N badNonce rejections on new-order
        self.new_order_posts = 0

    def thumbprint(self):
        canonical = json.dumps(self.jwk, separators=(",", ":"), sort_keys=True)
        return base64.urlsafe_b64encode(
            hashlib.sha256(canonical.encode()).digest()
        ).rstrip(b"=").decode()

    def app(self):
        app = web.Application()

        def nonce_headers():
            return {"Replay-Nonce": "nonce-" + hashlib.sha1(str(id(self)).encode()).hexdigest()[:8]}

        async def directory(request):
            return web.json_response({
                "newNonce": f"{self.base}/new-nonce",
                "newAccount": f"{self.base}/new-account",
                "newOrder": f"{self.base}/new-order",
            })

        async def new_nonce(request):
            return web.Response(status=200, headers=nonce_headers())

        def parse_jws(body):
            jws = json.loads(body)
            protected = json.loads(_b64u_decode(jws["protected"]))
            payload = jws["payload"]
            return protected, json.loads(_b64u_decode(payload)) if payload else None

        async def new_account(request):
            protected, _ = parse_jws(await request.read())
            self.jwk = protected["jwk"]
            return web.json_response(
                {"status": "valid"}, status=201,
                headers={**nonce_headers(), "Location": f"{self.base}/acct/1"},
            )

        async def new_order(request):
            self.new_order_posts += 1
            if self.badnonce_remaining > 0:
                self.badnonce_remaining -= 1
                return web.json_response(
                    {"type": "urn:ietf:params:acme:error:badNonce"},
                    status=400, headers=nonce_headers(),
                )
            _, payload = parse_jws(await request.read())
            assert payload["identifiers"][0]["value"] == "svc.test"
            return web.json_response(
                {
                    "status": "pending",
                    "authorizations": [f"{self.base}/authz/1"],
                    "finalize": f"{self.base}/finalize/1",
                },
                status=201,
                headers={**nonce_headers(), "Location": f"{self.base}/order/1"},
            )

        async def authz(request):
            return web.json_response(
                {
                    "status": self.authz_status,
                    "challenges": [
                        {"type": "dns-01", "token": "unused", "url": f"{self.base}/chall/0"},
                        {"type": "http-01", "token": "tok-123", "url": f"{self.base}/chall/1"},
                    ],
                },
                headers=nonce_headers(),
            )

        async def chall(request):
            # Validate over the wire like a real CA: fetch the challenge body
            # from the gateway's HTTP app. Must be async — the gateway serves
            # the challenge from this same event loop, so a blocking fetch
            # here would deadlock (this bug shipped in round 4).
            import aiohttp

            url = f"http://{self.challenge_host}/.well-known/acme-challenge/tok-123"
            async with aiohttp.ClientSession() as session:
                async with session.get(url, timeout=aiohttp.ClientTimeout(total=5)) as resp:
                    body = await resp.text()
            expected = f"tok-123.{self.thumbprint()}"
            if body == expected:
                self.authz_status = "valid"
                self.validated_tokens.append("tok-123")
            else:
                self.authz_status = "invalid"
            return web.json_response({"status": self.authz_status}, headers=nonce_headers())

        async def finalize(request):
            _, payload = parse_jws(await request.read())
            assert self.authz_status == "valid", "finalize before authz valid"
            self.cert_pem = self.ca.sign_csr(_b64u_decode(payload["csr"]))
            self.order_status = "valid"
            return web.json_response(
                {"status": "valid", "certificate": f"{self.base}/cert/1"},
                headers=nonce_headers(),
            )

        async def cert(request):
            return web.Response(body=self.cert_pem.encode(), headers=nonce_headers())

        app.router.add_get("/directory", directory)
        app.router.add_route("HEAD", "/new-nonce", new_nonce)
        app.router.add_post("/new-account", new_account)
        app.router.add_post("/new-order", new_order)
        app.router.add_post("/authz/1", authz)
        app.router.add_post("/chall/1", chall)
        app.router.add_post("/finalize/1", finalize)
        app.router.add_post("/cert/1", cert)
        return app


def _tls_get(port: int, server_name: str, path: str, ca_pem: str = None) -> tuple:
    """Raw TLS GET with SNI; returns (status_line, body, peer_cn)."""
    if ca_pem:
        ctx = ssl.create_default_context(cadata=ca_pem)
    else:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    tls = ctx.wrap_socket(sock, server_hostname=server_name)
    der = tls.getpeercert(binary_form=True)
    cn = minicrypto.cert_subject(der, inform="DER")
    tls.sendall(
        f"GET {path} HTTP/1.1\r\nHost: {server_name}\r\nConnection: close\r\n\r\n".encode()
    )
    data = b""
    while True:
        chunk = tls.recv(65536)
        if not chunk:
            break
        data += chunk
    tls.close()
    head, _, body = data.partition(b"\r\n\r\n")
    return head.split(b"\r\n")[0].decode(), body.decode(errors="replace"), cn


class TestSniStore:
    def test_per_domain_certs_served_by_sni(self, tmp_path):
        store = CertStore(str(tmp_path))
        for dom in ("a.test", "b.test"):
            chain, key = self_signed_cert(dom)
            store.put(dom, chain, key)
        assert store.domains() == ["a.test", "b.test"]
        assert store.has("A.TEST")

        async def run():
            app = web.Application()

            async def hello(request):
                return web.Response(text="hi")

            app.router.add_get("/{tail:.*}", hello)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0, ssl_context=store.server_context())
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            try:
                for dom in ("a.test", "b.test"):
                    status, _, cn = await asyncio.to_thread(_tls_get, port, dom, "/")
                    assert "200" in status
                    assert cn == f"CN={dom}"
                # Unknown SNI gets the placeholder, not a handshake failure.
                status, _, cn = await asyncio.to_thread(_tls_get, port, "other.test", "/")
                assert "placeholder" not in cn or cn  # handshake completed
            finally:
                await runner.cleanup()

        asyncio.run(run())


class TestAcmeEndToEnd:
    async def test_domain_service_gets_cert_and_serves_tls(self, tmp_path):
        ca = TestCa()

        # A tiny upstream replica the domain routes to.
        upstream = web.Application()

        async def pong(request):
            return web.json_response({"via": "replica", "path": request.path})

        upstream.router.add_get("/{tail:.*}", pong)
        upstream_server = TestServer(upstream)
        await upstream_server.start_server()

        # Gateway HTTP app with a TLS manager pointing at the fake ACME.
        fake = FakeAcme(ca, challenge_host="")
        acme_server = TestServer(fake.app())
        await acme_server.start_server()
        fake.base = f"http://127.0.0.1:{acme_server.port}"

        tm = TlsManager(str(tmp_path), acme_directory=f"{fake.base}/directory")
        gw_app = create_app("gw-token", tls_manager=tm)
        gw_server = TestServer(gw_app)
        await gw_server.start_server()
        fake.challenge_host = f"127.0.0.1:{gw_server.port}"

        try:
            # Register a service with a domain via the control API.
            resp = await gw_server.session if False else None
            import aiohttp

            async with aiohttp.ClientSession() as session:
                r = await session.post(
                    f"http://127.0.0.1:{gw_server.port}/api/registry/register",
                    json={
                        "project": "main", "run_name": "svc", "domain": "svc.test",
                        "replicas": [{"host": "127.0.0.1", "port": upstream_server.port}],
                    },
                    headers={"Authorization": "Bearer gw-token"},
                )
                assert r.status == 200

            # Issuance kicked off in the background; wait for the store.
            for _ in range(100):
                if tm.store.has("svc.test"):
                    break
                await asyncio.sleep(0.1)
            assert tm.store.has("svc.test"), "ACME issuance never completed"
            assert fake.validated_tokens == ["tok-123"]  # validated over HTTP

            # HTTPS listener serves the CA-signed cert under SNI and routes by
            # domain to the replica.
            runner = web.AppRunner(gw_app)
            await runner.setup()
            tls_site = web.TCPSite(runner, "127.0.0.1", 0, ssl_context=tm.server_context())
            await tls_site.start()
            tls_port = tls_site._server.sockets[0].getsockname()[1]
            try:
                status, body, cn = await asyncio.to_thread(
                    _tls_get, tls_port, "svc.test", "/ping", ca.ca_pem
                )
                assert "200" in status
                assert cn == "CN=svc.test"
                assert '"via": "replica"' in body
            finally:
                await runner.cleanup()
        finally:
            await gw_server.close()
            await acme_server.close()
            await upstream_server.close()

    async def test_near_expiry_cert_is_renewed(self, tmp_path):
        """A stored cert inside the renewal window is re-issued over ACME and
        the SNI store picks up the fresh one (certbot-renewal parity)."""
        ca = TestCa()
        fake = FakeAcme(ca, challenge_host="")
        acme_server = TestServer(fake.app())
        await acme_server.start_server()
        fake.base = f"http://127.0.0.1:{acme_server.port}"

        tm = TlsManager(
            str(tmp_path), acme_directory=f"{fake.base}/directory",
            renew_before_days=10,
        )
        # Seed a soon-expiring ACME-issued cert (5 days < the 10-day window)
        # plus an operator-provisioned one the sweep must never touch.
        chain, key = self_signed_cert("svc.test", days=5)
        tm.store.put("svc.test", chain, key, managed=True)
        op_chain, op_key = self_signed_cert("operator.test", days=5)
        tm.store.put("operator.test", op_chain, op_key)
        old_exp = tm.store.expiry("svc.test")
        op_exp = tm.store.expiry("operator.test")

        gw_app = create_app("gw-token", tls_manager=tm)
        gw_server = TestServer(gw_app)
        await gw_server.start_server()
        fake.challenge_host = f"127.0.0.1:{gw_server.port}"
        try:
            assert tm.renewal_due("svc.test")
            assert tm.check_renewals() == ["svc.test"]
            for _ in range(100):
                exp = tm.store.expiry("svc.test")
                if exp is not None and exp != old_exp:
                    break
                await asyncio.sleep(0.1)
            assert tm.store.expiry("svc.test") != old_exp, "never renewed"
            pem = (tmp_path / "svc.test" / "fullchain.pem").read_bytes()
            assert "fake-acme-ca" in minicrypto.cert_issuer(pem)
            # The fresh 30-day cert sits outside the 10-day window.
            assert not tm.renewal_due("svc.test")
            assert tm.check_renewals() == []
            # The operator-provisioned cert was left alone despite being due.
            assert tm.store.expiry("operator.test") == op_exp
            assert not tm.store.is_managed("operator.test")
        finally:
            await gw_server.close()
            await acme_server.close()

    async def test_badnonce_retry_and_account_persistence(self, tmp_path):
        """RFC 8555 §6.5 badNonce rejections are retried with the fresh nonce,
        and the account key + kid survive a manager restart."""
        ca = TestCa()
        fake = FakeAcme(ca, challenge_host="")
        acme_server = TestServer(fake.app())
        await acme_server.start_server()
        fake.base = f"http://127.0.0.1:{acme_server.port}"

        tm = TlsManager(str(tmp_path), acme_directory=f"{fake.base}/directory")
        gw_app = create_app("gw-token", tls_manager=tm)
        gw_server = TestServer(gw_app)
        await gw_server.start_server()
        fake.challenge_host = f"127.0.0.1:{gw_server.port}"
        try:
            fake.badnonce_remaining = 2  # two rejections, third attempt lands
            assert await tm.ensure("svc.test")
            assert fake.new_order_posts == 3

            acct_path = tmp_path / "acme_account.json"
            assert acct_path.exists()
            acct = json.loads(acct_path.read_text())
            assert acct["kid"] == tm.acme.kid

            # "Restart": a new manager over the same certs dir reuses the
            # registration instead of creating a fresh account.
            tm2 = TlsManager(str(tmp_path), acme_directory=f"{fake.base}/directory")
            assert tm2.acme.kid == tm.acme.kid
            assert minicrypto.pubkey_xy(tm.acme.account_key) == minicrypto.pubkey_xy(
                tm2.acme.account_key
            )
        finally:
            await gw_server.close()
            await acme_server.close()

    async def test_issuance_failure_does_not_break_registration(self, tmp_path):
        """A dead ACME endpoint must not fail service registration — the
        appliance keeps serving HTTP and logs the issuance failure."""
        tm = TlsManager(str(tmp_path), acme_directory="http://127.0.0.1:1/directory")
        gw_app = create_app("gw-token", tls_manager=tm)
        gw_server = TestServer(gw_app)
        await gw_server.start_server()
        try:
            import aiohttp

            async with aiohttp.ClientSession() as session:
                r = await session.post(
                    f"http://127.0.0.1:{gw_server.port}/api/registry/register",
                    json={"project": "main", "run_name": "s2", "domain": "dead.test",
                          "replicas": []},
                    headers={"Authorization": "Bearer gw-token"},
                )
                assert r.status == 200
            await asyncio.sleep(0.3)
            assert not tm.store.has("dead.test")
        finally:
            await gw_server.close()
