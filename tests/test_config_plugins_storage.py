"""Server config manager, plugin policies, blob storage.

Parity: reference services/config.py (ServerConfigManager), plugins.py:59
(load_plugins/apply policies), services/storage/ (blob offload)."""

import pytest

from dstack_tpu.core.errors import ServerClientError
from dstack_tpu.plugins import ApplyPolicy, Plugin
from dstack_tpu.server.services import config as config_service
from dstack_tpu.server.services import plugins as plugins_service
from dstack_tpu.server.services import storage as storage_service
from tests.common import api_server


class TagPolicy(ApplyPolicy):
    """Test policy: forces max_duration and rejects privileged runs."""

    def on_run_apply(self, user, project, spec):
        if spec.configuration.privileged:
            raise ValueError("privileged runs are forbidden by policy")
        spec.configuration.env.values["POLICY_APPLIED"] = f"{user}@{project}"
        return spec


class TestPlugin(Plugin):
    __test__ = False  # not a pytest class

    def get_apply_policies(self):
        return [TagPolicy()]


@pytest.fixture(autouse=True)
def _reset():
    plugins_service.reset_plugins()
    storage_service.set_storage(None)
    yield
    plugins_service.reset_plugins()
    storage_service.set_storage(None)


class TestPlugins:
    async def test_policy_mutates_and_rejects(self):
        loaded = plugins_service.load_plugins(
            ["tests.test_config_plugins_storage:TestPlugin"]
        )
        assert loaded == ["tests.test_config_plugins_storage:TestPlugin"]
        async with api_server() as api:
            run = await api.post(
                "/api/project/main/runs/submit",
                {
                    "run_spec": {
                        "run_name": "plugged",
                        "configuration": {"type": "task", "commands": ["true"]},
                    }
                },
            )
            # The policy stamped the env before the spec was persisted.
            assert (
                run["run_spec"]["configuration"]["env"]["values"]["POLICY_APPLIED"]
                == "admin@main"
            )

            resp = await api.post(
                "/api/project/main/runs/submit",
                {
                    "run_spec": {
                        "run_name": "nope",
                        "configuration": {
                            "type": "task",
                            "commands": ["true"],
                            "privileged": True,
                        },
                    }
                },
                expect=400,
            )
            assert "forbidden by policy" in str(resp)

    def test_broken_plugin_skipped(self):
        loaded = plugins_service.load_plugins(
            ["nonexistent.module:Nope", "tests.test_config_plugins_storage:TagPolicy"]
        )
        assert loaded == []  # TagPolicy is not a Plugin subclass; both skipped


class TestServerConfig:
    async def test_config_creates_projects_and_backends(self, tmp_path):
        (tmp_path / "config.yml").write_text(
            """
projects:
  - name: research
    backends:
      - type: mock
  - name: main
    backends:
      - type: mock
"""
        )
        cfg = config_service.load_config(tmp_path)
        assert [p.name for p in cfg.projects] == ["research", "main"]
        async with api_server() as api:
            admin = await api.db.fetchone("SELECT * FROM users WHERE username = 'admin'")
            await config_service.apply_config(api.db, admin, cfg)
            rows = await api.db.fetchall("SELECT name FROM projects WHERE deleted = 0")
            assert {r["name"] for r in rows} == {"main", "research"}
            backends = await api.post("/api/project/research/backends/list")
            assert any(b["type"] == "mock" for b in backends)
            # Idempotent: applying again changes nothing.
            await config_service.apply_config(api.db, admin, cfg)
            rows = await api.db.fetchall("SELECT name FROM projects WHERE deleted = 0")
            assert len(rows) == 2

    def test_default_config_written_on_first_boot(self, tmp_path):
        cfg = config_service.load_config(tmp_path)
        assert cfg.projects == []
        text = (tmp_path / "config.yml").read_text()
        assert "projects:" in text
        # Second load parses the written default.
        assert config_service.load_config(tmp_path).plugins == []


class FakeGcsRequest:
    """Scripted (method,url,params,data) -> (status, body) for GcsStorage."""

    def __init__(self):
        self.objects = {}
        self.calls = []

    async def __call__(self, method, url, params, data):
        self.calls.append((method, url, params))
        if method == "POST":
            self.objects[params["name"]] = data
            return 200, b"{}"
        name = url.rsplit("/o/", 1)[1]
        from urllib.parse import unquote

        name = unquote(name)
        if method == "GET":
            if name not in self.objects:
                return 404, b"not found"
            return 200, self.objects[name]
        if method == "DELETE":
            return (204, b"") if self.objects.pop(name, None) is not None else (404, b"")
        return 500, b"?"


class TestStorage:
    async def test_file_storage_roundtrip(self, tmp_path):
        store = storage_service.FileStorage(str(tmp_path / "blobs"))
        await store.put("codes/p/r/abc", b"tarball-bytes")
        assert await store.get("codes/p/r/abc") == b"tarball-bytes"
        await store.delete("codes/p/r/abc")
        assert await store.get("codes/p/r/abc") is None

    async def test_gcs_storage_roundtrip(self):
        req = FakeGcsRequest()
        store = storage_service.GcsStorage("my-bucket", prefix="dstack", request=req)
        await store.put("codes/p/r/abc", b"blob")
        assert await store.get("codes/p/r/abc") == b"blob"
        assert req.objects == {"dstack/codes/p/r/abc": b"blob"}
        await store.delete("codes/p/r/abc")
        assert await store.get("codes/p/r/abc") is None

    async def test_code_blobs_offloaded_and_fetched(self, tmp_path):
        """With storage configured, upload_code keeps the DB row blob-less and the
        scheduler's code fetch reads from the store."""
        storage_service.set_storage(storage_service.FileStorage(str(tmp_path / "s")))
        async with api_server() as api:
            await api.post("/api/project/main/repos/init", {"repo_name": "r1"})
            import json as _json

            blob = b"fake-code-tarball"
            resp = await api.client.post(
                "/api/project/main/repos/r1/upload_code",
                data=blob,
                headers={"Authorization": f"Bearer {api.token}"},
            )
            assert resp.status == 200
            code_hash = _json.loads(await resp.text())["code_hash"]
            row = await api.db.fetchone("SELECT * FROM codes")
            assert row["blob"] is None  # offloaded

            from dstack_tpu.core.models.runs import RunSpec
            from dstack_tpu.server.background.tasks import _get_code

            proj = await api.db.fetchone("SELECT * FROM projects")
            spec = RunSpec.model_validate(
                {
                    "run_name": "x",
                    "configuration": {"type": "task", "commands": ["true"]},
                    "repo_id": "r1",
                    "repo_data": {"code_hash": code_hash},
                }
            )
            assert await _get_code(api.db, proj["id"], spec) == blob


class FakeLoggingRequest:
    """Scripted Cloud Logging API: stores entries, answers list with a filter.

    ``page_size`` caps each list response and hands out nextPageToken like the
    real API, so pagination bugs (stopping after one page) surface in tests."""

    def __init__(self, page_size=None):
        self.entries = []
        self.page_size = page_size
        self.list_calls = 0

    def __call__(self, method, url, payload):
        if url.endswith("entries:write"):
            self.entries.extend(payload["entries"])
            return 200, {}
        if url.endswith("entries:list"):
            import re

            self.list_calls += 1
            flt = payload["filter"]
            want = dict(re.findall(r'labels\.(\w+)="([^"]+)"', flt))
            ranges = dict(re.findall(r'labels\.(\w+)>="([^"]+)"', flt))
            matched = [
                e
                for e in self.entries
                if all(e["labels"].get(k) == v for k, v in want.items())
                and all(e["labels"].get(k, "") >= v for k, v in ranges.items())
            ]
            start = int(payload.get("pageToken") or 0)
            size = self.page_size or len(matched) or 1
            page = matched[start : start + size]
            resp = {"entries": page}
            if start + size < len(matched):
                resp["nextPageToken"] = str(start + size)
            return 200, resp
        return 404, {}


class TestGcpLogStorage:
    def test_write_poll_offsets(self):
        from dstack_tpu.core.models.logs import LogEvent
        from dstack_tpu.server.services.logs import GcpLogStorage

        req = FakeLoggingRequest()
        store = GcpLogStorage("my-gcp-proj", request=req)
        evs = [
            LogEvent(timestamp="2026-01-01T00:00:00+00:00", message=f"line-{i}\n")
            for i in range(5)
        ]
        store.write_logs("p1", "run1", "j1", evs[:3])
        store.write_logs("p1", "run1", "j1", evs[3:])
        store.write_logs("p1", "other", "j2", evs[:1])

        got = store.poll_logs("p1", "run1", "j1")
        assert [e.message for e in got] == [f"line-{i}\n" for i in range(5)]
        # Offset-based resume skips already-read lines.
        got = store.poll_logs("p1", "run1", "j1", start_line=3)
        assert [e.message for e in got] == ["line-3\n", "line-4\n"]
        # Other streams are isolated.
        got = store.poll_logs("p1", "other", "j2")
        assert len(got) == 1
        # The write carried the log name + labels contract.
        assert req.entries[0]["logName"] == "projects/my-gcp-proj/logs/dstack-tpu-run-logs"
        assert req.entries[0]["labels"]["line"] == "000000000000"

    def test_poll_follows_pagination(self):
        """Lines past the first page must still be reachable: the poller follows
        nextPageToken instead of stopping at pageSize (a long job's lines >= 1000
        would otherwise never be returned)."""
        from dstack_tpu.core.models.logs import LogEvent
        from dstack_tpu.server.services.logs import GcpLogStorage

        req = FakeLoggingRequest(page_size=2)
        store = GcpLogStorage("my-gcp-proj", request=req)
        evs = [
            LogEvent(timestamp="2026-01-01T00:00:00+00:00", message=f"line-{i}\n")
            for i in range(7)
        ]
        store.write_logs("p1", "run1", "j1", evs)
        got = store.poll_logs("p1", "run1", "j1", start_line=5)
        assert [e.message for e in got] == ["line-5\n", "line-6\n"]
        # The tail poll filtered server-side: one page, not a re-read of the stream.
        assert req.list_calls == 1
        # A full read spans every page by following nextPageToken.
        got = store.poll_logs("p1", "run1", "j1")
        assert len(got) == 7
        assert req.list_calls - 1 > 1
