"""Control-plane fault tolerance: lease-owned runs, the unified resilience
layer (retry/timeout/circuit breaker), fault injection, runner-client failure
paths, proxy replica failover, and the no-timeoutless-aiohttp-calls lint.

Strategy matches the scheduler tests: real FSM loops + real DB + mock Compute,
with the runner faked where the FSM is under test and REAL where the client's
own failure handling is under test (misbehaving raw asyncio servers)."""

import ast
import asyncio
import json
import pathlib
import time

import pytest

from dstack_tpu.core import faults
from dstack_tpu.core.errors import NoCapacityError
from dstack_tpu.server import settings
from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from dstack_tpu.server.services import leases
from dstack_tpu.server.services import resilience
from dstack_tpu.server.services.runner import client as runner_client_module
from tests.common import (
    FakeRunnerClient,
    api_server,
    drive,
    setup_mock_backend,
    tpu_task_spec,
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    FakeRunnerClient.reset()
    backends_service.reset_compute_cache()
    resilience.reset()
    faults.clear()
    monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
    yield
    resilience.reset()
    faults.clear()
    FakeRunnerClient.reset()


async def _run_id(db, run_name: str) -> str:
    row = await db.fetchone("SELECT id FROM runs WHERE run_name = ?", (run_name,))
    return row["id"]


async def _lease_row(db, run_id: str):
    return await db.fetchone("SELECT * FROM run_leases WHERE run_id = ?", (run_id,))


async def _events(db, run_id: str):
    return await db.fetchall(
        "SELECT * FROM run_events WHERE run_id = ? ORDER BY seq", (run_id,)
    )


class TestLeases:
    async def test_claim_renew_contention_reclaim(self):
        async with api_server() as api:
            with leases.as_replica("rep-a"):
                owned, reclaimed = await leases.claim_runs(api.db, ["r1", "r2"])
            assert owned == {"r1", "r2"} and reclaimed == set()
            # Another replica cannot take a live lease...
            with leases.as_replica("rep-b"):
                owned, reclaimed = await leases.claim_runs(api.db, ["r1"])
            assert owned == set() and reclaimed == set()
            # ...the holder renews (expiry advances)...
            before = (await _lease_row(api.db, "r1"))["expires_at"]
            await asyncio.sleep(0.01)
            with leases.as_replica("rep-a"):
                owned, _ = await leases.claim_runs(api.db, ["r1"])
            assert owned == {"r1"}
            assert (await _lease_row(api.db, "r1"))["expires_at"] >= before
            # ...and an EXPIRED lease is reclaimed by whoever claims next.
            await api.db.execute(
                "UPDATE run_leases SET expires_at = '2000-01-01T00:00:00+00:00'"
                " WHERE run_id = 'r1'"
            )
            with leases.as_replica("rep-b"):
                owned, reclaimed = await leases.claim_runs(api.db, ["r1"])
            assert owned == {"r1"} and reclaimed == {"r1"}
            row = await _lease_row(api.db, "r1")
            assert row["owner"] == "rep-b" and row["reclaims"] == 1

    async def test_passes_process_only_owned_runs(self):
        """A run leased to another live replica is untouched by this replica's
        passes; once the lease expires the run is reclaimed, reconciled (with
        a run_event) and driven to completion."""
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("mine", "v5e-8"))
            await api.post("/api/project/main/runs/submit", tpu_task_spec("theirs", "v5e-8"))
            theirs = await _run_id(api.db, "theirs")
            with leases.as_replica("other-replica"):
                await leases.claim_runs(api.db, [theirs])

            await drive(api.db)
            runs = {
                r["run_name"]: r["status"]
                for r in await api.db.fetchall("SELECT run_name, status FROM runs")
            }
            assert runs["mine"] == "done"
            assert runs["theirs"] == "submitted"  # not ours to schedule

            # The other replica "dies": its lease expires, we reclaim + finish.
            await api.db.execute(
                "UPDATE run_leases SET expires_at = '2000-01-01T00:00:00+00:00'"
                " WHERE run_id = ?",
                (theirs,),
            )
            await drive(api.db)
            row = await api.db.fetchone(
                "SELECT status FROM runs WHERE id = ?", (theirs,)
            )
            assert row["status"] == "done"
            recon = [
                e for e in await _events(api.db, theirs)
                if e["new_status"] == "reconciled"
            ]
            assert recon and recon[0]["reason"] == "lease_reclaimed"
            # Terminal runs hold no lease (released at finalize).
            assert await _lease_row(api.db, theirs) is None

    async def test_startup_reconcile_adopts_orphan_and_probes(self, monkeypatch):
        """A run left mid-flight by a dead replica is adopted at startup: the
        lease moves, the runner is re-probed, and the timeline records it."""
        monkeypatch.setattr(
            FakeRunnerClient,
            "default_script",
            lambda self: [{"job_states": [{"state": "running"}], "logs": [], "offset": 1}],
        )
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("orphan", "v5e-8"))
            await drive(api.db, passes=4)
            run_id = await _run_id(api.db, "orphan")
            row = await api.db.fetchone("SELECT status FROM runs WHERE id = ?", (run_id,))
            assert row["status"] == "running"
            # Simulate the owning replica having died mid-run.
            await api.db.execute(
                "UPDATE run_leases SET owner = 'dead-replica',"
                " expires_at = '2000-01-01T00:00:00+00:00' WHERE run_id = ?",
                (run_id,),
            )
            adopted = await leases.startup_reconcile(api.db)
            assert adopted == 1
            assert (await _lease_row(api.db, run_id))["owner"] == leases.replica_id()
            recon = [
                e for e in await _events(api.db, run_id)
                if e["new_status"] == "reconciled"
            ]
            assert recon and recon[-1]["reason"] == "startup"
            assert "1 reachable" in recon[-1]["message"]

            # The OWNER column surfaces through the runs API.
            data = await api.post("/api/project/main/runs/list")
            by_name = {r["run_spec"]["run_name"]: r for r in data}
            assert by_name["orphan"]["owner"] == leases.replica_id()

    async def test_sweep_drops_leases_of_finished_runs(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("fin", "v5e-8"))
            run_id = await _run_id(api.db, "fin")
            await drive(api.db)
            # Simulate a crash between terminal transition and release.
            await api.db.execute(
                "INSERT INTO run_leases (run_id, owner, acquired_at, heartbeat_at,"
                " expires_at) VALUES (?, 'ghost', '2026-01-01', '2026-01-01', '2099-01-01')",
                (run_id,),
            )
            await leases.sweep(api.db)
            assert await _lease_row(api.db, run_id) is None

    async def test_disabled_leases_own_everything(self, monkeypatch):
        monkeypatch.setattr(settings, "RUN_LEASES_ENABLED", False)
        async with api_server() as api:
            owned, reclaimed = await leases.claim_runs(api.db, ["a", "b"])
            assert owned == {"a", "b"} and reclaimed == set()
            assert await _lease_row(api.db, "a") is None


class TestCircuitBreaker:
    def test_opens_after_threshold_then_half_open_probe(self, monkeypatch):
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 3)
        monkeypatch.setattr(settings, "BREAKER_COOLDOWN", 0.05)
        t = "backend:test"
        for _ in range(2):
            resilience.record_failure(t)
        assert resilience.state(t) == "closed" and not resilience.is_open(t)
        resilience.record_failure(t)
        assert resilience.state(t) == "open" and resilience.is_open(t)
        with pytest.raises(resilience.BreakerOpenError):
            resilience.check(t)
        time.sleep(0.06)
        assert not resilience.is_open(t)  # cooled down: probe may route here
        resilience.check(t)  # first caller becomes the half-open probe
        assert resilience.state(t) == "half_open"
        with pytest.raises(resilience.BreakerOpenError):
            resilience.check(t)  # concurrent callers rejected during the probe
        resilience.record_success(t)
        assert resilience.state(t) == "closed"
        resilience.check(t)

    def test_half_open_failure_reopens(self, monkeypatch):
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 1)
        monkeypatch.setattr(settings, "BREAKER_COOLDOWN", 0.02)
        t = "backend:flaky"
        resilience.record_failure(t)
        assert resilience.state(t) == "open"
        time.sleep(0.03)
        resilience.check(t)
        resilience.record_failure(t)  # the probe failed
        assert resilience.state(t) == "open" and resilience.is_open(t)

    async def test_cancelled_probe_releases_the_half_open_slot(self, monkeypatch):
        """A half-open probe whose task is cancelled must hand the slot back —
        otherwise the breaker wedges open forever (no outcome ever recorded)."""
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 1)
        monkeypatch.setattr(settings, "BREAKER_COOLDOWN", 0.02)
        t = "backend:wedge"
        resilience.record_failure(t)
        await asyncio.sleep(0.03)
        started = asyncio.Event()

        async def hang():
            started.set()
            await asyncio.sleep(30)

        task = asyncio.create_task(resilience.with_retry(hang, target=t, attempts=1))
        await started.wait()
        with pytest.raises(resilience.BreakerOpenError):
            resilience.check(t)  # probe slot held by the hanging task
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        resilience.check(t)  # released: this caller becomes the probe

    def test_stale_probe_presumed_dead_after_cooldown(self, monkeypatch):
        """Belt-and-braces for probe holders that vanish without cancelling
        through with_retry (crashed pass): the slot expires after a cooldown."""
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 1)
        monkeypatch.setattr(settings, "BREAKER_COOLDOWN", 0.02)
        t = "backend:stale"
        resilience.record_failure(t)
        time.sleep(0.03)
        resilience.check(t)  # probe taken...
        with pytest.raises(resilience.BreakerOpenError):
            resilience.check(t)
        time.sleep(0.03)  # ...never reports back; presumed dead
        resilience.check(t)

    async def test_with_retry_retries_then_succeeds(self):
        calls = []

        async def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ValueError("transient")
            return 7

        result = await resilience.with_retry(
            flaky, attempts=3, base_delay=0.001, max_delay=0.002
        )
        assert result == 7 and len(calls) == 3

    async def test_with_retry_per_attempt_timeout(self):
        async def slow():
            await asyncio.sleep(0.5)

        t0 = time.monotonic()
        with pytest.raises(asyncio.TimeoutError):
            await resilience.with_retry(slow, attempts=1, timeout=0.05)
        assert time.monotonic() - t0 < 0.4

    async def test_with_retry_deadline_bounds_total(self):
        async def always_fail():
            raise ValueError("nope")

        t0 = time.monotonic()
        with pytest.raises(ValueError):
            await resilience.with_retry(
                always_fail, attempts=50, base_delay=0.05, max_delay=0.05, deadline=0.2
            )
        assert time.monotonic() - t0 < 1.0

    async def test_treat_as_success_closes_breaker(self, monkeypatch):
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 2)
        t = "backend:answers"
        resilience.record_failure(t)

        async def no_capacity():
            raise NoCapacityError("sold out")

        with pytest.raises(NoCapacityError):
            await resilience.with_retry(
                no_capacity, target=t, attempts=1, treat_as_success=(NoCapacityError,)
            )
        # The NoCapacity answer reset the consecutive-failure count: one more
        # failure is again below the threshold.
        resilience.record_failure(t)
        assert resilience.state(t) == "closed"

    async def test_breaker_state_rendered_on_metrics(self, monkeypatch):
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 1)
        async with api_server() as api:
            resilience.record_failure("backend:gcp")
            resp = await api.client.get("/metrics")
            text = await resp.text()
            assert 'dstack_tpu_circuit_breaker_state{target="backend:gcp"} 2' in text
            assert "# TYPE dstack_tpu_run_leases gauge" in text


class TestSchedulerDegradation:
    async def test_open_backend_breaker_requeues_instead_of_failing(self, monkeypatch):
        """With the mock backend's circuit open, placement defers (reason'd
        run_event, jobs stay submitted); when it closes, the run completes."""
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 1)
        monkeypatch.setattr(settings, "BREAKER_COOLDOWN", 60.0)
        async with api_server() as api:
            await setup_mock_backend(api)
            resilience.record_failure("backend:mock")
            await api.post("/api/project/main/runs/submit", tpu_task_spec("deferred", "v5e-8"))
            run_id = await _run_id(api.db, "deferred")
            await drive(api.db, passes=3)
            row = await api.db.fetchone("SELECT status FROM runs WHERE id = ?", (run_id,))
            assert row["status"] == "submitted"
            evs = await _events(api.db, run_id)
            breaker_evs = [e for e in evs if e["reason"] == "backend_circuit_open"]
            assert len(breaker_evs) == 1  # deduped: one event, not one per pass
            # Backend recovers -> breaker closes -> the same queued gang places.
            resilience.record_success("backend:mock")
            await drive(api.db)
            row = await api.db.fetchone("SELECT status FROM runs WHERE id = ?", (run_id,))
            assert row["status"] == "done"

    async def test_injected_backend_faults_open_breaker(self, monkeypatch):
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 2)
        monkeypatch.setattr(settings, "BREAKER_COOLDOWN", 60.0)
        faults.configure(
            {"sites": {"backend.create_slice": {"fail": 1.0, "error": "injected 5xx"}}}
        )
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec(
                    "chaos", "v5e-8", retry={"on_events": ["no-capacity"], "duration": "1h"}
                ),
            )
            await drive(api.db, passes=4)
            assert resilience.state("backend:mock") == "open"
            run_id = await _run_id(api.db, "chaos")
            row = await api.db.fetchone("SELECT status FROM runs WHERE id = ?", (run_id,))
            assert row["status"] == "submitted"  # degraded, not failed


class TestFaults:
    async def test_fail_and_budget_and_match(self):
        faults.configure(
            {"seed": 1, "sites": {"s": {"fail": 1.0, "times": 2, "match": "yes"}}}
        )
        await faults.check("s", detail="no-hit")  # match filter: no injection
        with pytest.raises(faults.FaultInjected):
            await faults.check("s", detail="yes-1")
        with pytest.raises(faults.FaultInjected):
            await faults.check("s", detail="yes-2")
        await faults.check("s", detail="yes-3")  # budget exhausted
        assert faults.stats() == {"s": 2}

    async def test_env_config_and_clear(self, monkeypatch):
        monkeypatch.setenv(
            "DSTACK_TPU_FAULTS", json.dumps({"sites": {"e": {"fail": 1.0}}})
        )
        with pytest.raises(faults.FaultInjected):
            await faults.check("e")
        monkeypatch.delenv("DSTACK_TPU_FAULTS")
        await faults.check("e")

    async def test_delay_injection(self):
        faults.configure({"sites": {"d": {"delay": 0.05}}})
        t0 = time.monotonic()
        await faults.check("d")
        assert time.monotonic() - t0 >= 0.05

    async def test_inactive_is_noop(self):
        await faults.check("anything")
        assert not faults.active()


class TestJitteredGangRetry:
    def test_jitter_bounds_and_determinism(self):
        cap = tasks._retry_delay(2)
        assert cap == min(settings.RETRY_BACKOFF_BASE * 4, settings.RETRY_BACKOFF_MAX)
        d1 = tasks._retry_delay(2, jitter_key="run-a:0:2")
        d2 = tasks._retry_delay(2, jitter_key="run-a:0:2")
        d3 = tasks._retry_delay(2, jitter_key="run-b:0:2")
        assert d1 == d2  # stable across passes: the backoff window can't flap
        assert 0.5 * cap <= d1 <= cap
        assert 0.5 * cap <= d3 <= cap
        assert d1 != d3  # different runs desynchronize

    def test_cap_still_respected(self):
        d = tasks._retry_delay(50, jitter_key="x")
        assert d <= settings.RETRY_BACKOFF_MAX


async def _seed_running_job(db, run_name: str, port: int) -> dict:
    """A running single-job run whose agent endpoint is 127.0.0.1:port —
    pointed at a misbehaving raw socket server by the failure-path tests."""
    proj = await db.fetchone("SELECT * FROM projects LIMIT 1")
    run_spec = {
        "run_name": run_name,
        "configuration": {"type": "task", "commands": ["sleep 1"]},
    }
    await db.execute(
        "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at, status,"
        " run_spec) VALUES (?, ?, ?, ?, '2026-01-01', 'running', ?)",
        (f"run-{run_name}", proj["id"], proj["owner_id"], run_name, json.dumps(run_spec)),
    )
    job_spec = {
        "job_name": f"{run_name}-0-0",
        "image_name": "stub",
        "requirements": {"resources": {}},
    }
    jpd = {
        "backend": "local",
        "instance_type": {
            "name": "local", "resources": {"cpus": 1, "memory_gb": 1, "disk_gb": 1},
        },
        "instance_id": f"i-{run_name}",
        "hostname": "127.0.0.1",
        "region": "local",
    }
    jrd = {"runner_port": port}
    await db.execute(
        "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, job_spec, status,"
        " submitted_at, job_provisioning_data, job_runtime_data)"
        " VALUES (?, ?, ?, ?, 0, ?, 'running', '2026-01-01', ?, ?)",
        (f"job-{run_name}", proj["id"], f"run-{run_name}", run_name,
         json.dumps(job_spec), json.dumps(jpd), json.dumps(jrd)),
    )
    return await db.fetchone("SELECT * FROM jobs WHERE id = ?", (f"job-{run_name}",))


async def _drive_disconnect_to_termination(db, job_row):
    """Two pull passes: the first records the disconnect, the second (grace
    window forced to 0) terminates. Returns the fresh job row."""
    await tasks._process_pulling_or_running(db, job_row)
    mid = await db.fetchone("SELECT * FROM jobs WHERE id = ?", (job_row["id"],))
    assert mid["disconnected_at"] is not None, "first failure should start the grace window"
    assert mid["status"] == "running"
    await tasks._process_pulling_or_running(db, mid)
    return await db.fetchone("SELECT * FROM jobs WHERE id = ?", (job_row["id"],))


class TestRunnerFailurePaths:
    """The REAL RunnerClient against misbehaving sockets: each failure mode
    must land the job in the right FSM position with a run_event to show for
    it (these paths were previously untested)."""

    @pytest.fixture(autouse=True)
    def _real_client(self, monkeypatch):
        monkeypatch.setattr(
            tasks, "get_runner_client", runner_client_module.get_runner_client
        )
        monkeypatch.setattr(settings, "RUNNER_DISCONNECT_TIMEOUT", 0.0)
        monkeypatch.setattr(settings, "RUNNER_CALL_ATTEMPTS", 1)
        monkeypatch.setattr(settings, "RUNNER_REQUEST_TIMEOUT", 0.5)

    async def test_connect_failure_transitions_to_unreachable(self):
        # Bind-and-release a port so nothing listens on it.
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        async with api_server() as api:
            job = await _seed_running_job(api.db, "refused", port)
            final = await _drive_disconnect_to_termination(api.db, job)
            assert final["status"] == "terminating"
            assert final["termination_reason"] == "instance_unreachable"
            evs = await _events(api.db, final["run_id"])
            assert any(
                e["new_status"] == "terminating" and e["job_id"] == final["id"]
                for e in evs
            )

    async def test_mid_body_disconnect_transitions_to_unreachable(self):
        async def handler(reader, writer):
            await reader.read(1024)
            # Promise 4096 bytes, deliver 7, hang up: a mid-body disconnect.
            writer.write(b"HTTP/1.1 200 OK\r\nContent-Length: 4096\r\n\r\npartial")
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        try:
            async with api_server() as api:
                job = await _seed_running_job(api.db, "midbody", port)
                final = await _drive_disconnect_to_termination(api.db, job)
                assert final["status"] == "terminating"
                assert final["termination_reason"] == "instance_unreachable"
        finally:
            server.close()
            await server.wait_closed()

    async def test_slow_response_hits_deadline_not_forever(self):
        async def handler(reader, writer):
            await reader.read(1024)
            await asyncio.sleep(30)  # never answers within the deadline

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        try:
            async with api_server() as api:
                job = await _seed_running_job(api.db, "slowpoke", port)
                t0 = time.monotonic()
                final = await _drive_disconnect_to_termination(api.db, job)
                # The explicit request timeout bounded both passes.
                assert time.monotonic() - t0 < 5.0
                assert final["status"] == "terminating"
                assert final["termination_reason"] == "instance_unreachable"
        finally:
            server.close()
            await server.wait_closed()

    async def test_runner_5xx_counts_toward_breaker_but_4xx_does_not(self, monkeypatch):
        monkeypatch.setattr(settings, "BREAKER_THRESHOLD", 2)

        async def handler(reader, writer):
            data = await reader.read(1024)
            status = b"500 Oops" if b"pull" in data else b"404 Nope"
            writer.write(
                b"HTTP/1.1 " + status + b"\r\nContent-Length: 0\r\n\r\n"
            )
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        target = f"runner:http://127.0.0.1:{port}"
        client = runner_client_module.RunnerClient("127.0.0.1", port)
        try:
            with pytest.raises(runner_client_module.RunnerError):
                await client.pull()  # 500
            with pytest.raises(runner_client_module.RunnerError):
                await client.pull()  # 500 -> threshold reached
            assert resilience.state(target) == "open"
            resilience.reset()
            with pytest.raises(runner_client_module.RunnerRequestError):
                await client.run_job()  # 404: agent alive, breaker untouched
            assert resilience.state(target) == "closed"
        finally:
            server.close()
            await server.wait_closed()


class TestProxyFailover:
    async def test_upstream_502_retries_other_replica(self):
        """Replica 0 is dark; the proxy fails over to replica 1 within the
        same request instead of surfacing the 502."""
        from dstack_tpu.server.services import proxy as proxy_service

        async def handler(reader, writer):
            await reader.read(1024)
            writer.write(
                b"HTTP/1.1 200 OK\r\nContent-Length: 4\r\n\r\npong"
            )
            await writer.drain()
            writer.close()

        live = await asyncio.start_server(handler, "127.0.0.1", 0)
        live_port = live.sockets[0].getsockname()[1]
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        try:
            async with api_server() as api:
                proj = await api.db.fetchone("SELECT * FROM projects LIMIT 1")
                run_spec = {
                    "run_name": "ha-svc",
                    "configuration": {
                        "type": "service", "commands": ["serve"], "port": 8000,
                        "auth": False,
                    },
                }
                await api.db.execute(
                    "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at,"
                    " status, run_spec) VALUES (?, ?, ?, 'ha-svc', '2026-01-01',"
                    " 'running', ?)",
                    ("run-ha", proj["id"], proj["owner_id"], json.dumps(run_spec)),
                )
                for replica_num, port in ((0, dead_port), (1, live_port)):
                    job_spec = {
                        "job_name": f"ha-svc-{replica_num}-0",
                        "image_name": "stub",
                        "requirements": {"resources": {}},
                        "service_port": 8000,
                    }
                    jpd = {
                        "backend": "local",
                        "instance_type": {
                            "name": "local",
                            "resources": {"cpus": 1, "memory_gb": 1, "disk_gb": 1},
                        },
                        "instance_id": f"i-ha-{replica_num}",
                        "hostname": "127.0.0.1",
                        "region": "local",
                    }
                    jrd = {"ports_mapping": {"8000": port}, "probe_ready": True}
                    await api.db.execute(
                        "INSERT INTO jobs (id, project_id, run_id, run_name, job_num,"
                        " replica_num, job_spec, status, submitted_at,"
                        " job_provisioning_data, job_runtime_data)"
                        " VALUES (?, ?, 'run-ha', 'ha-svc', 0, ?, ?, 'running',"
                        " '2026-01-01', ?, ?)",
                        (f"job-ha-{replica_num}", proj["id"], replica_num,
                         json.dumps(job_spec), json.dumps(jpd), json.dumps(jrd)),
                    )
                resp = await api.client.get("/proxy/services/main/ha-svc/ping")
                body = await resp.text()
                assert resp.status == 200 and body == "pong", body
                # The dead endpoint took a breaker failure (one, so still
                # closed at the default threshold — but recorded).
                assert resilience._breakers[f"replica:127.0.0.1:{dead_port}"].failures == 1
                # A second request also succeeds (rebuilt route, live replica).
                resp = await api.client.get("/proxy/services/main/ha-svc/ping")
                assert resp.status == 200
        finally:
            live.close()
            await live.wait_closed()


REPO = pathlib.Path(__file__).resolve().parent.parent
_SCAN_DIRS = ("dstack_tpu/server", "dstack_tpu/core/services")
_HTTP_VERBS = {"request", "get", "post", "put", "delete", "ws_connect"}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _has_timeout_kw(node: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in node.keywords)


class TestExternalCallTimeoutLint:
    def test_every_aiohttp_call_has_an_explicit_timeout(self):
        """Static analysis over the server services AST: every
        aiohttp.ClientSession must either be constructed with `timeout=` or
        have ALL its verb calls (`session.request/get/post/...`) carry a
        per-request `timeout=`. An unbounded external call is exactly the bug
        class this PR exists to remove — the lint keeps it removed."""
        violations = []
        for scan in _SCAN_DIRS:
            for path in sorted((REPO / scan).rglob("*.py")):
                source = path.read_text()
                if "aiohttp" not in source:
                    continue
                tree = ast.parse(source, filename=str(path))
                naked_sessions = []
                naked_verb_calls = []
                for node in ast.walk(tree):
                    if not isinstance(node, ast.Call):
                        continue
                    name = _call_name(node)
                    if name == "ClientSession" and not _has_timeout_kw(node):
                        naked_sessions.append(node.lineno)
                    if (
                        name in _HTTP_VERBS
                        and isinstance(node.func, ast.Attribute)
                        and "session" in ast.unparse(node.func.value).lower()
                        and not _has_timeout_kw(node)
                    ):
                        naked_verb_calls.append(node.lineno)
                # A session without a default timeout is fine ONLY when every
                # request it serves sets its own.
                if naked_sessions and naked_verb_calls:
                    rel = path.relative_to(REPO)
                    violations.append(
                        f"{rel}: ClientSession without timeout at line(s)"
                        f" {naked_sessions} and timeout-less call(s) at line(s)"
                        f" {naked_verb_calls}"
                    )
        assert not violations, "\n".join(violations)

    def test_lint_is_not_vacuous(self):
        """The lint must actually be scanning code that uses aiohttp."""
        scanned = [
            p
            for scan in _SCAN_DIRS
            for p in (REPO / scan).rglob("*.py")
            if "aiohttp.ClientSession" in p.read_text()
        ]
        assert len(scanned) >= 3, scanned
