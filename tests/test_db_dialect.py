"""The sqlite/postgres dialect seam in server/db.py.

Parity: reference server/db.py supports both engines behind one session
interface and uses postgres advisory locks for multi-replica HA init
(ref services/locking.py, app.py:109-113). The image ships no postgres
driver, so the live-postgres tests skip cleanly unless a driver AND a
DSTACK_TPU_TEST_PG_DSN are present; everything else (placeholder
translation, DDL fixups, URL dispatch, the connection adapter protocol)
is exercised directly."""

import os

import pytest

from dstack_tpu.server import migrations
from dstack_tpu.server.db import (
    Database,
    PgRow,
    PostgresDialect,
    SqliteDialect,
    _PgConnection,
    make_dialect,
    split_script,
    translate_qmark,
)


def _have_pg_driver() -> bool:
    try:
        PostgresDialect._driver()
        return True
    except RuntimeError:
        return False


class TestTranslateQmark:
    def test_basic(self):
        assert translate_qmark("SELECT * FROM t WHERE a = ? AND b = ?") == (
            "SELECT * FROM t WHERE a = %s AND b = %s"
        )

    def test_question_mark_inside_literal_untouched(self):
        sql = "UPDATE t SET note = 'why?' WHERE id = ?"
        assert translate_qmark(sql) == "UPDATE t SET note = 'why?' WHERE id = %s"

    def test_escaped_quote_inside_literal(self):
        sql = "SELECT 'it''s a ?' , ? FROM t"
        assert translate_qmark(sql) == "SELECT 'it''s a ?' , %s FROM t"

    def test_no_placeholders(self):
        assert translate_qmark("SELECT 1") == "SELECT 1"


class TestScriptHandling:
    def test_split_script(self):
        script = """
        CREATE TABLE a (x TEXT);
        CREATE INDEX ix ON a(x);
        """
        assert split_script(script) == [
            "CREATE TABLE a (x TEXT)",
            "CREATE INDEX ix ON a(x)",
        ]

    def test_split_ignores_semicolons_in_literals(self):
        script = "INSERT INTO a VALUES ('x;y');CREATE TABLE b (z TEXT)"
        assert split_script(script) == [
            "INSERT INTO a VALUES ('x;y')",
            "CREATE TABLE b (z TEXT)",
        ]

    def test_blob_becomes_bytea(self):
        d = PostgresDialect("postgresql://ignored")
        assert d.fixup_ddl("blob BLOB,") == "blob BYTEA,"
        assert "BLOB" not in d.fixup_ddl("\n".join(s for _, s in migrations.MIGRATIONS))

    def test_integer_becomes_bigint(self):
        """sqlite INTEGER is 64-bit; pg INTEGER is int4 and byte counters
        (memory_usage_bytes, cpu_usage_micro) overflow it within hours."""
        d = PostgresDialect("postgresql://ignored")
        assert d.fixup_ddl("memory_usage_bytes INTEGER,") == "memory_usage_bytes BIGINT,"
        fixed = d.fixup_ddl("\n".join(s for _, s in migrations.MIGRATIONS))
        assert "INTEGER" not in fixed

    def test_migration_ddl_splits_cleanly(self):
        # Every migration script must survive the statement splitter: no
        # triggers/procedural bodies with embedded semicolons.
        for _version, script in migrations.MIGRATIONS:
            for stmt in split_script(script):
                assert stmt.upper().startswith(("CREATE", "ALTER", "INSERT", "DROP")), stmt


class TestDialectDispatch:
    def test_urls(self):
        assert isinstance(make_dialect(":memory:"), SqliteDialect)
        assert isinstance(make_dialect("/tmp/x.db"), SqliteDialect)
        assert isinstance(make_dialect("sqlite:///tmp/x.db"), SqliteDialect)
        assert isinstance(make_dialect("postgres://u@h/db"), PostgresDialect)
        assert isinstance(make_dialect("postgresql://u@h/db"), PostgresDialect)

    def test_sqlite_url_strips_scheme(self):
        assert make_dialect("sqlite:///tmp/x.db").path == "tmp/x.db"

    @pytest.mark.skipif(_have_pg_driver(), reason="a postgres driver is installed")
    def test_missing_driver_is_a_clear_error(self):
        with pytest.raises(RuntimeError, match="no driver"):
            PostgresDialect("postgresql://u@h/db").connect()


class TestPgRow:
    def test_dual_access(self):
        row = PgRow(["id", "name"], ["u1", "alice"])
        assert row["id"] == "u1"
        assert row[1] == "alice"
        assert row.keys() == ["id", "name"]
        assert list(row) == ["u1", "alice"]
        with pytest.raises(KeyError):
            row["missing"]


class _StubCursor:
    def __init__(self, log):
        self.log = log
        self.description = [("a",), ("b",)]
        self.rowcount = 1

    def execute(self, sql, params=()):
        self.log.append(("execute", sql, params))

    def executemany(self, sql, rows):
        self.log.append(("executemany", sql, rows))

    def fetchone(self):
        return (1, 2)

    def fetchall(self):
        return [(1, 2), (3, 4)]


class _StubRaw:
    def __init__(self):
        self.log = []

    def cursor(self):
        return _StubCursor(self.log)

    def commit(self):
        self.log.append(("commit",))

    def rollback(self):
        self.log.append(("rollback",))

    def close(self):
        self.log.append(("close",))


class TestPgConnectionAdapter:
    def test_execute_translates_and_wraps_rows(self):
        raw = _StubRaw()
        conn = _PgConnection(raw)
        cur = conn.execute("SELECT a, b FROM t WHERE a = ?", ("x",))
        assert raw.log == [("execute", "SELECT a, b FROM t WHERE a = %s", ("x",))]
        row = cur.fetchone()
        assert row["a"] == 1 and row["b"] == 2
        assert [r["b"] for r in cur.fetchall()] == [2, 4]
        assert cur.rowcount == 1

    def test_executemany_translates(self):
        raw = _StubRaw()
        _PgConnection(raw).executemany("INSERT INTO t VALUES (?, ?)", [(1, 2), (3, 4)])
        assert raw.log == [("executemany", "INSERT INTO t VALUES (%s, %s)", [(1, 2), (3, 4)])]

    def test_advisory_lock_sql(self):
        raw = _StubRaw()
        d = PostgresDialect("postgresql://ignored")
        d.tx_advisory_lock(_PgConnection(raw), "server-init")
        assert raw.log[0][1] == "SELECT pg_advisory_xact_lock(hashtext(%s))"
        d.session_lock(_PgConnection(raw), "server-init")
        d.session_unlock(_PgConnection(raw), "server-init")
        assert [e[1] for e in raw.log[1:]] == [
            "SELECT pg_advisory_lock(hashtext(%s))",
            "SELECT pg_advisory_unlock(hashtext(%s))",
        ]


class TestSqliteAdvisoryLockNoop:
    async def test_advisory_lock_context_is_usable(self):
        db = Database(":memory:")
        await db.connect()
        try:
            async with db.advisory_lock("server-init"):
                await db.execute(
                    "INSERT INTO users (id, username, token, created_at)"
                    " VALUES (?, ?, ?, ?)",
                    ("u1", "alice", "tok", "2026-01-01"),
                )
            row = await db.fetchone("SELECT username FROM users WHERE id = ?", ("u1",))
            assert row["username"] == "alice"
        finally:
            await db.close()

    async def test_portable_upserts_run_on_sqlite(self):
        """The ON CONFLICT statements services now use must work on sqlite."""
        db = Database(":memory:")
        await db.connect()
        try:
            await db.execute(
                "INSERT INTO service_stats (run_id, bucket, count) VALUES (?, ?, ?)"
                " ON CONFLICT (run_id, bucket) DO UPDATE SET count = excluded.count",
                ("r1", 10, 1),
            )
            await db.execute(
                "INSERT INTO service_stats (run_id, bucket, count) VALUES (?, ?, ?)"
                " ON CONFLICT (run_id, bucket) DO UPDATE SET count = excluded.count",
                ("r1", 10, 7),
            )
            row = await db.fetchone(
                "SELECT count FROM service_stats WHERE run_id = ? AND bucket = ?",
                ("r1", 10),
            )
            assert row["count"] == 7
        finally:
            await db.close()


PG_DSN = os.getenv("DSTACK_TPU_TEST_PG_DSN")


@pytest.mark.skipif(
    not (_have_pg_driver() and PG_DSN),
    reason="needs a postgres driver and DSTACK_TPU_TEST_PG_DSN",
)
class TestLivePostgres:
    """Runs only where a real postgres is available (not in this image)."""

    async def test_migrate_crud_upsert_and_locks(self):
        db = Database(PG_DSN)
        await db.connect()
        try:
            async with db.advisory_lock("pg-e2e"):
                await db.execute(
                    "INSERT INTO users (id, username, token, created_at)"
                    " VALUES (?, ?, ?, ?) ON CONFLICT (username) DO NOTHING",
                    ("u-pg", "pg-user", "tok-pg", "2026-01-01"),
                )
            row = await db.fetchone(
                "SELECT username FROM users WHERE id = ?", ("u-pg",)
            )
            assert row["username"] == "pg-user"
        finally:
            await db.execute("DELETE FROM users WHERE id = ?", ("u-pg",))
            await db.close()
