"""Volumes v1: lifecycle loop, backend create/register/delete, slice attach,
scheduler mounts, local-backend persistence.

Parity: reference services/volumes.py + process_volumes.py + TPU data disks
(gcp/compute.py:1003-1016 — disks attach at node-create time to every host of
the slice)."""

import asyncio
import json

import pytest

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.common import (
    FakeRunnerClient,
    api_server,
    drive,
    setup_mock_backend,
    tpu_task_spec,
)


@pytest.fixture(autouse=True)
def _fake_runner(monkeypatch):
    FakeRunnerClient.reset()
    backends_service.reset_compute_cache()
    yield


VOLUME_CONF = {
    "configuration": {
        "type": "volume",
        "name": "data",
        "backend": "mock",
        "region": "us-east5",
        "size": "100GB",
    }
}


class TestVolumeLifecycle:
    async def test_create_activate_delete(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            vol = await api.post("/api/project/main/volumes/create", VOLUME_CONF)
            assert vol["status"] == "submitted"
            await tasks.process_volumes(api.db)
            vol = await api.post("/api/project/main/volumes/get", {"name": "data"})
            assert vol["status"] == "active"
            assert vol["volume_id"] == "mock-disk-data"
            assert vol["provisioning_data"]["availability_zone"] == "us-east5-a"

            compute = dict(
                await backends_service.get_project_computes(
                    api.db, await api.db.fetchone("SELECT * FROM projects")
                )
            )["mock"]
            assert compute.created_volumes == ["data"]

            await api.post("/api/project/main/volumes/delete", {"names": ["data"]})
            assert compute.deleted_volumes == ["data"]

    async def test_register_external_disk(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/volumes/create",
                {
                    "configuration": {
                        "type": "volume",
                        "name": "ext",
                        "backend": "mock",
                        "region": "us-east5",
                        "volume_id": "pre-existing-disk",
                    }
                },
            )
            await tasks.process_volumes(api.db)
            vol = await api.post("/api/project/main/volumes/get", {"name": "ext"})
            assert vol["status"] == "active"
            assert vol["external"] is True
            assert vol["volume_id"] == "pre-existing-disk"

    async def test_unconfigured_backend_fails_volume(self):
        async with api_server() as api:
            await api.post(
                "/api/project/main/volumes/create",
                {
                    "configuration": {
                        "type": "volume",
                        "name": "bad",
                        "backend": "gcp",
                        "region": "us-east5",
                        "size": "10GB",
                    }
                },
            )
            await tasks.process_volumes(api.db)
            vol = await api.post("/api/project/main/volumes/get", {"name": "bad"})
            assert vol["status"] == "failed"
            assert "gcp" in vol["status_message"]


class TestAttachmentData:
    def test_gcp_device_name_is_positional(self):
        """The TPU API cannot name data disks: they surface as
        google-persistent-disk-<n> with the boot disk at n=0, so the recorded
        device must come from the disk's position in the dataDisks list — NOT
        from the volume id (which would point at a nonexistent device and let
        job writes silently land on the boot disk)."""
        from dstack_tpu.core.models.volumes import Volume, VolumeProvisioningData, VolumeStatus
        from dstack_tpu.server.background.tasks import _volume_attachment_data

        def gcp_vol(name, vid):
            import datetime
            import uuid

            return Volume(
                id=uuid.uuid4(),
                name=name,
                project_name="main",
                configuration={"name": name, "backend": "gcp", "region": "us", "size": 10},
                created_at=datetime.datetime(2026, 1, 1),
                status=VolumeStatus.ACTIVE,
                provisioning_data=VolumeProvisioningData(backend="gcp", volume_id=vid),
            )

        first = _volume_attachment_data(gcp_vol("a", "disk-aaaa"), 0)
        second = _volume_attachment_data(gcp_vol("b", "disk-bbbb"), 1)
        assert first["device_name"] == "/dev/disk/by-id/google-persistent-disk-1"
        assert second["device_name"] == "/dev/disk/by-id/google-persistent-disk-2"


class TestVolumeScheduling:
    async def test_slice_run_mounts_volume_on_all_hosts(self, monkeypatch):
        monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/volumes/create", VOLUME_CONF)
            await tasks.process_volumes(api.db)

            # v5p-16 = 2 hosts: the data disk must reach BOTH workers.
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec("vt", "v5p-16", volumes=["data:/data"]),
            )
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "vt"})
            assert run["status"] == "done", run.get("termination_reason")

            vrow = await api.db.fetchone("SELECT * FROM volumes WHERE name = 'data'")
            # Attachments recorded per worker, then cleaned when the slice retired...
            fakes = list(FakeRunnerClient.registry.values())
            assert len(fakes) == 2
            for fake in fakes:
                [mount] = fake.submitted.volumes
                assert mount.path == "/data"
                assert mount.device == "/dev/disk/dstack/data"

            compute = dict(
                await backends_service.get_project_computes(
                    api.db, await api.db.fetchone("SELECT * FROM projects")
                )
            )["mock"]
            # The slice was created WITH the volume (attach-at-create, not hot).
            assert list(compute.slice_volumes.values()) == [["data"]]

            att = await api.db.fetchall("SELECT * FROM volume_attachments")
            assert len(att) == 2
            for a in att:
                assert json.loads(a["attachment_data"])["device_name"] == "/dev/disk/dstack/data"

    async def test_volume_backed_gang_does_not_reuse_bare_slice(self, monkeypatch):
        """An idle slice without the volume cannot host a volume-backed gang —
        data disks attach at create time only."""
        monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
        async with api_server() as api:
            await setup_mock_backend(api)
            # First run provisions a bare slice and returns it to the pool.
            await api.post("/api/project/main/runs/submit", tpu_task_spec("bare", "v5p-16"))
            await drive(api.db)
            idle = await api.db.fetchall("SELECT * FROM instances WHERE status = 'idle'")
            assert len(idle) == 2

            await api.post("/api/project/main/volumes/create", VOLUME_CONF)
            await tasks.process_volumes(api.db)
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec("vt2", "v5p-16", volumes=["data:/data"]),
            )
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "vt2"})
            assert run["status"] == "done"
            compute = dict(
                await backends_service.get_project_computes(
                    api.db, await api.db.fetchone("SELECT * FROM projects")
                )
            )["mock"]
            # A SECOND slice was created (with the volume); the bare one was not reused.
            assert len(compute.created) == 2
            assert len(compute.slice_volumes) == 1

    async def test_missing_volume_rejected_at_submit(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            resp = await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec("ghostly", "v5p-16", volumes=["ghost:/data"]),
                expect=404,
            )
            assert "ghost" in str(resp)


@pytest.mark.skipif(find_runner_binary() is None, reason="native runner binary unavailable")
class TestLocalVolumeE2E:
    async def test_job_writes_persist_into_volume_dir(self, tmp_path):
        """Local backend: the volume is a host dir; the agent links it at the mount
        path and job writes land in it."""
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                await api.post(
                    "/api/project/main/volumes/create",
                    {
                        "configuration": {
                            "type": "volume",
                            "name": "scratch",
                            "backend": "local",
                            "region": "local",
                            "size": "1GB",
                        }
                    },
                )
                await tasks.process_volumes(api.db)
                vol = await api.post("/api/project/main/volumes/get", {"name": "scratch"})
                assert vol["status"] == "active"
                host_dir = json.loads(vol["provisioning_data"]["backend_data"])["host_dir"]

                mount_path = str(tmp_path / "mnt" / "scratch")
                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "vol-e2e",
                            "configuration": {
                                "type": "task",
                                "commands": [f"echo persisted-data > {mount_path}/out.txt"],
                                "volumes": [f"scratch:{mount_path}"],
                            },
                        }
                    },
                )
                for _ in range(100):
                    await drive(api.db, passes=1)
                    run = await api.post(
                        "/api/project/main/runs/get", {"run_name": "vol-e2e"}
                    )
                    if run["status"] in ("done", "failed", "terminated"):
                        break
                    await asyncio.sleep(0.1)
                assert run["status"] == "done"
                with open(f"{host_dir}/out.txt") as f:
                    assert f.read().strip() == "persisted-data"
        finally:
            logs_service.set_log_storage(None)
