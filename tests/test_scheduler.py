"""Scheduler loop tests: real FSM loops + real DB + mock Compute + fake runner.

Parity with the reference's distributed-without-a-cluster strategy (SURVEY §4,
test_process_submitted_jobs.py / test_process_running_jobs.py / test_process_runs.py)."""

import pytest

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from tests.common import (
    FakeRunnerClient,
    api_server,
    drive,
    setup_mock_backend,
    tpu_task_spec,
)

CPU_TASK = {
    "run_spec": {
        "run_name": "cpu-task",
        "configuration": {"type": "task", "commands": ["echo hi"]},
    }
}


@pytest.fixture(autouse=True)
def _fake_runner(monkeypatch):
    FakeRunnerClient.reset()
    backends_service.reset_compute_cache()
    monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
    yield
    FakeRunnerClient.reset()


async def _job_rows(db, run_name=None):
    sql = "SELECT * FROM jobs"
    params = ()
    if run_name:
        sql += " WHERE run_name = ?"
        params = (run_name,)
    return await db.fetchall(sql + " ORDER BY replica_num, job_num, submission_num", params)


class TestSubmittedJobs:
    async def test_no_capacity_fails_run(self):
        async with api_server() as api:
            # TPU request with no TPU backend configured -> no offers -> failed.
            await api.post("/api/project/main/runs/submit", tpu_task_spec("t1"))
            await drive(api.db, passes=3)
            run = await api.post("/api/project/main/runs/get", {"run_name": "t1"})
            assert run["status"] == "failed"
            job_sub = run["jobs"][0]["job_submissions"][-1]
            assert job_sub["termination_reason"] == "failed_to_start_due_to_no_capacity"

    async def test_cpu_task_runs_to_done_on_local(self):
        async with api_server() as api:
            await api.post("/api/project/main/runs/submit", CPU_TASK)
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "cpu-task"})
            assert run["status"] == "done"
            [(key, fake)] = FakeRunnerClient.registry.items()
            assert fake.ran
            assert fake.submitted.commands == ["echo hi"]

    async def test_tpu_slice_gang_placement(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            # v5p-16 = 8 chips = 2 hosts -> 2 gang jobs on one slice.
            await api.post("/api/project/main/runs/submit", tpu_task_spec("tpu1", "v5p-16"))
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "tpu1"})
            assert run["status"] == "done"
            assert len(run["jobs"]) == 2

            instances = await api.db.fetchall("SELECT * FROM instances")
            slice_ids = {r["slice_id"] for r in instances}
            assert len(slice_ids) == 1  # both workers on one slice
            assert sorted(r["worker_num"] for r in instances) == [0, 1]

            # Cluster contract: per-worker identity, shared coordinator.
            fakes = sorted(FakeRunnerClient.registry.values(), key=lambda f: f.cluster_info.node_rank)
            assert [f.cluster_info.tpu_worker_id for f in fakes] == [0, 1]
            assert fakes[0].cluster_info.nodes_num == 2
            assert fakes[0].cluster_info.coordinator_address == fakes[1].cluster_info.coordinator_address
            env = fakes[1].cluster_info.to_env()
            assert env["TPU_WORKER_ID"] == "1"
            assert env["DSTACK_NODE_RANK"] == "1"
            assert len(env["TPU_WORKER_HOSTNAMES"].split(",")) == 2

    async def test_pool_reuse_same_slice(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("r1", "v5e-8"))
            await drive(api.db)
            compute = None
            for t, c in await backends_service.get_project_computes(
                api.db, await api.db.fetchone("SELECT * FROM projects")
            ):
                if t == "mock":
                    compute = c
            assert len(compute.created) == 1
            run = await api.post("/api/project/main/runs/get", {"run_name": "r1"})
            assert run["status"] == "done"

            # Second run reuses the idle slice: no new cloud create.
            await api.post("/api/project/main/runs/submit", tpu_task_spec("r2", "v5e-8"))
            await drive(api.db)
            run2 = await api.post("/api/project/main/runs/get", {"run_name": "r2"})
            assert run2["status"] == "done"
            assert len(compute.created) == 1

    async def test_multislice_megascale_contract(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            spec = {
                "run_spec": {
                    "run_name": "ms",
                    "configuration": {
                        "type": "task",
                        "commands": ["python train.py"],
                        "resources": {"tpu": {"generation": "v5p", "chips": 8, "count": 2}},
                    },
                }
            }
            await api.post("/api/project/main/runs/submit", spec)
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "ms"})
            assert run["status"] == "done"
            assert len(run["jobs"]) == 4  # 2 slices x 2 hosts

            instances = await api.db.fetchall("SELECT * FROM instances")
            assert len({r["slice_id"] for r in instances}) == 2

            fakes = sorted(FakeRunnerClient.registry.values(), key=lambda f: f.cluster_info.node_rank)
            infos = [f.cluster_info for f in fakes]
            assert [i.slice_id for i in infos] == [0, 0, 1, 1]
            assert [i.tpu_worker_id for i in infos] == [0, 1, 0, 1]
            env = infos[3].to_env()
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == "1"
            assert "MEGASCALE_COORDINATOR_ADDRESS" in env


class TestRetries:
    async def test_no_capacity_retry_keeps_queued(self):
        async with api_server() as api:
            project = await api.db.fetchone("SELECT * FROM projects")
            from dstack_tpu.backends.mock import MockTpuCompute

            await setup_mock_backend(api)
            backends_service._compute_cache[(project["id"], "mock")] = MockTpuCompute(
                fail_provision=True
            )
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec("rt", "v5e-8", retry=True),
            )
            await drive(api.db, passes=3)
            rows = await _job_rows(api.db, "rt")
            assert all(r["status"] == "submitted" for r in rows)

            # Capacity appears -> run completes.
            backends_service._compute_cache[(project["id"], "mock")] = MockTpuCompute()
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "rt"})
            assert run["status"] == "done"

    async def test_gang_retry_on_job_failure(self, monkeypatch):
        monkeypatch.setattr("dstack_tpu.server.settings.RETRY_BACKOFF_BASE", 0.0)
        async with api_server() as api:
            await setup_mock_backend(api)
            # First attempt fails on worker 1; whole gang resubmitted.
            orig_for_jpd = FakeRunnerClient.for_jpd
            injected = []

            def failing_for_jpd(jpd, jrd):
                fake = orig_for_jpd(jpd, jrd)
                if jpd.worker_num == 1 and not injected and fake.submitted is None:
                    injected.append(True)
                    fake.script = [
                        {
                            "job_states": [{"state": "failed", "exit_status": 1}],
                            "logs": [],
                            "offset": 1,
                        }
                    ]
                return fake

            tasks.get_runner_client = failing_for_jpd
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec("gr", "v5p-16", retry={"on_events": ["error"], "duration": "1h"}),
            )
            await drive(api.db, passes=20)
            rows = await _job_rows(api.db, "gr")
            # 2 jobs x 2 submissions
            assert max(r["submission_num"] for r in rows) == 1
            run = await api.post("/api/project/main/runs/get", {"run_name": "gr"})
            assert run["status"] == "done"

    async def test_failure_without_retry_fails_run(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            orig_for_jpd = FakeRunnerClient.for_jpd

            def failing_for_jpd(jpd, jrd):
                fake = orig_for_jpd(jpd, jrd)
                fake.script = [
                    {"job_states": [{"state": "failed", "exit_status": 2}], "logs": [], "offset": 1}
                ]
                return fake

            tasks.get_runner_client = failing_for_jpd
            await api.post("/api/project/main/runs/submit", tpu_task_spec("f1", "v5e-8"))
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "f1"})
            assert run["status"] == "failed"
            sub = run["jobs"][0]["job_submissions"][-1]
            assert sub["termination_reason"] == "container_exited_with_error"
            assert sub["exit_status"] == 2


class TestStopAndInstances:
    async def test_stop_run_releases_instance(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            # Keep the job running forever.
            orig_for_jpd = FakeRunnerClient.for_jpd

            def running_for_jpd(jpd, jrd):
                fake = orig_for_jpd(jpd, jrd)
                fake.script = [{"job_states": [{"state": "running"}], "logs": [], "offset": 1}]
                return fake

            tasks.get_runner_client = running_for_jpd
            await api.post("/api/project/main/runs/submit", tpu_task_spec("s1", "v5e-8"))
            await drive(api.db, passes=4)
            run = await api.post("/api/project/main/runs/get", {"run_name": "s1"})
            assert run["status"] == "running"

            await api.post("/api/project/main/runs/stop", {"runs_names": ["s1"]})
            await drive(api.db, passes=4)
            run = await api.post("/api/project/main/runs/get", {"run_name": "s1"})
            assert run["status"] == "terminated"
            fake = next(iter(FakeRunnerClient.registry.values()))
            assert fake.stopped

            inst = await api.db.fetchone("SELECT * FROM instances")
            assert inst["status"] == "idle"
            assert inst["busy_blocks"] == 0

    async def test_idle_instance_terminated_after_expiry(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("i1", "v5e-8"))
            await drive(api.db)
            # Force expiry: idle_since far in the past.
            await api.db.execute(
                "UPDATE instances SET idle_since = '2020-01-01T00:00:00+00:00'"
            )
            await drive(api.db, passes=3)
            inst = await api.db.fetchone("SELECT * FROM instances")
            assert inst["status"] == "terminated"
            project = await api.db.fetchone("SELECT * FROM projects")
            compute = dict(await backends_service.get_project_computes(api.db, project))["mock"]
            assert len(compute.terminated) == 1
            # Auto-created fleet is cleaned up with its last instance.
            fleets = await api.db.fetchall("SELECT * FROM fleets WHERE deleted = 0")
            assert fleets == []

    async def test_unreachable_runner_fails_job_after_grace(self, monkeypatch):
        async with api_server() as api:
            monkeypatch.setattr(
                "dstack_tpu.server.settings.RUNNER_DISCONNECT_TIMEOUT", 0.0
            )
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("u1", "v5e-8"))
            await drive(api.db, passes=2)
            # Runner goes dark mid-run.
            for fake in FakeRunnerClient.registry.values():
                async def dead_pull(offset=0):
                    raise RuntimeError("connection refused")

                fake.pull = dead_pull
            await drive(api.db, passes=4)
            run = await api.post("/api/project/main/runs/get", {"run_name": "u1"})
            sub = run["jobs"][0]["job_submissions"][-1]
            assert sub["termination_reason"] == "instance_unreachable"


class TestFleets:
    async def test_cloud_fleet_provisions_and_run_reuses_it(self):
        from dstack_tpu.core.models.fleets import FleetSpec
        from dstack_tpu.server.services import fleets as fleets_service

        async with api_server() as api:
            await setup_mock_backend(api)
            project = await api.db.fetchone("SELECT * FROM projects")
            user = await api.db.fetchone("SELECT * FROM users")
            spec = FleetSpec.model_validate(
                {
                    "configuration": {
                        "type": "fleet",
                        "name": "pool",
                        "nodes": 1,
                        "resources": {"tpu": "v5p-16"},
                    }
                }
            )
            await fleets_service.create_fleet(api.db, project, user, spec)
            await drive(api.db, passes=3)
            rows = await api.db.fetchall("SELECT * FROM instances ORDER BY worker_num")
            assert [r["status"] for r in rows] == ["idle", "idle"]  # 2 hosts of one slice
            assert len({r["slice_id"] for r in rows}) == 1

            compute = dict(
                await backends_service.get_project_computes(api.db, project)
            )["mock"]
            assert len(compute.created) == 1

            # A run targeting the fleet reuses the idle slice: no new cloud create.
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec("fr", "v5p-16", fleets=["pool"]),
            )
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "fr"})
            assert run["status"] == "done"
            assert len(compute.created) == 1

            # Fleet delete drains the slice.
            await fleets_service.delete_fleets(api.db, project, ["pool"])
            await drive(api.db, passes=3)
            rows = await api.db.fetchall("SELECT * FROM instances")
            assert all(r["status"] == "terminated" for r in rows)
            assert compute.terminated == compute.created


class TestLogsFromRunner:
    async def test_logs_written_to_storage(self, tmp_path):
        from dstack_tpu.server.services import logs as logs_service

        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                await api.post("/api/project/main/runs/submit", CPU_TASK)
                await drive(api.db)
                job = await api.db.fetchone("SELECT * FROM jobs")
                events = logs_service.get_log_storage().poll_logs(
                    job["project_id"], "cpu-task", job["id"]
                )
                assert [e.message for e in events] == ["hello\n"]
        finally:
            logs_service.set_log_storage(None)


class TestSecretsInjection:
    async def test_only_referenced_secrets_injected(self):
        # ADVICE r1 (medium): a job must receive only the secrets its configuration
        # references via ${{ secrets.X }} — never the whole project store.
        async with api_server() as api:
            await api.post(
                "/api/project/main/secrets/set", {"name": "USED", "value": "s3cret"}
            )
            await api.post(
                "/api/project/main/secrets/set", {"name": "UNUSED", "value": "hidden"}
            )
            await api.post(
                "/api/project/main/runs/submit",
                {
                    "run_spec": {
                        "run_name": "sec-task",
                        "configuration": {
                            "type": "task",
                            "commands": ["echo $TOKEN"],
                            "env": {"TOKEN": "${{ secrets.USED }}", "PLAIN": "x"},
                        },
                    }
                },
            )
            await drive(api.db)
            fakes = list(FakeRunnerClient.registry.values())
            assert len(fakes) == 1
            env = fakes[0].submitted.env
            assert env["TOKEN"] == "s3cret"
            assert env["PLAIN"] == "x"
            values = " ".join(map(str, env.values())) + " ".join(
                map(str, (fakes[0].secrets or {}).values())
            )
            assert "hidden" not in values


class TestTransactionalPlacement:
    """Crash-injection: every placement's multi-statement bookkeeping commits atomically
    (parity: reference wraps each scheduler pass in one session transaction,
    process_submitted_jobs.py:193-241)."""

    async def test_crash_between_create_slice_and_assign_leaves_no_orphans(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("crash1"))

            def _boom(conn, job_row, instance_id, jpd_dict):
                raise RuntimeError("injected crash before assignment")

            with pytest.MonkeyPatch.context() as mp:
                mp.setattr(tasks, "_assign_job_tx", _boom)
                with pytest.raises(RuntimeError):
                    await tasks.process_submitted_jobs(api.db)

            # The whole transaction rolled back: no instance rows, no fleet rows, and
            # the gang is still queued (a billed-but-untracked cloud slice is the
            # backend's leak-sweep's problem; scheduler state must stay consistent).
            instances = await api.db.fetchall("SELECT * FROM instances")
            assert instances == []
            jobs = await _job_rows(api.db, "crash1")
            assert all(j["status"] == "submitted" for j in jobs)
            assert all(j["instance_id"] is None for j in jobs)

            # Recovery: with the crash removed the next pass places the gang normally.
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "crash1"})
            assert run["status"] == "done"

    async def test_crash_during_pool_assignment_keeps_slice_idle(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            # First run provisions a slice and finishes -> slice parked idle.
            await api.post("/api/project/main/runs/submit", tpu_task_spec("pool1"))
            await drive(api.db)
            idle = await api.db.fetchall(
                "SELECT * FROM instances WHERE status = 'idle' AND deleted = 0"
            )
            assert len(idle) == 2

            await api.post("/api/project/main/runs/submit", tpu_task_spec("pool2"))

            real_mark = tasks.instances_service.mark_slice_busy_tx

            def _mark_then_boom(conn, ids):
                real_mark(conn, ids)
                raise RuntimeError("injected crash after mark-busy")

            with pytest.MonkeyPatch.context() as mp:
                mp.setattr(
                    tasks.instances_service, "mark_slice_busy_tx", _mark_then_boom
                )
                with pytest.raises(RuntimeError):
                    await tasks.process_submitted_jobs(api.db)

            # mark-busy rolled back with the rest: the slice is still idle, jobs queued.
            idle = await api.db.fetchall(
                "SELECT * FROM instances WHERE status = 'idle' AND deleted = 0"
            )
            assert len(idle) == 2
            jobs = await _job_rows(api.db, "pool2")
            assert all(j["status"] == "submitted" for j in jobs)

            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "pool2"})
            assert run["status"] == "done"


class TestConcurrentPasses:
    """The PR-1 concurrency contract: fan-out passes + keyed run locks + the
    conditional slice claim must never double-place, and the offer cache must
    drop on backend reconfig."""

    async def test_overlapping_passes_place_run_exactly_once(self):
        import asyncio

        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("c1", "v5e-8"))
            # Two whole scheduler passes race on the same submitted run: the
            # run-keyed lock serializes them and the second pass's fresh
            # re-fetch sees the gang already placed.
            await asyncio.gather(
                tasks.process_submitted_jobs(api.db),
                tasks.process_submitted_jobs(api.db),
            )
            jobs = await _job_rows(api.db, "c1")
            assert [j["status"] for j in jobs] == ["provisioning"]
            instances = await api.db.fetchall("SELECT * FROM instances")
            assert len(instances) == 1  # exactly one slice provisioned, not two
            assert jobs[0]["instance_id"] == instances[0]["id"]

            project = await api.db.fetchone("SELECT * FROM projects")
            compute = dict(
                await backends_service.get_project_computes(api.db, project)
            )["mock"]
            assert len(compute.created) == 1

            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "c1"})
            assert run["status"] == "done"

    async def test_concurrent_runs_cannot_share_one_idle_slice(self):
        """Two different runs (different locks!) race for the same pool slice:
        mark_slice_busy_tx's idle guard lets exactly one claim it; the loser
        provisions fresh instead of double-assigning."""
        import asyncio

        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("p0", "v5e-8"))
            await drive(api.db)
            idle = await api.db.fetchall(
                "SELECT * FROM instances WHERE status = 'idle' AND deleted = 0"
            )
            assert len(idle) == 1

            await api.post("/api/project/main/runs/submit", tpu_task_spec("pa", "v5e-8"))
            await api.post("/api/project/main/runs/submit", tpu_task_spec("pb", "v5e-8"))
            run_a = await api.db.fetchone("SELECT id FROM runs WHERE run_name = 'pa'")
            run_b = await api.db.fetchone("SELECT id FROM runs WHERE run_name = 'pb'")
            job_a = (await _job_rows(api.db, "pa"))[0]
            job_b = (await _job_rows(api.db, "pb"))[0]
            # Race the two placements directly (one pass would serialize them
            # only through the semaphore, which doesn't force interleaving).
            await asyncio.gather(
                tasks._place_replica(api.db, run_a["id"], 0, 0),
                tasks._place_replica(api.db, run_b["id"], 0, 0),
            )
            jobs = {r["run_name"]: r for r in await _job_rows(api.db)}
            a_inst = jobs["pa"]["instance_id"]
            b_inst = jobs["pb"]["instance_id"]
            placed = [i for i in (a_inst, b_inst) if i is not None]
            assert len(set(placed)) == len(placed), "two runs share one slice"
            # The pool slice went to at most one of them; nobody was double-booked.
            busy = await api.db.fetchall(
                "SELECT id, busy_blocks FROM instances WHERE busy_blocks = 1"
            )
            assert len(busy) == len(placed)

    async def test_offer_cache_hit_and_invalidation_on_reconfig(self, monkeypatch):
        from dstack_tpu.backends.mock import MockTpuCompute
        from dstack_tpu.core.models.runs import Requirements
        from dstack_tpu.server.services import offers as offers_service

        calls = {"n": 0}
        orig = MockTpuCompute.get_offers

        async def counting(self, *a, **kw):
            calls["n"] += 1
            return await orig(self, *a, **kw)

        monkeypatch.setattr(MockTpuCompute, "get_offers", counting)
        async with api_server() as api:
            await setup_mock_backend(api)
            project = await api.db.fetchone("SELECT * FROM projects")
            req = Requirements.model_validate({"resources": {"tpu": "v5e-8"}})

            first = await offers_service.get_offers_by_requirements(api.db, project, req)
            assert first and calls["n"] == 1
            again = await offers_service.get_offers_by_requirements(api.db, project, req)
            assert [o.instance.name for o in again] == [o.instance.name for o in first]
            assert calls["n"] == 1  # served from the TTL cache

            # Reconfiguring the project's backends must invalidate immediately.
            await setup_mock_backend(api)
            await offers_service.get_offers_by_requirements(api.db, project, req)
            assert calls["n"] == 2

            # reset_compute_cache (config reload path) also drops the cache.
            await offers_service.get_offers_by_requirements(api.db, project, req)
            assert calls["n"] == 2
            backends_service.reset_compute_cache()
            await offers_service.get_offers_by_requirements(api.db, project, req)
            assert calls["n"] == 3


class TestRegistryAuthSecrets:
    async def test_registry_auth_secret_interpolation(self, monkeypatch):
        """${{ secrets.X }} in registry_auth resolves at submit time (the most
        common secret consumer; reference interpolates it the same way)."""
        from dstack_tpu.server.background import tasks as _tasks

        monkeypatch.setattr(_tasks, "get_runner_client", FakeRunnerClient.for_jpd)
        FakeRunnerClient.reset()
        backends_service.reset_compute_cache()
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/secrets/set",
                {"name": "REG_TOKEN", "value": "sekrit-pull-token"},
            )
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec(
                    "regauth", "v5e-8",
                    image="private.io/img:1",
                    registry_auth={"username": "bot", "password": "${{ secrets.REG_TOKEN }}"},
                ),
            )
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "regauth"})
            assert run["status"] == "done"
            [fake] = FakeRunnerClient.registry.values()
            assert fake.submitted.registry_auth.password == "sekrit-pull-token"
            # The stored job spec keeps the placeholder, not the secret.
            row = await api.db.fetchone("SELECT job_spec FROM jobs LIMIT 1")
            assert "sekrit" not in row["job_spec"]


class TestSchedulerNudge:
    """The submit->assign fast path: submit_run sets the process_submitted_jobs
    wake event, so the loop starts its next pass immediately instead of
    sleeping out the rest of its interval (bench_scheduler measures the win:
    ~6ms vs ~interval/2 p50)."""

    async def test_wake_cuts_the_sleep_short(self):
        import asyncio

        from dstack_tpu.server import background

        calls = []

        async def tick():
            calls.append(1)

        sched = background.BackgroundScheduler()
        # 30s interval: without the nudge the second pass would be far
        # outside this test's lifetime.
        sched.add_periodic(tick, interval=30.0, name="nudge-probe")
        try:
            for _ in range(100):
                if calls:
                    break
                await asyncio.sleep(0.01)
            assert len(calls) == 1
            background.wake("nudge-probe")
            for _ in range(100):
                if len(calls) >= 2:
                    break
                await asyncio.sleep(0.01)
            assert len(calls) == 2, "wake() did not cut the sleep short"
        finally:
            await sched.stop()
        # stop() deregisters the event; a late wake is a clean no-op.
        assert "nudge-probe" not in background._WAKE_EVENTS
        background.wake("nudge-probe")

    async def test_wake_during_pass_is_not_lost(self):
        """A nudge landing WHILE the pass runs (a submit racing the DB query)
        must trigger one more pass, not vanish — the event is cleared before
        fn(), so a mid-pass set survives into the wait."""
        import asyncio

        from dstack_tpu.server import background

        calls = []
        in_first_pass = asyncio.Event()
        release = asyncio.Event()

        async def tick():
            calls.append(1)
            if len(calls) == 1:
                in_first_pass.set()
                await release.wait()

        sched = background.BackgroundScheduler()
        sched.add_periodic(tick, interval=30.0, name="nudge-race")
        try:
            await asyncio.wait_for(in_first_pass.wait(), timeout=5)
            background.wake("nudge-race")  # lands mid-pass
            release.set()
            for _ in range(100):
                if len(calls) >= 2:
                    break
                await asyncio.sleep(0.01)
            assert len(calls) == 2, "mid-pass wake was lost"
        finally:
            await sched.stop()

    async def test_submit_run_nudges_process_submitted_jobs(self, monkeypatch):
        from dstack_tpu.server import background

        woken = []
        monkeypatch.setattr(background, "wake", woken.append)
        async with api_server() as api:
            await api.post("/api/project/main/runs/submit", CPU_TASK)
        assert "process_submitted_jobs" in woken
