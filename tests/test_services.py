"""Service data plane: in-server proxy, replica scaling, RPS autoscaler.

Parity: reference server/services/proxy/ (routing over instance tunnels,
service_connection.py:158), runs.py:995 scale_run_replicas, autoscalers.py:60-110
RPSAutoscaler. E2E: a real service process (spawned by the real C++ agent through
the local backend) serves HTTP through the proxy; synthetic RPS scales 1→2→1.
"""

import asyncio

import pytest

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.server.services import proxy as proxy_service
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.common import api_server

pytestmark = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)

# A minimal HTTP app binding the port the control plane assigns (the contract:
# services listen on DSTACK_SERVICE_PORT, which equals the configured port on
# dedicated hosts and an ephemeral port on the shared-host local backend).
_APP = (
    "python3 -c \"\n"
    "import http.server, os\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        body = ('pong:' + self.path).encode()\n"
    "        self.send_response(200)\n"
    "        self.send_header('Content-Length', str(len(body)))\n"
    "        self.end_headers()\n"
    "        self.wfile.write(body)\n"
    "    def log_message(self, *a):\n"
    "        pass\n"
    "    do_POST = do_GET\n"
    "http.server.HTTPServer(('127.0.0.1', int(os.environ['DSTACK_SERVICE_PORT'])), H).serve_forever()\n"
    "\""
)


async def _drive(api, passes=1):
    for _ in range(passes):
        await tasks.process_submitted_jobs(api.db)
        await tasks.process_running_jobs(api.db)
        await tasks.process_terminating_jobs(api.db)
        await tasks.process_runs(api.db)
        await tasks.process_instances(api.db)


async def _drive_until_replicas(api, run_name, want_running, timeout=40.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        await _drive(api)
        rows = await api.db.fetchall(
            "SELECT * FROM jobs WHERE run_name = ? AND status = 'running'", (run_name,)
        )
        if len(rows) == want_running:
            return rows
        await asyncio.sleep(0.15)
    raise AssertionError(f"never reached {want_running} running replicas")


async def _stop_run(api, run_name):
    await api.post(
        f"/api/project/main/runs/stop", {"runs_names": [run_name], "abort": True}
    )
    for _ in range(60):
        await _drive(api)
        run = await api.post("/api/project/main/runs/get", {"run_name": run_name})
        if run["status"] in ("terminated", "failed", "done"):
            return
        await asyncio.sleep(0.1)


class TestServiceProxy:
    async def test_proxy_routes_to_replica(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        proxy_service.stats.reset()
        try:
            async with api_server() as api:
                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "svc",
                            "configuration": {
                                "type": "service",
                                "commands": [_APP],
                                "port": 8000,
                            },
                        }
                    },
                )
                await _drive_until_replicas(api, "svc", 1)
                # The service socket takes a moment after the job turns running.
                body = None
                for _ in range(50):
                    resp = await api.client.get(
                        "/proxy/services/main/svc/hello/world?q=1",
                        headers={"Authorization": f"Bearer {api.token}"},
                    )
                    if resp.status == 200:
                        body = await resp.text()
                        break
                    await asyncio.sleep(0.2)
                assert body == "pong:/hello/world?q=1"

                # service_spec recorded the proxy URL.
                run = await api.post("/api/project/main/runs/get", {"run_name": "svc"})
                assert run["service"]["url"] == "/proxy/services/main/svc/"

                # auth: default-on -> no token is a 401.
                resp = await api.client.get("/proxy/services/main/svc/hello")
                assert resp.status == 401

                await _stop_run(api, "svc")
        finally:
            logs_service.set_log_storage(None)

    async def test_proxy_404_for_missing_run(self):
        async with api_server() as api:
            resp = await api.client.get(
                "/proxy/services/main/ghost/x",
                headers={"Authorization": f"Bearer {api.token}"},
            )
            assert resp.status == 404


class TestAutoscaler:
    async def test_rps_scales_up_then_down(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        proxy_service.stats.reset()
        try:
            async with api_server() as api:
                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "asvc",
                            "configuration": {
                                "type": "service",
                                "commands": [_APP],
                                "port": 8000,
                                "replicas": "1..3",
                                "scaling": {
                                    "metric": "rps",
                                    "target": 1,
                                    "scale_up_delay": 0,
                                    "scale_down_delay": 0,
                                },
                            },
                        }
                    },
                )
                await _drive_until_replicas(api, "asvc", 1)
                run_row = await api.db.fetchone(
                    "SELECT * FROM runs WHERE run_name = 'asvc'"
                )

                # Synthetic demand: ~2 rps over the last minute -> target 2.
                for _ in range(120):
                    proxy_service.stats.record(run_row["id"])
                await tasks.process_services(api.db)
                rows = await _drive_until_replicas(api, "asvc", 2)
                assert {r["replica_num"] for r in rows} == {0, 1}
                run = await api.post("/api/project/main/runs/get", {"run_name": "asvc"})
                assert run["status"] == "running"

                # Proxy balances across both replicas (different assigned ports);
                # retry while the fresh replica's socket binds.
                ok = 0
                for _ in range(100):
                    resp = await api.client.get(
                        "/proxy/services/main/asvc/ping",
                        headers={"Authorization": f"Bearer {api.token}"},
                    )
                    if resp.status == 200:
                        ok += 1
                        if ok >= 4:
                            break
                    else:
                        await asyncio.sleep(0.2)
                assert ok >= 4
                replicas = await proxy_service.list_service_replicas(
                    api.db, run_row["project_id"], "asvc"
                )
                seen_ports = {port for *_, port in replicas}
                assert len(seen_ports) == 2  # distinct ephemeral ports on one host

                # Demand evaporates -> scale back down to min (1).
                proxy_service.stats.reset()
                await tasks.process_services(api.db)
                rows = await _drive_until_replicas(api, "asvc", 1)
                run = await api.post("/api/project/main/runs/get", {"run_name": "asvc"})
                assert run["status"] == "running"  # scaled-down replica is not a failure
                scaled = await api.db.fetchall(
                    "SELECT * FROM jobs WHERE run_name = 'asvc'"
                    " AND termination_reason = 'scaled_down'"
                )
                assert len(scaled) == 1

                await _stop_run(api, "asvc")
        finally:
            logs_service.set_log_storage(None)


class TestStatsPersistence:
    async def test_rps_window_survives_server_restart(self, tmp_path):
        """The autoscaler's request window is checkpointed to the DB and
        re-primed at startup: after a restart, a busy service still reads a
        warm RPS instead of scaling on zero knowledge."""
        db_file = str(tmp_path / "server.db")
        proxy_service.stats.reset()
        try:
            async with api_server(db_path=db_file) as api:
                for _ in range(120):
                    proxy_service.stats.record("run-abc")
                assert proxy_service.stats.rps("run-abc") == pytest.approx(2.0)
                # process_services checkpoints the window every pass.
                await tasks.process_services(api.db)
                rows = await api.db.fetchall("SELECT * FROM service_stats")
                assert sum(r["count"] for r in rows) == 120

            # "Restart": fresh process state, same DB file.
            proxy_service.stats.reset()
            assert proxy_service.stats.rps("run-abc") == 0.0
            async with api_server(db_path=db_file) as api:
                warm = proxy_service.stats.rps("run-abc")
                assert warm == pytest.approx(2.0, rel=0.2)
        finally:
            proxy_service.stats.reset()

    def test_flush_prime_roundtrip_drops_expired_buckets(self):
        import time as time_mod

        s = proxy_service.ServiceStats()
        now = time_mod.monotonic()
        s.record("r1", now - 300.0)
        s.record("r1", now - 1.0)
        s.record("r1", now - 1.0)
        rows = s.flush_rows()
        assert sum(c for _, _, c in rows) == 3
        # An expired bucket (older than the window) never comes back.
        old_bucket = int(time_mod.time() - proxy_service.STATS_WINDOW - 60)
        rows.append(("r1", old_bucket, 50))
        s2 = proxy_service.ServiceStats()
        s2.prime(rows)
        assert s2.rps("r1", window=60.0) == pytest.approx(2 / 60.0)
        assert s2.rps("r1", window=proxy_service.STATS_WINDOW) == pytest.approx(
            3 / proxy_service.STATS_WINDOW
        )


class TestReadinessProbes:
    async def test_unready_replica_excluded_until_socket_answers(self, tmp_path):
        """A replica whose app socket is not yet up fails the probe and is dropped
        from routing; once the socket answers, a later probe readmits it."""
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        proxy_service.stats.reset()
        try:
            async with api_server() as api:
                # The app sleeps before binding, so the first probe must fail.
                slow_app = _APP.replace(
                    "import http.server, os\n", "import http.server, os, time\ntime.sleep(2)\n"
                )
                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "probe-svc",
                            "configuration": {
                                "type": "service",
                                "commands": [slow_app],
                                "port": 8000,
                            },
                        }
                    },
                )
                await _drive_until_replicas(api, "probe-svc", 1)
                await tasks.process_services(api.db)  # first probe: socket not up
                replicas = await proxy_service.list_service_replicas(
                    api.db, (await api.db.fetchone("SELECT * FROM projects"))["id"],
                    "probe-svc", ready_only=True,
                )
                assert replicas == []
                resp = await api.client.get(
                    "/proxy/services/main/probe-svc/ping",
                    headers={"Authorization": f"Bearer {api.token}"},
                )
                assert resp.status == 503
                assert "starting" in await resp.text()

                # Socket comes up; a later probe readmits the replica.
                ok = False
                for _ in range(40):
                    await asyncio.sleep(0.3)
                    await tasks.process_services(api.db)
                    resp = await api.client.get(
                        "/proxy/services/main/probe-svc/ping",
                        headers={"Authorization": f"Bearer {api.token}"},
                    )
                    if resp.status == 200:
                        ok = True
                        break
                assert ok
                await _stop_run(api, "probe-svc")
        finally:
            logs_service.set_log_storage(None)
