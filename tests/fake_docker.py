"""A fake Docker Engine API daemon on a unix socket.

Speaks enough of the engine REST API for the runner's container path (ping, image
inspect + pull with X-Registry-Auth capture, container create / start / logs / wait /
kill / delete / list / stats). Containers actually execute their Entrypoint+Cmd via
subprocess, so the log stream and exit codes flowing back through the C++ agent are
real — the same fidelity bar as fake_ssh.py (which really forwards TCP).
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import signal
import uuid
from typing import Dict, List, Optional

from aiohttp import web


class FakeContainer:
    def __init__(self, cid: str, name: str, config: dict) -> None:
        self.id = cid
        self.name = name
        self.config = config
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.exit_code: Optional[int] = None
        self.log_buf = bytearray()
        self.exited = asyncio.Event()

    @property
    def labels(self) -> dict:
        return self.config.get("Labels") or {}

    @property
    def running(self) -> bool:
        return self.proc is not None and self.exit_code is None


class FakeDockerDaemon:
    def __init__(self, socket_path: str, images: Optional[List[str]] = None) -> None:
        self.socket_path = socket_path
        self.images = set(images or [])
        # image -> argv used when a container config carries no Entrypoint/Cmd
        # (the engine falls back to the image's baked-in ENTRYPOINT/CMD).
        self.image_defaults: Dict[str, List[str]] = {}
        self.pulls: List[dict] = []  # {"image", "tag", "auth": decoded-or-None}
        self.pull_error: Optional[str] = None  # set to make pulls fail
        self.creates: List[dict] = []  # every container config passed to create
        self.containers: Dict[str, FakeContainer] = {}
        self._runner: Optional[web.AppRunner] = None
        self._tasks: List[asyncio.Task] = []

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/_ping", self._ping)
        app.router.add_get("/images/{name}/json", self._image_inspect)
        app.router.add_post("/images/create", self._image_create)
        app.router.add_post("/containers/create", self._create)
        app.router.add_post("/containers/{id}/start", self._start)
        app.router.add_get("/containers/{id}/logs", self._logs)
        app.router.add_post("/containers/{id}/wait", self._wait)
        app.router.add_post("/containers/{id}/kill", self._kill)
        app.router.add_delete("/containers/{id}", self._delete)
        app.router.add_get("/containers/json", self._list)
        app.router.add_get("/containers/{id}/json", self._inspect)
        app.router.add_get("/containers/{id}/stats", self._stats)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.UnixSite(self._runner, self.socket_path)
        await site.start()

    async def stop(self) -> None:
        for c in self.containers.values():
            if c.running and c.proc is not None:
                try:
                    c.proc.kill()
                except ProcessLookupError:
                    pass
        for t in self._tasks:
            t.cancel()
        if self._runner is not None:
            await self._runner.cleanup()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    def find(self, ref: str) -> Optional[FakeContainer]:
        """Resolve an id or a name, like the engine does."""
        c = self.containers.get(ref)
        if c is not None:
            return c
        for c in self.containers.values():
            if c.name == ref:
                return c
        return None

    # -- handlers -----------------------------------------------------------

    async def _ping(self, request: web.Request) -> web.Response:
        return web.Response(text="OK")

    async def _image_inspect(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        if name in self.images or f"{name}:latest" in self.images:
            return web.json_response({"Id": "sha256:" + name})
        return web.json_response({"message": f"no such image: {name}"}, status=404)

    async def _image_create(self, request: web.Request) -> web.StreamResponse:
        image = request.query.get("fromImage", "")
        tag = request.query.get("tag", "latest")
        auth = None
        hdr = request.headers.get("X-Registry-Auth")
        if hdr:
            auth = json.loads(base64.b64decode(hdr + "=" * (-len(hdr) % 4)))
        self.pulls.append({"image": image, "tag": tag, "auth": auth})
        resp = web.StreamResponse()
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        await resp.write(json.dumps({"status": f"Pulling from {image}"}).encode() + b"\n")
        if self.pull_error:
            await resp.write(json.dumps({"error": self.pull_error}).encode() + b"\n")
        else:
            await resp.write(
                json.dumps({"status": "Downloading", "progressDetail": {"current": 10, "total": 10}}).encode()
                + b"\n"
            )
            await resp.write(
                json.dumps({"status": f"Status: Downloaded newer image for {image}:{tag}"}).encode() + b"\n"
            )
            self.images.add(f"{image}:{tag}")
            self.images.add(image)
        await resp.write_eof()
        return resp

    async def _create(self, request: web.Request) -> web.Response:
        name = request.query.get("name") or ("c-" + uuid.uuid4().hex[:8])
        if any(c.name == name for c in self.containers.values()):
            return web.json_response(
                {"message": f"Conflict. The container name {name} is already in use"}, status=409
            )
        config = await request.json()
        self.creates.append(config)
        image = config.get("Image", "")
        if image not in self.images and f"{image}:latest" not in self.images:
            return web.json_response({"message": f"No such image: {image}"}, status=404)
        cid = uuid.uuid4().hex
        self.containers[cid] = FakeContainer(cid, name, config)
        return web.json_response({"Id": cid}, status=201)

    async def _start(self, request: web.Request) -> web.Response:
        c = self.find(request.match_info["id"])
        if c is None:
            return web.json_response({"message": "no such container"}, status=404)
        if c.proc is not None:
            return web.Response(status=304)
        argv = list(c.config.get("Entrypoint") or []) + list(c.config.get("Cmd") or [])
        if not argv:
            argv = list(self.image_defaults.get(c.config.get("Image", ""), ["/bin/true"]))
        env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin")}
        for kv in c.config.get("Env") or []:
            k, _, v = kv.partition("=")
            env[k] = v
        # Map the /workflow bind back to its host source so relative file access works.
        cwd = None
        host_config = c.config.get("HostConfig") or {}
        for bind in host_config.get("Binds") or []:
            src, _, dst = bind.partition(":")
            workdir = c.config.get("WorkingDir") or ""
            if dst and workdir.startswith(dst) and os.path.isdir(src):
                cwd = src + workdir[len(dst):]
                break
        # Simulate other binds (volume mounts) for the host-process "container":
        # symlink the target to the source, but only under /tmp — the fake must
        # never touch real system paths.
        for bind in host_config.get("Binds") or []:
            src, _, dst = bind.partition(":")
            if dst.startswith("/tmp/") and os.path.exists(src) and not os.path.exists(dst):
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                os.symlink(src, dst)
        c.proc = await asyncio.create_subprocess_exec(
            *argv,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            env=env,
            cwd=cwd,
            start_new_session=True,
        )
        self._tasks.append(asyncio.ensure_future(self._pump(c)))
        return web.Response(status=204)

    async def _pump(self, c: FakeContainer) -> None:
        assert c.proc is not None and c.proc.stdout is not None
        while True:
            chunk = await c.proc.stdout.read(4096)
            if not chunk:
                break
            c.log_buf.extend(chunk)
        c.exit_code = await c.proc.wait()
        c.exited.set()

    async def _logs(self, request: web.Request) -> web.StreamResponse:
        c = self.find(request.match_info["id"])
        if c is None:
            return web.json_response({"message": "no such container"}, status=404)
        follow = request.query.get("follow") in ("1", "true")
        resp = web.StreamResponse()
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        sent = 0
        while True:
            if len(c.log_buf) > sent:
                await resp.write(bytes(c.log_buf[sent:]))
                sent = len(c.log_buf)
            if not follow or c.exited.is_set():
                if len(c.log_buf) > sent:
                    continue
                break
            await asyncio.sleep(0.02)
        await resp.write_eof()
        return resp

    async def _wait(self, request: web.Request) -> web.Response:
        c = self.find(request.match_info["id"])
        if c is None:
            return web.json_response({"message": "no such container"}, status=404)
        if c.proc is None:
            # Created but never started: the engine would block; report error.
            return web.json_response({"message": "container not started"}, status=409)
        await c.exited.wait()
        return web.json_response({"StatusCode": c.exit_code})

    async def _kill(self, request: web.Request) -> web.Response:
        c = self.find(request.match_info["id"])
        if c is None:
            return web.json_response({"message": "no such container"}, status=404)
        if not c.running:
            return web.json_response({"message": "container is not running"}, status=409)
        sig = request.query.get("signal", "SIGKILL")
        signum = getattr(signal, sig, signal.SIGKILL) if isinstance(sig, str) else int(sig)
        assert c.proc is not None
        try:
            os.killpg(c.proc.pid, signum)
        except ProcessLookupError:
            pass
        return web.Response(status=204)

    async def _delete(self, request: web.Request) -> web.Response:
        c = self.find(request.match_info["id"])
        if c is None:
            return web.json_response({"message": "no such container"}, status=404)
        if c.running and c.proc is not None:
            try:
                os.killpg(c.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        self.containers.pop(c.id, None)
        return web.Response(status=204)

    async def _list(self, request: web.Request) -> web.Response:
        label_filters: List[str] = []
        raw = request.query.get("filters")
        if raw:
            label_filters = json.loads(raw).get("label") or []
        out = []
        for c in self.containers.values():
            ok = True
            for f in label_filters:
                k, _, v = f.partition("=")
                if c.labels.get(k) != v:
                    ok = False
                    break
            if ok:
                out.append(
                    {
                        "Id": c.id,
                        "Names": ["/" + c.name],
                        "Labels": c.labels,
                        "State": "running" if c.running else "exited",
                    }
                )
        return web.json_response(out)

    async def _inspect(self, request: web.Request) -> web.Response:
        c = self.find(request.match_info["id"])
        if c is None:
            return web.json_response({"message": "no such container"}, status=404)
        return web.json_response(
            {
                "Id": c.id,
                "Name": "/" + c.name,
                "Config": c.config,
                "State": {
                    "Running": c.running,
                    "ExitCode": c.exit_code if c.exit_code is not None else 0,
                },
            }
        )

    async def _stats(self, request: web.Request) -> web.Response:
        c = self.find(request.match_info["id"])
        if c is None:
            return web.json_response({"message": "no such container"}, status=404)
        return web.json_response(
            {
                "cpu_stats": {"cpu_usage": {"total_usage": 123_000_000}},
                "memory_stats": {"usage": 42 * 1024 * 1024},
            }
        )
