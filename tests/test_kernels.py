"""In-repo Pallas kernels + int8 quantization + collective matmul (PR 8).

Everything runs the EXACT kernel code the TPU executes, via Pallas interpret
mode on the virtual 8-device CPU mesh (conftest). The load-bearing claims:

- the flash kernel matches ``blockwise_attention`` to <=1e-4, outputs AND
  gradients, causal and not, GQA included;
- the collective-matmul ppermute ring equals all-gather-then-matmul;
- int8 quantization is bounded-error forward and *exactly* fp backward (STE);
- the serving engine is token-identical with either decode implementation,
  preemption included.
"""

import dataclasses
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Pin to CPU fp32 numerics (the axon TPU plugin ignores JAX_PLATFORMS).
jax.config.update("jax_default_device", jax.devices("cpu")[0])

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import quantize as quant_lib
from dstack_tpu.workloads import serve as serve_lib
from dstack_tpu.workloads import train as train_lib
from dstack_tpu.workloads.attention import (
    attention_core,
    blockwise_attention,
    paged_decode_attention,
)
from dstack_tpu.workloads.config import get_config, validate_config
from dstack_tpu.workloads.kernels import (
    collective_matmul,
    flash_attention,
    paged_decode_attention_pallas,
    pick_flash_block,
)
from dstack_tpu.workloads.kernels.collective import can_overlap
from dstack_tpu.workloads.sharding import (
    batch_sharding,
    make_mesh,
    shard_params,
)

TOL = 1e-4


def qkv(key, t=128, s=None, h=4, kh=2, d=16, b=2):
    s = s or t
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, t, h, d)),
        jax.random.normal(kk, (b, s, kh, d)),
        jax.random.normal(kv, (b, s, kh, d)),
    )


class TestFlashKernel:
    @pytest.mark.parametrize("causal", [True, False])
    def test_fwd_matches_blockwise(self, causal):
        q, k, v = qkv(jax.random.PRNGKey(0))
        out = flash_attention(q, k, v, causal=causal)
        ref = blockwise_attention(q, k, v, causal=causal, block_size=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_blockwise(self, causal):
        """fwd AND bwd equivalence — the custom-VJP backward kernels (dq and
        dk/dv passes, GQA repeat-group gradient sum) against XLA autodiff
        through the blockwise scan."""
        q, k, v = qkv(jax.random.PRNGKey(1), t=64, h=4, kh=2, d=16)

        got = jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(
                flash_attention(q, k, v, causal=causal))),
            argnums=(0, 1, 2),
        )(q, k, v)
        want = jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(blockwise_attention(
                q, k, v, causal=causal, block_size=32))),
            argnums=(0, 1, 2),
        )(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=TOL,
                err_msg=f"d{name} mismatch",
            )

    def test_gqa_multiple_repeat_groups(self):
        # n_rep = 4: the repeat fold and the bwd repeat-group sum.
        q, k, v = qkv(jax.random.PRNGKey(2), t=64, h=8, kh=2, d=8)
        out = flash_attention(q, k, v)
        ref = blockwise_attention(q, k, v, block_size=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_nondivisible_seq_raises(self):
        q, k, v = qkv(jax.random.PRNGKey(3), t=63)
        assert pick_flash_block(63) is None
        with pytest.raises(ValueError, match="block-divisible"):
            flash_attention(q, k, v)

    def test_attention_core_flash_falls_back_on_odd_seq(self):
        # Mid-model (no explicit CLI request) the dispatcher degrades to
        # blockwise instead of crashing on a ragged length.
        q, k, v = qkv(jax.random.PRNGKey(4), t=63)
        out = attention_core(q, k, v, "flash", None)
        ref = blockwise_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_model_forward_flash_matches_blockwise(self):
        cfg_f = get_config("test", max_seq_len=64, attn_impl="flash",
                           dtype="float32")
        cfg_b = get_config("test", max_seq_len=64, dtype="float32")
        params = model_lib.init_params(cfg_b, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 64), 0, cfg_b.vocab_size
        )
        lf = model_lib.forward(params, tokens, cfg_f)
        lb = model_lib.forward(params, tokens, cfg_b)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lb), atol=2e-3)

    def test_flash_sharded_on_mesh_matches(self):
        """Under a (fsdp, tp) mesh the kernel runs per-shard via shard_map —
        same numbers as the meshless kernel."""
        mesh = make_mesh(dp=1, fsdp=2, tp=2, sp=1,
                         devices=jax.devices("cpu")[:4])
        cfg = get_config("test", max_seq_len=64, attn_impl="flash",
                         dtype="float32")
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size
        )
        ref = model_lib.forward(params, tokens, cfg)  # meshless kernel
        with mesh:
            sp = shard_params(params, mesh)
            toks = jax.device_put(tokens, batch_sharding(mesh))
            got = jax.jit(
                lambda p, t: model_lib.forward(p, t, cfg, mesh)
            )(sp, toks)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-3)


class TestQuantize:
    def test_roundtrip_error_bounded_by_half_step(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (32, 64))
        q, s = quant_lib.quantize_int8(x, axis=0)
        deq = quant_lib.dequantize(q, s)
        assert float(jnp.max(jnp.abs(deq - x) / s)) <= 0.5 + 1e-6

    def test_int8_matmul_error_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 256))
        w = jax.random.normal(jax.random.PRNGKey(2), (256, 128))
        got = quant_lib.int8_matmul(x, w)
        ref = x @ w
        rel = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
        # Two independently-rounded int8 operands over K=256: ~1% observed;
        # 5% is the loud-failure line.
        assert rel < 0.05, rel

    def test_zero_channel_safe(self):
        x = jnp.zeros((8, 16))
        q, s = quant_lib.quantize_int8(x, axis=-1)
        assert float(jnp.max(jnp.abs(quant_lib.dequantize(q, s)))) == 0.0

    def test_ste_grads_are_exactly_fp(self):
        """The straight-through VJP must return the fp-matmul gradients (the
        whole point: quantization noise is forward-only)."""
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 8, 16))
        w = jax.random.normal(jax.random.PRNGKey(4), (16, 12))

        def loss_q(x, w):
            return jnp.sum(jnp.sin(quant_lib.int8_matmul_ste(x, w)))

        gx, gw = jax.grad(loss_q, argnums=(0, 1))(x, w)
        y = quant_lib.int8_matmul(x, w)
        g = jnp.cos(y)  # d/dy sum(sin(y))
        want_gx = jnp.einsum("abn,kn->abk", g, w)
        want_gw = jnp.einsum("abk,abn->kn", x, g)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(want_gx),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(want_gw),
                                   atol=1e-5)

    def test_weight_only_matmul_error_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(5), (4, 64))
        w = jax.random.normal(jax.random.PRNGKey(6), (64, 32))
        qw = quant_lib.quantize_weight(w)
        got = quant_lib.weight_only_matmul(x, qw.values, qw.scales)
        rel = float(jnp.linalg.norm(got - x @ w) / jnp.linalg.norm(x @ w))
        # Only the weight is rounded: tighter than the dual-quantized bound.
        assert rel < 0.02, rel

    def test_fake_quant_ste(self):
        w = jax.random.normal(jax.random.PRNGKey(7), (3, 8, 4))
        fq = quant_lib.fake_quant(w, axis=1)
        assert fq.shape == w.shape
        # Values land on the per-channel int8 grid.
        scales = quant_lib.absmax_scales(w, axis=1)
        steps = fq / scales
        np.testing.assert_allclose(
            np.asarray(steps), np.round(np.asarray(steps)), atol=1e-4
        )
        # Gradients pass straight through.
        g = jax.grad(lambda w: jnp.sum(quant_lib.fake_quant(w, 1) ** 2))(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(2 * fq), atol=1e-5)

    def test_check_quant_raises(self):
        with pytest.raises(ValueError, match="unknown quant"):
            quant_lib.check_quant("fp4")

    def test_int8_train_convergence_not_worse(self):
        """The acceptance bar: an int8 STE train run on the tiny config must
        descend like the fp run (same data, same init, same steps)."""
        losses = {}
        for quant in ("none", "int8"):
            cfg = get_config("test", max_seq_len=32, quant=quant,
                             d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
                             d_ff=256, vocab_size=512)
            opt = train_lib.make_optimizer(learning_rate=1e-3)
            state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt)
            step = train_lib.make_train_step(cfg, opt)
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size
            )
            run = []
            for _ in range(8):
                state, m = step(state, tokens, tokens)
                run.append(float(m["loss"]))
            losses[quant] = run
        assert losses["int8"][-1] < losses["int8"][0], losses["int8"]
        # Not worse: within 10% of the fp final loss on this overfit probe.
        assert losses["int8"][-1] <= losses["none"][-1] * 1.10 + 0.05, losses


class TestCollectiveMatmul:
    def _mesh(self):
        return make_mesh(dp=1, fsdp=2, tp=4, sp=1)

    def test_matches_allgather_matmul(self):
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 16, 64))
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
        with mesh:
            got = jax.jit(lambda a, b: collective_matmul(a, b, mesh))(x, w)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(jnp.einsum("btk,kn->btn", x, w)),
            atol=TOL,
        )

    def test_grads_match_allgather_matmul(self):
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 8, 32))
        w = jax.random.normal(jax.random.PRNGKey(3), (32, 16))
        with mesh:
            gx, gw = jax.jit(jax.grad(
                lambda a, b: jnp.sum(jnp.sin(collective_matmul(a, b, mesh))),
                argnums=(0, 1),
            ))(x, w)
        rx, rw = jax.grad(
            lambda a, b: jnp.sum(jnp.sin(jnp.einsum("btk,kn->btn", a, b))),
            argnums=(0, 1),
        )(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=TOL)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=TOL)

    def test_int8_partials(self):
        """quant=int8 composes: each ring chunk runs the quantized dot with
        per-shard scales — bounded error vs the fp product."""
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(4), (8, 8, 64))
        w = jax.random.normal(jax.random.PRNGKey(5), (64, 32))
        with mesh:
            got = jax.jit(lambda a, b: collective_matmul(
                a, b, mesh, matmul=quant_lib.int8_matmul_ste
            ))(x, w)
        ref = jnp.einsum("btk,kn->btn", x, w)
        rel = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
        assert rel < 0.05, rel

    def test_can_overlap_divisibility(self):
        mesh = self._mesh()
        assert can_overlap(mesh, batch=8, seq=16)
        # 2 local rows x 16 seq = 32 rows ... batch=2 -> 1 row/shard x 16 = 16,
        # 16 % 4 == 0 still fine; batch=2, seq=3 -> 3 rows, not divisible by 4.
        assert not can_overlap(mesh, batch=2, seq=3)
        assert not can_overlap(None, batch=8, seq=16)
        tp1 = make_mesh(dp=1, fsdp=8, tp=1, sp=1)
        assert not can_overlap(tp1, batch=8, seq=16)

    def test_model_forward_tp_overlap_matches(self):
        mesh = self._mesh()
        cfg_o = get_config("test", max_seq_len=32, tp_overlap=True,
                           dtype="float32")
        cfg_p = get_config("test", max_seq_len=32, dtype="float32")
        params = model_lib.init_params(cfg_p, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg_p.vocab_size
        )
        with mesh:
            sp = shard_params(params, mesh)
            toks = jax.device_put(tokens, batch_sharding(mesh))
            lo = jax.jit(lambda p, t: model_lib.forward(p, t, cfg_o, mesh))(sp, toks)
            lp = jax.jit(lambda p, t: model_lib.forward(p, t, cfg_p, mesh))(sp, toks)
        np.testing.assert_allclose(np.asarray(lo), np.asarray(lp), atol=1e-3)

    def test_train_step_with_tp_overlap_descends(self):
        mesh = self._mesh()
        cfg = get_config("test", max_seq_len=32, tp_overlap=True,
                         dtype="float32")
        opt = train_lib.make_optimizer()
        with mesh:
            state = train_lib.init_train_state(
                cfg, jax.random.PRNGKey(0), opt, mesh
            )
            step = train_lib.make_train_step(cfg, opt, mesh)
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                   cfg.vocab_size),
                batch_sharding(mesh),
            )
            losses = []
            for _ in range(3):
                state, m = step(state, tokens, tokens)
                losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses


TINY_SERVE = get_config(
    "test", n_layers=2, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
    vocab_size=251, max_seq_len=128, dtype="float32", param_dtype="float32",
    remat=False,
)


@pytest.fixture(scope="module")
def serve_params():
    return model_lib.init_params(TINY_SERVE, jax.random.PRNGKey(0))


def run_engine(engine, limit=3000):
    for _ in range(limit):
        if not engine.has_work():
            return
        engine.step()
    raise AssertionError("engine did not drain")


class TestPagedKernel:
    def test_matches_xla_reference(self):
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        q = jax.random.normal(ks[0], (4, 4, 16))
        kp = jax.random.normal(ks[1], (12, 8, 2, 16))
        vp = jax.random.normal(ks[2], (12, 8, 2, 16))
        pt = jax.random.randint(ks[3], (4, 6), 0, 12)
        lens = jnp.array([0, 5, 17, 48], jnp.int32)
        got = paged_decode_attention_pallas(q, kp, vp, pt, lens)
        ref = paged_decode_attention(q, kp, vp, pt, lens)
        # Active slots identical; the kv_len==0 slot just needs to be finite
        # (engine discards it — XLA emits uniform-weight garbage, the kernel
        # emits zeros).
        np.testing.assert_allclose(
            np.asarray(got[1:]), np.asarray(ref[1:]), atol=TOL
        )
        assert bool(jnp.isfinite(got).all())

    def test_engine_token_identity_pallas_vs_reference(self, serve_params):
        prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [10, 11, 12, 13]]
        engine = serve_lib.ServeEngine(
            TINY_SERVE,
            serve_lib.EngineConfig(page_size=8, num_pages=32, max_batch=4,
                                   max_seq=128, decode_impl="pallas"),
            params=serve_params,
        )
        assert engine.decode_impl == "pallas"
        reqs = [engine.submit(p, max_new_tokens=10) for p in prompts]
        run_engine(engine)
        for p, r in zip(prompts, reqs):
            assert r.tokens == serve_lib.greedy_reference_decode(
                serve_params, TINY_SERVE, p, 10
            )

    def test_engine_token_identity_under_preemption(self, serve_params):
        """The acceptance bar: the Pallas decode path stays token-identical
        through preemption + re-prefill (pool sized to force >=1 preemption).
        """
        engine = serve_lib.ServeEngine(
            TINY_SERVE,
            serve_lib.EngineConfig(page_size=4, num_pages=7, max_batch=3,
                                   max_seq=96, decode_impl="pallas"),
            params=serve_params,
        )
        prompts = [[i + 1, i + 2, i + 3, i + 4, i + 5] for i in (0, 10, 20)]
        reqs = [engine.submit(p, max_new_tokens=20) for p in prompts]
        run_engine(engine)
        assert max(r.preemptions for r in reqs) >= 1, (
            "pool was sized to force preemption"
        )
        for p, r in zip(prompts, reqs):
            assert r.tokens == serve_lib.greedy_reference_decode(
                serve_params, TINY_SERVE, p, 20
            )


class TestServeQuant:
    def test_quantized_param_layout(self, serve_params):
        qp = serve_lib.quantize_serve_params(serve_params)
        for k in serve_lib._WEIGHT_KEYS:
            assert qp[k + "_q"].dtype == jnp.int8
            assert qp[k + "_q"].shape == serve_params[k].shape
            assert qp[k + "_s"].dtype == jnp.float32
            # stacked [L, K, N] -> per-channel scales [L, 1, N]
            assert qp[k + "_s"].shape[-2] == 1
            assert k not in qp  # fp copy not duplicated into the jit args
        assert qp["lm_head_q"].dtype == jnp.int8
        assert qp["embed"].dtype == serve_params["embed"].dtype

    def test_int8_engine_decodes_finitely_and_deterministically(
        self, serve_params
    ):
        def run():
            engine = serve_lib.ServeEngine(
                TINY_SERVE,
                serve_lib.EngineConfig(page_size=8, num_pages=32, max_batch=2,
                                       max_seq=128, quant="int8"),
                params=serve_params,
            )
            req = engine.submit([3, 5, 7, 11], max_new_tokens=8)
            run_engine(engine)
            return req.tokens

        a, b = run(), run()
        assert a == b and len(a) == 8
        assert all(0 <= t < TINY_SERVE.vocab_size for t in a)

    def test_bad_engine_config_raises(self, serve_params):
        with pytest.raises(ValueError, match="decode_impl"):
            serve_lib.ServeEngine(
                TINY_SERVE, serve_lib.EngineConfig(decode_impl="mosaic"),
                params=serve_params,
            )
        with pytest.raises(ValueError, match="quant"):
            serve_lib.ServeEngine(
                TINY_SERVE, serve_lib.EngineConfig(quant="fp8"),
                params=serve_params,
            )


class TestValidation:
    def test_flash_plus_sp_raises(self):
        mesh = make_mesh(dp=1, fsdp=1, tp=1, sp=8)
        cfg = get_config("test", attn_impl="flash")
        with pytest.raises(ValueError, match="sequence"):
            validate_config(cfg, mesh, batch=8, seq=128)

    def test_flash_nondivisible_seq_raises(self):
        cfg = get_config("test", attn_impl="flash")
        with pytest.raises(ValueError, match="block-divisible"):
            validate_config(cfg, None, batch=8, seq=127)

    def test_flash_tp_must_divide_kv_heads(self):
        mesh = make_mesh(dp=1, fsdp=1, tp=8, sp=1)
        cfg = get_config("test", attn_impl="flash")  # n_kv_heads=4
        with pytest.raises(ValueError, match="n_kv_heads"):
            validate_config(cfg, mesh, batch=8, seq=128)

    def test_flash_tpu_under_mesh_raises(self):
        # The public kernel has no SPMD rule: under any mesh (train always
        # builds one) it would silently degrade to blockwise — reject loudly.
        mesh = make_mesh(dp=1, tp=1, sp=1)  # fsdp absorbs all devices
        cfg = get_config("test", attn_impl="flash_tpu")
        with pytest.raises(ValueError, match="meshless"):
            validate_config(cfg, mesh, batch=8, seq=128)
        validate_config(get_config("test", attn_impl="flash_tpu"), None,
                        batch=8, seq=128)

    def test_flash_tpu_seq_uses_public_kernel_blocks(self):
        # The public kernel's block menu is 512/256/128 only; seq=576 splits
        # under the in-repo picker (64) but not the public one — flash_tpu
        # must reject it instead of silently running blockwise at runtime.
        with pytest.raises(ValueError, match="block-divisible"):
            validate_config(get_config("test", attn_impl="flash_tpu"), None,
                            batch=8, seq=576)
        validate_config(get_config("test", attn_impl="flash"), None,
                        batch=8, seq=576)

    def test_tp_overlap_nondivisible_rows_raises(self):
        mesh = make_mesh(dp=1, fsdp=2, tp=4, sp=1)
        cfg = get_config("test", tp_overlap=True)
        with pytest.raises(ValueError, match="tp_overlap"):
            validate_config(cfg, mesh, batch=2, seq=3)

    def test_unknown_impls_raise(self):
        with pytest.raises(ValueError, match="attn_impl"):
            validate_config(get_config("test", attn_impl="splash"), None)
        with pytest.raises(ValueError, match="quant"):
            validate_config(get_config("test", quant="fp8"), None)

    def test_valid_combo_passes(self):
        mesh = make_mesh(dp=1, fsdp=2, tp=4, sp=1)
        cfg = get_config("test", attn_impl="flash", quant="int8",
                         tp_overlap=True)
        validate_config(cfg, mesh, batch=8, seq=64)


class TestCLI:
    def test_train_main_threads_attn_impl_and_quant(self, monkeypatch, capsys):
        """--attn-impl flash --quant int8 run end to end in-process: the
        interpret-mode kernel + STE dot inside a real jitted train step."""
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--attn-impl", "flash", "--quant", "int8",
            "--prefetch", "0",
        ])
        train_lib.main()
        out = capsys.readouterr().out
        assert "compile+first-step" in out

    def test_train_main_tp_axis_runs_overlap(self, monkeypatch, capsys):
        """--tp 4 --tp-overlap builds a real tp mesh from the CLI and runs the
        collective-matmul ring inside the jitted step."""
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--tp", "4", "--tp-overlap", "--prefetch", "0",
        ])
        train_lib.main()
        out = capsys.readouterr().out
        assert "'tp': 4" in out

    def test_train_main_tp_overlap_without_tp_raises(self, monkeypatch):
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--tp-overlap",
        ])
        with pytest.raises(ValueError, match="--tp > 1"):
            train_lib.main()

    def test_train_main_rejects_invalid_combo(self, monkeypatch):
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "31",
            "--attn-impl", "flash",
        ])
        with pytest.raises(ValueError, match="block-divisible"):
            train_lib.main()

    def test_serve_engine_config_from_cli_shapes(self):
        # The ServeEngine config surface the serve CLI constructs.
        ecfg = serve_lib.EngineConfig(decode_impl="xla", quant="int8")
        engine = serve_lib.ServeEngine(
            TINY_SERVE, ecfg,
            params=model_lib.init_params(TINY_SERVE, jax.random.PRNGKey(1)),
        )
        stats = engine.stats()
        assert stats["decode_impl"] == "xla"
        assert stats["quant"] == "int8"


class TestBenchPlan:
    def test_variant_plan_covers_kernel_levers(self):
        sys.path.insert(0, "/root/repo")
        import bench

        names = [n for n, _ in bench._variant_plan(8)]
        for expected in ("static", "flash", "int8", "flash_int8"):
            assert expected in names, names
        tp_names = [n for n, _ in bench._tp_variant_plan(8)]
        assert "tp_overlap" in tp_names
        # Every kernel-lever variant carries its cfg overrides.
        plan = dict(bench._variant_plan(8))
        assert plan["flash"]["cfg_overrides"] == {"attn_impl": "flash"}
        assert plan["int8"]["cfg_overrides"] == {"quant": "int8"}
