"""In-repo Pallas kernels + int8 quantization + collective matmul (PR 8).

Everything runs the EXACT kernel code the TPU executes, via Pallas interpret
mode on the virtual 8-device CPU mesh (conftest). The load-bearing claims:

- the flash kernel matches ``blockwise_attention`` to <=1e-4, outputs AND
  gradients, causal and not, GQA included;
- the splash block-sparse kernel matches the masked materializing reference
  (causal / local-window / document masks), outputs AND gradients;
- the collective-matmul ppermute ring equals all-gather-then-matmul, and the
  FSDP all-gather ring (``allgather_matmul``) equals the plain einsum;
- int8 AND fp8 quantization are bounded-error forward and *exactly* fp
  backward (STE);
- the serving engine is token-identical with either decode implementation,
  preemption included;
- the autotune block cache round-trips, keys by chip generation, and
  degrades (never crashes) on corrupt or stale entries.
"""

import dataclasses
import json
import os
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Pin to CPU fp32 numerics (the axon TPU plugin ignores JAX_PLATFORMS).
jax.config.update("jax_default_device", jax.devices("cpu")[0])

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import quantize as quant_lib
from dstack_tpu.workloads import serve as serve_lib
from dstack_tpu.workloads import train as train_lib
from dstack_tpu.workloads.attention import (
    attention_core,
    blockwise_attention,
    paged_decode_attention,
)
from dstack_tpu.workloads.config import get_config, validate_config
from dstack_tpu.workloads.kernels import (
    allgather_matmul,
    collective_matmul,
    flash_attention,
    flash_attention_sharded,
    paged_decode_attention_pallas,
    pick_flash_block,
    splash_attention,
    splash_attention_sharded,
)
from dstack_tpu.workloads.kernels import autotune as autotune_lib
from dstack_tpu.workloads.kernels import platform as platform_lib
from dstack_tpu.workloads.kernels.collective import can_fsdp_overlap, can_overlap
from dstack_tpu.workloads.kernels.splash import splash_reference
from dstack_tpu.workloads.sharding import (
    batch_sharding,
    make_mesh,
    shard_params,
)

TOL = 1e-4


def qkv(key, t=128, s=None, h=4, kh=2, d=16, b=2):
    s = s or t
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, t, h, d)),
        jax.random.normal(kk, (b, s, kh, d)),
        jax.random.normal(kv, (b, s, kh, d)),
    )


class TestFlashKernel:
    @pytest.mark.parametrize("causal", [True, False])
    def test_fwd_matches_blockwise(self, causal):
        q, k, v = qkv(jax.random.PRNGKey(0))
        out = flash_attention(q, k, v, causal=causal)
        ref = blockwise_attention(q, k, v, causal=causal, block_size=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_blockwise(self, causal):
        """fwd AND bwd equivalence — the custom-VJP backward kernels (dq and
        dk/dv passes, GQA repeat-group gradient sum) against XLA autodiff
        through the blockwise scan."""
        q, k, v = qkv(jax.random.PRNGKey(1), t=64, h=4, kh=2, d=16)

        got = jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(
                flash_attention(q, k, v, causal=causal))),
            argnums=(0, 1, 2),
        )(q, k, v)
        want = jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(blockwise_attention(
                q, k, v, causal=causal, block_size=32))),
            argnums=(0, 1, 2),
        )(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=TOL,
                err_msg=f"d{name} mismatch",
            )

    def test_gqa_multiple_repeat_groups(self):
        # n_rep = 4: the repeat fold and the bwd repeat-group sum.
        q, k, v = qkv(jax.random.PRNGKey(2), t=64, h=8, kh=2, d=8)
        out = flash_attention(q, k, v)
        ref = blockwise_attention(q, k, v, block_size=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_nondivisible_seq_raises(self):
        q, k, v = qkv(jax.random.PRNGKey(3), t=63)
        assert pick_flash_block(63) is None
        with pytest.raises(ValueError, match="block-divisible"):
            flash_attention(q, k, v)

    def test_attention_core_flash_falls_back_on_odd_seq(self):
        # Mid-model (no explicit CLI request) the dispatcher degrades to
        # blockwise instead of crashing on a ragged length.
        q, k, v = qkv(jax.random.PRNGKey(4), t=63)
        out = attention_core(q, k, v, "flash", None)
        ref = blockwise_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_model_forward_flash_matches_blockwise(self):
        cfg_f = get_config("test", max_seq_len=64, attn_impl="flash",
                           dtype="float32")
        cfg_b = get_config("test", max_seq_len=64, dtype="float32")
        params = model_lib.init_params(cfg_b, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 64), 0, cfg_b.vocab_size
        )
        lf = model_lib.forward(params, tokens, cfg_f)
        lb = model_lib.forward(params, tokens, cfg_b)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lb), atol=2e-3)

    def test_flash_sharded_on_mesh_matches(self):
        """Under a (fsdp, tp) mesh the kernel runs per-shard via shard_map —
        same numbers as the meshless kernel."""
        mesh = make_mesh(dp=1, fsdp=2, tp=2, sp=1,
                         devices=jax.devices("cpu")[:4])
        cfg = get_config("test", max_seq_len=64, attn_impl="flash",
                         dtype="float32")
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size
        )
        ref = model_lib.forward(params, tokens, cfg)  # meshless kernel
        with mesh:
            sp = shard_params(params, mesh)
            toks = jax.device_put(tokens, batch_sharding(mesh))
            got = jax.jit(
                lambda p, t: model_lib.forward(p, t, cfg, mesh)
            )(sp, toks)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-3)


class TestQuantize:
    def test_roundtrip_error_bounded_by_half_step(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (32, 64))
        q, s = quant_lib.quantize_int8(x, axis=0)
        deq = quant_lib.dequantize(q, s)
        assert float(jnp.max(jnp.abs(deq - x) / s)) <= 0.5 + 1e-6

    def test_int8_matmul_error_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 256))
        w = jax.random.normal(jax.random.PRNGKey(2), (256, 128))
        got = quant_lib.int8_matmul(x, w)
        ref = x @ w
        rel = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
        # Two independently-rounded int8 operands over K=256: ~1% observed;
        # 5% is the loud-failure line.
        assert rel < 0.05, rel

    def test_zero_channel_safe(self):
        x = jnp.zeros((8, 16))
        q, s = quant_lib.quantize_int8(x, axis=-1)
        assert float(jnp.max(jnp.abs(quant_lib.dequantize(q, s)))) == 0.0

    def test_ste_grads_are_exactly_fp(self):
        """The straight-through VJP must return the fp-matmul gradients (the
        whole point: quantization noise is forward-only)."""
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 8, 16))
        w = jax.random.normal(jax.random.PRNGKey(4), (16, 12))

        def loss_q(x, w):
            return jnp.sum(jnp.sin(quant_lib.int8_matmul_ste(x, w)))

        gx, gw = jax.grad(loss_q, argnums=(0, 1))(x, w)
        y = quant_lib.int8_matmul(x, w)
        g = jnp.cos(y)  # d/dy sum(sin(y))
        want_gx = jnp.einsum("abn,kn->abk", g, w)
        want_gw = jnp.einsum("abk,abn->kn", x, g)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(want_gx),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(want_gw),
                                   atol=1e-5)

    def test_weight_only_matmul_error_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(5), (4, 64))
        w = jax.random.normal(jax.random.PRNGKey(6), (64, 32))
        qw = quant_lib.quantize_weight(w)
        got = quant_lib.weight_only_matmul(x, qw.values, qw.scales)
        rel = float(jnp.linalg.norm(got - x @ w) / jnp.linalg.norm(x @ w))
        # Only the weight is rounded: tighter than the dual-quantized bound.
        assert rel < 0.02, rel

    def test_fake_quant_ste(self):
        w = jax.random.normal(jax.random.PRNGKey(7), (3, 8, 4))
        fq = quant_lib.fake_quant(w, axis=1)
        assert fq.shape == w.shape
        # Values land on the per-channel int8 grid.
        scales = quant_lib.absmax_scales(w, axis=1)
        steps = fq / scales
        np.testing.assert_allclose(
            np.asarray(steps), np.round(np.asarray(steps)), atol=1e-4
        )
        # Gradients pass straight through.
        g = jax.grad(lambda w: jnp.sum(quant_lib.fake_quant(w, 1) ** 2))(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(2 * fq), atol=1e-5)

    def test_check_quant_raises(self):
        with pytest.raises(ValueError, match="unknown quant"):
            quant_lib.check_quant("fp4")

    def test_int8_train_convergence_not_worse(self):
        """The acceptance bar: an int8 STE train run on the tiny config must
        descend like the fp run (same data, same init, same steps)."""
        losses = {}
        for quant in ("none", "int8"):
            cfg = get_config("test", max_seq_len=32, quant=quant,
                             d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
                             d_ff=256, vocab_size=512)
            opt = train_lib.make_optimizer(learning_rate=1e-3)
            state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt)
            step = train_lib.make_train_step(cfg, opt)
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size
            )
            run = []
            for _ in range(8):
                state, m = step(state, tokens, tokens)
                run.append(float(m["loss"]))
            losses[quant] = run
        assert losses["int8"][-1] < losses["int8"][0], losses["int8"]
        # Not worse: within 10% of the fp final loss on this overfit probe.
        assert losses["int8"][-1] <= losses["none"][-1] * 1.10 + 0.05, losses


class TestCollectiveMatmul:
    def _mesh(self):
        return make_mesh(dp=1, fsdp=2, tp=4, sp=1)

    def test_matches_allgather_matmul(self):
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 16, 64))
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 32))
        with mesh:
            got = jax.jit(lambda a, b: collective_matmul(a, b, mesh))(x, w)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(jnp.einsum("btk,kn->btn", x, w)),
            atol=TOL,
        )

    def test_grads_match_allgather_matmul(self):
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 8, 32))
        w = jax.random.normal(jax.random.PRNGKey(3), (32, 16))
        with mesh:
            gx, gw = jax.jit(jax.grad(
                lambda a, b: jnp.sum(jnp.sin(collective_matmul(a, b, mesh))),
                argnums=(0, 1),
            ))(x, w)
        rx, rw = jax.grad(
            lambda a, b: jnp.sum(jnp.sin(jnp.einsum("btk,kn->btn", a, b))),
            argnums=(0, 1),
        )(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=TOL)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=TOL)

    def test_int8_partials(self):
        """quant=int8 composes: each ring chunk runs the quantized dot with
        per-shard scales — bounded error vs the fp product."""
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(4), (8, 8, 64))
        w = jax.random.normal(jax.random.PRNGKey(5), (64, 32))
        with mesh:
            got = jax.jit(lambda a, b: collective_matmul(
                a, b, mesh, matmul=quant_lib.int8_matmul_ste
            ))(x, w)
        ref = jnp.einsum("btk,kn->btn", x, w)
        rel = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
        assert rel < 0.05, rel

    def test_can_overlap_divisibility(self):
        mesh = self._mesh()
        assert can_overlap(mesh, batch=8, seq=16)
        # 2 local rows x 16 seq = 32 rows ... batch=2 -> 1 row/shard x 16 = 16,
        # 16 % 4 == 0 still fine; batch=2, seq=3 -> 3 rows, not divisible by 4.
        assert not can_overlap(mesh, batch=2, seq=3)
        assert not can_overlap(None, batch=8, seq=16)
        tp1 = make_mesh(dp=1, fsdp=8, tp=1, sp=1)
        assert not can_overlap(tp1, batch=8, seq=16)

    def test_model_forward_tp_overlap_matches(self):
        mesh = self._mesh()
        cfg_o = get_config("test", max_seq_len=32, tp_overlap=True,
                           dtype="float32")
        cfg_p = get_config("test", max_seq_len=32, dtype="float32")
        params = model_lib.init_params(cfg_p, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg_p.vocab_size
        )
        with mesh:
            sp = shard_params(params, mesh)
            toks = jax.device_put(tokens, batch_sharding(mesh))
            lo = jax.jit(lambda p, t: model_lib.forward(p, t, cfg_o, mesh))(sp, toks)
            lp = jax.jit(lambda p, t: model_lib.forward(p, t, cfg_p, mesh))(sp, toks)
        np.testing.assert_allclose(np.asarray(lo), np.asarray(lp), atol=1e-3)

    def test_train_step_with_tp_overlap_descends(self):
        mesh = self._mesh()
        cfg = get_config("test", max_seq_len=32, tp_overlap=True,
                         dtype="float32")
        opt = train_lib.make_optimizer()
        with mesh:
            state = train_lib.init_train_state(
                cfg, jax.random.PRNGKey(0), opt, mesh
            )
            step = train_lib.make_train_step(cfg, opt, mesh)
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                   cfg.vocab_size),
                batch_sharding(mesh),
            )
            losses = []
            for _ in range(3):
                state, m = step(state, tokens, tokens)
                losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses


TINY_SERVE = get_config(
    "test", n_layers=2, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
    vocab_size=251, max_seq_len=128, dtype="float32", param_dtype="float32",
    remat=False,
)


@pytest.fixture(scope="module")
def serve_params():
    return model_lib.init_params(TINY_SERVE, jax.random.PRNGKey(0))


def run_engine(engine, limit=3000):
    for _ in range(limit):
        if not engine.has_work():
            return
        engine.step()
    raise AssertionError("engine did not drain")


class TestPagedKernel:
    def test_matches_xla_reference(self):
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        q = jax.random.normal(ks[0], (4, 4, 16))
        kp = jax.random.normal(ks[1], (12, 8, 2, 16))
        vp = jax.random.normal(ks[2], (12, 8, 2, 16))
        pt = jax.random.randint(ks[3], (4, 6), 0, 12)
        lens = jnp.array([0, 5, 17, 48], jnp.int32)
        got = paged_decode_attention_pallas(q, kp, vp, pt, lens)
        ref = paged_decode_attention(q, kp, vp, pt, lens)
        # Active slots identical; the kv_len==0 slot just needs to be finite
        # (engine discards it — XLA emits uniform-weight garbage, the kernel
        # emits zeros).
        np.testing.assert_allclose(
            np.asarray(got[1:]), np.asarray(ref[1:]), atol=TOL
        )
        assert bool(jnp.isfinite(got).all())

    def test_engine_token_identity_pallas_vs_reference(self, serve_params):
        prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [10, 11, 12, 13]]
        engine = serve_lib.ServeEngine(
            TINY_SERVE,
            serve_lib.EngineConfig(page_size=8, num_pages=32, max_batch=4,
                                   max_seq=128, decode_impl="pallas"),
            params=serve_params,
        )
        assert engine.decode_impl == "pallas"
        reqs = [engine.submit(p, max_new_tokens=10) for p in prompts]
        run_engine(engine)
        for p, r in zip(prompts, reqs):
            assert r.tokens == serve_lib.greedy_reference_decode(
                serve_params, TINY_SERVE, p, 10
            )

    def test_engine_token_identity_under_preemption(self, serve_params):
        """The acceptance bar: the Pallas decode path stays token-identical
        through preemption + re-prefill (pool sized to force >=1 preemption).
        """
        engine = serve_lib.ServeEngine(
            TINY_SERVE,
            serve_lib.EngineConfig(page_size=4, num_pages=7, max_batch=3,
                                   max_seq=96, decode_impl="pallas"),
            params=serve_params,
        )
        prompts = [[i + 1, i + 2, i + 3, i + 4, i + 5] for i in (0, 10, 20)]
        reqs = [engine.submit(p, max_new_tokens=20) for p in prompts]
        run_engine(engine)
        assert max(r.preemptions for r in reqs) >= 1, (
            "pool was sized to force preemption"
        )
        for p, r in zip(prompts, reqs):
            assert r.tokens == serve_lib.greedy_reference_decode(
                serve_params, TINY_SERVE, p, 20
            )


class TestServeQuant:
    def test_quantized_param_layout(self, serve_params):
        qp = serve_lib.quantize_serve_params(serve_params)
        for k in serve_lib._WEIGHT_KEYS:
            assert qp[k + "_q"].dtype == jnp.int8
            assert qp[k + "_q"].shape == serve_params[k].shape
            assert qp[k + "_s"].dtype == jnp.float32
            # stacked [L, K, N] -> per-channel scales [L, 1, N]
            assert qp[k + "_s"].shape[-2] == 1
            assert k not in qp  # fp copy not duplicated into the jit args
        assert qp["lm_head_q"].dtype == jnp.int8
        assert qp["embed"].dtype == serve_params["embed"].dtype

    def test_int8_engine_decodes_finitely_and_deterministically(
        self, serve_params
    ):
        def run():
            engine = serve_lib.ServeEngine(
                TINY_SERVE,
                serve_lib.EngineConfig(page_size=8, num_pages=32, max_batch=2,
                                       max_seq=128, quant="int8"),
                params=serve_params,
            )
            req = engine.submit([3, 5, 7, 11], max_new_tokens=8)
            run_engine(engine)
            return req.tokens

        a, b = run(), run()
        assert a == b and len(a) == 8
        assert all(0 <= t < TINY_SERVE.vocab_size for t in a)

    def test_bad_engine_config_raises(self, serve_params):
        with pytest.raises(ValueError, match="decode_impl"):
            serve_lib.ServeEngine(
                TINY_SERVE, serve_lib.EngineConfig(decode_impl="mosaic"),
                params=serve_params,
            )
        with pytest.raises(ValueError, match="quant"):
            serve_lib.ServeEngine(
                TINY_SERVE, serve_lib.EngineConfig(quant="fp4"),
                params=serve_params,
            )


class TestValidation:
    def test_flash_plus_sp_raises(self):
        mesh = make_mesh(dp=1, fsdp=1, tp=1, sp=8)
        cfg = get_config("test", attn_impl="flash")
        with pytest.raises(ValueError, match="sequence"):
            validate_config(cfg, mesh, batch=8, seq=128)

    def test_flash_nondivisible_seq_raises(self):
        cfg = get_config("test", attn_impl="flash")
        with pytest.raises(ValueError, match="block-divisible"):
            validate_config(cfg, None, batch=8, seq=127)

    def test_flash_tp_must_divide_kv_heads(self):
        mesh = make_mesh(dp=1, fsdp=1, tp=8, sp=1)
        cfg = get_config("test", attn_impl="flash")  # n_kv_heads=4
        with pytest.raises(ValueError, match="n_kv_heads"):
            validate_config(cfg, mesh, batch=8, seq=128)

    def test_flash_tpu_under_mesh_raises(self):
        # The public kernel has no SPMD rule: under any mesh (train always
        # builds one) it would silently degrade to blockwise — reject loudly.
        mesh = make_mesh(dp=1, tp=1, sp=1)  # fsdp absorbs all devices
        cfg = get_config("test", attn_impl="flash_tpu")
        with pytest.raises(ValueError, match="meshless"):
            validate_config(cfg, mesh, batch=8, seq=128)
        validate_config(get_config("test", attn_impl="flash_tpu"), None,
                        batch=8, seq=128)

    def test_flash_tpu_seq_uses_public_kernel_blocks(self):
        # The public kernel's block menu is 512/256/128 only; seq=576 splits
        # under the in-repo picker (64) but not the public one — flash_tpu
        # must reject it instead of silently running blockwise at runtime.
        with pytest.raises(ValueError, match="block-divisible"):
            validate_config(get_config("test", attn_impl="flash_tpu"), None,
                            batch=8, seq=576)
        validate_config(get_config("test", attn_impl="flash"), None,
                        batch=8, seq=576)

    def test_tp_overlap_nondivisible_rows_raises(self):
        mesh = make_mesh(dp=1, fsdp=2, tp=4, sp=1)
        cfg = get_config("test", tp_overlap=True)
        with pytest.raises(ValueError, match="tp_overlap"):
            validate_config(cfg, mesh, batch=2, seq=3)

    def test_unknown_impls_raise(self):
        with pytest.raises(ValueError, match="attn_impl"):
            validate_config(get_config("test", attn_impl="splashy"), None)
        with pytest.raises(ValueError, match="quant"):
            validate_config(get_config("test", quant="fp4"), None)

    def test_valid_combo_passes(self):
        mesh = make_mesh(dp=1, fsdp=2, tp=4, sp=1)
        cfg = get_config("test", attn_impl="flash", quant="int8",
                         tp_overlap=True)
        validate_config(cfg, mesh, batch=8, seq=64)


class TestCLI:
    def test_train_main_threads_attn_impl_and_quant(self, monkeypatch, capsys):
        """--attn-impl flash --quant int8 run end to end in-process: the
        interpret-mode kernel + STE dot inside a real jitted train step."""
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--attn-impl", "flash", "--quant", "int8",
            "--prefetch", "0",
        ])
        train_lib.main()
        out = capsys.readouterr().out
        assert "compile+first-step" in out

    def test_train_main_threads_splash_and_window(self, monkeypatch, capsys):
        """--attn-impl splash --attn-window 16: the block-sparse kernel with
        a live local-window bound inside a real jitted train step."""
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--attn-impl", "splash", "--attn-window", "16",
            "--prefetch", "0",
        ])
        train_lib.main()
        out = capsys.readouterr().out
        assert "compile+first-step" in out

    def test_train_main_fsdp_overlap_runs_ring(self, monkeypatch, capsys):
        """--fsdp-overlap on the default (dp, fsdp) mesh runs the allgather
        ring inside the jitted step."""
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--fsdp-overlap", "--prefetch", "0",
        ])
        train_lib.main()
        out = capsys.readouterr().out
        assert "compile+first-step" in out

    def test_train_main_tp_axis_runs_overlap(self, monkeypatch, capsys):
        """--tp 4 --tp-overlap builds a real tp mesh from the CLI and runs the
        collective-matmul ring inside the jitted step."""
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--tp", "4", "--tp-overlap", "--prefetch", "0",
        ])
        train_lib.main()
        out = capsys.readouterr().out
        assert "'tp': 4" in out

    def test_train_main_tp_overlap_without_tp_raises(self, monkeypatch):
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--tp-overlap",
        ])
        with pytest.raises(ValueError, match="--tp > 1"):
            train_lib.main()

    def test_train_main_rejects_invalid_combo(self, monkeypatch):
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "31",
            "--attn-impl", "flash",
        ])
        with pytest.raises(ValueError, match="block-divisible"):
            train_lib.main()

    def test_serve_engine_config_from_cli_shapes(self):
        # The ServeEngine config surface the serve CLI constructs.
        ecfg = serve_lib.EngineConfig(decode_impl="xla", quant="int8")
        engine = serve_lib.ServeEngine(
            TINY_SERVE, ecfg,
            params=model_lib.init_params(TINY_SERVE, jax.random.PRNGKey(1)),
        )
        stats = engine.stats()
        assert stats["decode_impl"] == "xla"
        assert stats["quant"] == "int8"


class TestBenchPlan:
    def test_variant_plan_covers_kernel_levers(self):
        sys.path.insert(0, "/root/repo")
        import bench

        names = [n for n, _ in bench._variant_plan(8)]
        for expected in ("static", "flash", "int8", "flash_int8"):
            assert expected in names, names
        tp_names = [n for n, _ in bench._tp_variant_plan(8)]
        assert "tp_overlap" in tp_names
        # Every kernel-lever variant carries its cfg overrides.
        plan = dict(bench._variant_plan(8))
        assert plan["flash"]["cfg_overrides"] == {"attn_impl": "flash"}
        assert plan["int8"]["cfg_overrides"] == {"quant": "int8"}

    def test_variant_plan_covers_new_levers(self):
        sys.path.insert(0, "/root/repo")
        import bench

        plan = dict(bench._variant_plan(8))
        assert plan["fp8"]["cfg_overrides"] == {"quant": "fp8"}
        assert plan["splash"]["cfg_overrides"] == {"attn_impl": "splash"}
        assert plan["splash_window"]["cfg_overrides"] == {
            "attn_impl": "splash", "attn_window": 64,
        }
        assert plan["flash_autotuned"]["autotune"] is True
        fsdp = dict(bench._fsdp_variant_plan(8))
        assert fsdp["fsdp_overlap"]["cfg_overrides"] == {"fsdp_overlap": True}
        assert fsdp["fsdp_overlap_int8"]["cfg_overrides"] == {
            "fsdp_overlap": True, "quant": "int8",
        }


class TestSplashKernel:
    @pytest.mark.parametrize("causal,window", [(True, 0), (True, 48),
                                               (False, 0)])
    def test_fwd_matches_reference(self, causal, window):
        q, k, v = qkv(jax.random.PRNGKey(10))
        out = splash_attention(q, k, v, causal=causal, window=window)
        ref = splash_reference(q, k, v, causal=causal, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_doc_mask_matches_reference(self):
        q, k, v = qkv(jax.random.PRNGKey(11))
        # Three packed documents of uneven length in a 128-token row.
        doc_ids = jnp.concatenate([
            jnp.zeros((2, 40), jnp.int32),
            jnp.ones((2, 56), jnp.int32),
            jnp.full((2, 32), 2, jnp.int32),
        ], axis=1)
        out = splash_attention(q, k, v, doc_ids=doc_ids)
        ref = splash_reference(q, k, v, doc_ids=doc_ids)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_grads_match_reference(self):
        """fwd AND bwd under the window band — the custom-VJP backward must
        apply the identical block-sparse mask."""
        q, k, v = qkv(jax.random.PRNGKey(12), t=64)

        got = jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(
                splash_attention(q, k, v, window=32))),
            argnums=(0, 1, 2),
        )(q, k, v)
        want = jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(
                splash_reference(q, k, v, window=32))),
            argnums=(0, 1, 2),
        )(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=TOL,
                err_msg=f"d{name} mismatch",
            )

    def test_gqa_multiple_repeat_groups(self):
        q, k, v = qkv(jax.random.PRNGKey(13), t=64, h=8, kh=2, d=8)
        out = splash_attention(q, k, v, window=24)
        ref = splash_reference(q, k, v, window=24)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_window_requires_causal(self):
        q, k, v = qkv(jax.random.PRNGKey(14), t=64)
        with pytest.raises(ValueError, match="causal"):
            splash_attention(q, k, v, causal=False, window=16)

    def test_attention_core_dispatches_splash(self):
        q, k, v = qkv(jax.random.PRNGKey(15))
        out = attention_core(q, k, v, "splash", None, window=48)
        ref = splash_reference(q, k, v, window=48)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_attention_core_splash_falls_back_on_odd_seq(self):
        # No block divides 63: the dispatcher degrades to the masked
        # reference instead of crashing mid-model.
        q, k, v = qkv(jax.random.PRNGKey(16), t=63)
        out = attention_core(q, k, v, "splash", None, window=16)
        ref = splash_reference(q, k, v, window=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_sharded_matches_unsharded(self):
        mesh = make_mesh(dp=1, fsdp=2, tp=2, sp=2)
        q, k, v = qkv(jax.random.PRNGKey(17), t=64, b=2)
        doc_ids = jnp.concatenate([
            jnp.zeros((2, 24), jnp.int32), jnp.ones((2, 40), jnp.int32)
        ], axis=1)
        with mesh:
            got = jax.jit(lambda a, b, c, d: splash_attention_sharded(
                a, b, c, mesh, window=32, doc_ids=d
            ))(q, k, v, doc_ids)
        ref = splash_attention(q, k, v, window=32, doc_ids=doc_ids)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=TOL)

    def test_flash_sharded_matches_unsharded(self):
        # Same shard_map contract as splash: flash_attention_sharded is the
        # batch/head-parallel wrapper attention_core uses under a mesh.
        mesh = make_mesh(dp=1, fsdp=2, tp=2, sp=2)
        q, k, v = qkv(jax.random.PRNGKey(18), t=64, b=2)
        with mesh:
            got = jax.jit(lambda a, b, c: flash_attention_sharded(
                a, b, c, mesh
            ))(q, k, v)
        ref = flash_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=TOL)

    def test_validation_window_rules(self):
        with pytest.raises(ValueError, match="attn_window"):
            validate_config(
                get_config("test", attn_impl="splash", attn_window=-1), None
            )
        with pytest.raises(ValueError, match="attn_window"):
            validate_config(
                get_config("test", attn_impl="flash", attn_window=64), None,
                batch=8, seq=128,
            )
        validate_config(
            get_config("test", attn_impl="splash", attn_window=64), None,
            batch=8, seq=128,
        )


class TestFp8:
    def test_quantize_fp8_dtypes_and_scales(self):
        w = jax.random.normal(jax.random.PRNGKey(20), (64, 32))
        q, s = quant_lib.quantize_fp8(w, axis=0)
        assert q.dtype == jnp.float8_e4m3fn
        assert s.dtype == jnp.float32 and s.shape == (1, 32)
        q5, _ = quant_lib.quantize_fp8(w, axis=0, fmt="e5m2")
        assert q5.dtype == jnp.float8_e5m2

    def test_fp8_matmul_error_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(21), (64, 256))
        w = jax.random.normal(jax.random.PRNGKey(22), (256, 128))
        got = quant_lib.fp8_matmul(x, w)
        ref = x @ w
        rel = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
        # e4m3 has 3 mantissa bits: coarser than int8's per-channel grid.
        assert rel < 0.1, rel

    def test_ste_grads_are_exactly_fp(self):
        """Same contract as int8: forward in e4m3, backward the EXACT fp
        gradients against the original operands."""
        x = jax.random.normal(jax.random.PRNGKey(23), (4, 8, 16))
        w = jax.random.normal(jax.random.PRNGKey(24), (16, 12))

        def loss_q(x, w):
            return jnp.sum(jnp.sin(quant_lib.fp8_matmul_ste(x, w)))

        gx, gw = jax.grad(loss_q, argnums=(0, 1))(x, w)
        y = quant_lib.fp8_matmul(x, w)
        g = jnp.cos(y)  # d/dy sum(sin(y))
        want_gx = jnp.einsum("abn,kn->abk", g, w)
        want_gw = jnp.einsum("abk,abn->kn", x, g)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(want_gx),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(want_gw),
                                   atol=1e-5)

    def test_weight_only_fp8_matmul_error_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(25), (4, 64))
        w = jax.random.normal(jax.random.PRNGKey(26), (64, 32))
        qw = quant_lib.quantize_weight(w, mode="fp8")
        assert qw.values.dtype == jnp.float8_e4m3fn
        got = quant_lib.weight_only_matmul(x, qw.values, qw.scales)
        rel = float(jnp.linalg.norm(got - x @ w) / jnp.linalg.norm(x @ w))
        assert rel < 0.05, rel

    def test_supports_fp8_generations(self):
        assert platform_lib.supports_fp8("v5p")
        assert platform_lib.supports_fp8("v6e")
        assert platform_lib.supports_fp8("cpu")  # tests emulate the numerics
        assert not platform_lib.supports_fp8("v4")
        assert not platform_lib.supports_fp8("v5e")

    def test_chip_generation_parses_accelerator_type(self):
        gen = platform_lib.chip_generation
        assert gen({"TPU_ACCELERATOR_TYPE": "v5p-16"}) == "v5p"
        assert gen({"TPU_ACCELERATOR_TYPE": "v5litepod-8"}) == "v5e"
        assert gen({"TPU_ACCELERATOR_TYPE": "v6e-8"}) == "v6e"
        assert gen({}) == "cpu"  # off-TPU test host

    def test_validate_config_gates_fp8_by_generation(self, monkeypatch):
        cfg = get_config("test", quant="fp8")
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-8")
        with pytest.raises(ValueError, match="fp8"):
            validate_config(cfg, None, batch=8, seq=32)
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-16")
        validate_config(cfg, None, batch=8, seq=32)

    def test_fp8_serve_param_layout(self, serve_params):
        qp = serve_lib.quantize_serve_params(serve_params, mode="fp8")
        for k in serve_lib._WEIGHT_KEYS:
            assert qp[k + "_q"].dtype == jnp.float8_e4m3fn
            assert qp[k + "_q"].shape == serve_params[k].shape
            assert qp[k + "_s"].dtype == jnp.float32
            assert k not in qp
        assert qp["lm_head_q"].dtype == jnp.float8_e4m3fn

    def test_fp8_engine_decodes_finitely_and_deterministically(
        self, serve_params
    ):
        def run():
            engine = serve_lib.ServeEngine(
                TINY_SERVE,
                serve_lib.EngineConfig(page_size=8, num_pages=32, max_batch=2,
                                       max_seq=128, quant="fp8"),
                params=serve_params,
            )
            req = engine.submit([3, 5, 7, 11], max_new_tokens=8)
            run_engine(engine)
            return req.tokens

        a, b = run(), run()
        assert a == b and len(a) == 8
        assert all(0 <= t < TINY_SERVE.vocab_size for t in a)

    def test_fp8_train_descends(self):
        cfg = get_config("test", max_seq_len=32, quant="fp8",
                         d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
                         d_ff=256, vocab_size=512)
        opt = train_lib.make_optimizer(learning_rate=1e-3)
        state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), opt)
        step = train_lib.make_train_step(cfg, opt)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size
        )
        losses = []
        for _ in range(5):
            state, m = step(state, tokens, tokens)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses


class TestFsdpOverlap:
    def _mesh(self):
        return make_mesh(dp=2, fsdp=4, tp=1, sp=1)

    def test_matches_einsum(self):
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(30), (8, 16, 64))
        w = jax.random.normal(jax.random.PRNGKey(31), (64, 32))
        with mesh:
            got = jax.jit(lambda a, b: allgather_matmul(a, b, mesh))(x, w)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(jnp.einsum("btk,kn->btn", x, w)),
            atol=TOL,
        )

    def test_grads_match_einsum(self):
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(32), (8, 8, 32))
        w = jax.random.normal(jax.random.PRNGKey(33), (32, 16))
        with mesh:
            gx, gw = jax.jit(jax.grad(
                lambda a, b: jnp.sum(jnp.sin(allgather_matmul(a, b, mesh))),
                argnums=(0, 1),
            ))(x, w)
        rx, rw = jax.grad(
            lambda a, b: jnp.sum(jnp.sin(jnp.einsum("btk,kn->btn", a, b))),
            argnums=(0, 1),
        )(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=TOL)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=TOL)

    def test_int8_partials(self):
        mesh = self._mesh()
        x = jax.random.normal(jax.random.PRNGKey(34), (8, 8, 64))
        w = jax.random.normal(jax.random.PRNGKey(35), (64, 32))
        with mesh:
            got = jax.jit(lambda a, b: allgather_matmul(
                a, b, mesh, matmul=quant_lib.int8_matmul_ste
            ))(x, w)
        ref = jnp.einsum("btk,kn->btn", x, w)
        rel = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
        assert rel < 0.05, rel

    def test_can_fsdp_overlap_divisibility(self):
        mesh = self._mesh()  # dp*fsdp = 8
        assert can_fsdp_overlap(mesh, 64)
        assert not can_fsdp_overlap(mesh, 60)  # 60 % 8 != 0
        assert not can_fsdp_overlap(None, 64)
        flat = make_mesh(dp=1, fsdp=1, tp=8, sp=1)  # no data axes to ring
        assert not can_fsdp_overlap(flat, 64)

    def test_model_forward_fsdp_overlap_matches(self):
        mesh = self._mesh()
        cfg_o = get_config("test", max_seq_len=32, fsdp_overlap=True,
                           dtype="float32")
        cfg_p = get_config("test", max_seq_len=32, dtype="float32")
        params = model_lib.init_params(cfg_p, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg_p.vocab_size
        )
        with mesh:
            sp = shard_params(params, mesh)
            toks = jax.device_put(tokens, batch_sharding(mesh))
            lo = jax.jit(lambda p, t: model_lib.forward(p, t, cfg_o, mesh))(sp, toks)
            lp = jax.jit(lambda p, t: model_lib.forward(p, t, cfg_p, mesh))(sp, toks)
        np.testing.assert_allclose(np.asarray(lo), np.asarray(lp), atol=1e-3)

    def test_train_step_with_fsdp_overlap_descends(self):
        mesh = self._mesh()
        cfg = get_config("test", max_seq_len=32, fsdp_overlap=True,
                         dtype="float32")
        opt = train_lib.make_optimizer()
        with mesh:
            state = train_lib.init_train_state(
                cfg, jax.random.PRNGKey(0), opt, mesh
            )
            step = train_lib.make_train_step(cfg, opt, mesh)
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                   cfg.vocab_size),
                batch_sharding(mesh),
            )
            losses = []
            for _ in range(3):
                state, m = step(state, tokens, tokens)
                losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    def test_validate_config_fsdp_overlap_divisibility(self):
        mesh = self._mesh()
        cfg = get_config("test", fsdp_overlap=True, d_model=60, n_heads=4,
                         n_kv_heads=2)
        with pytest.raises(ValueError, match="fsdp_overlap"):
            validate_config(cfg, mesh, batch=8, seq=32)
        validate_config(get_config("test", fsdp_overlap=True), mesh,
                        batch=8, seq=32)


class TestAutotune:
    @pytest.fixture(autouse=True)
    def _isolated_cache(self, monkeypatch, tmp_path):
        monkeypatch.setenv(autotune_lib.ENV_DIR, str(tmp_path))
        monkeypatch.setattr(autotune_lib, "_memo", None)
        yield

    def test_env_dir_override(self, tmp_path):
        assert autotune_lib.cache_dir() == str(tmp_path)
        assert autotune_lib.cache_path().startswith(str(tmp_path))

    def test_record_lookup_roundtrip(self):
        assert autotune_lib.record("flash", 32, 256, (64, 64), gen="v5e")
        assert autotune_lib.lookup("flash", 32, 256, gen="v5e") == (64, 64)
        # Persisted, not just memoized: a cold reload sees the same entry.
        autotune_lib._memo = None
        assert autotune_lib.lookup("flash", 32, 256, gen="v5e") == (64, 64)

    def test_generation_is_part_of_the_key(self):
        autotune_lib.record("flash", 32, 256, (64, 64), gen="v5e")
        # A v5e-tuned entry must never leak into a v5p (or cpu) lookup.
        assert autotune_lib.lookup("flash", 32, 256, gen="v5p") is None
        assert autotune_lib.lookup("flash", 32, 256, gen="cpu") is None
        # Shipped defaults are ALSO per-generation.
        assert autotune_lib.lookup("flash", 128, 4096, gen="v5p") == (512, 512)
        assert autotune_lib.lookup("flash", 128, 4096, gen="v5e") == (512, 256)

    def test_corrupt_cache_falls_back_to_shipped_defaults(self):
        os.makedirs(autotune_lib.cache_dir(), exist_ok=True)
        with open(autotune_lib.cache_path(), "w") as f:
            f.write("{not json")
        assert autotune_lib.lookup("flash", 64, 2048, gen="v5p") == (512, 512)
        # And recording over the corrupt file heals it.
        assert autotune_lib.record("splash", 64, 1024, (128, 128), gen="v5p")
        assert autotune_lib.lookup("splash", 64, 1024, gen="v5p") == (128, 128)

    def test_malformed_entries_are_dropped_not_fatal(self):
        os.makedirs(autotune_lib.cache_dir(), exist_ok=True)
        with open(autotune_lib.cache_path(), "w") as f:
            json.dump({
                "flash|cpu|16|128": [0, 64],        # non-positive
                "flash|cpu|16|64": "big",           # wrong type
                "splash|cpu|16|128": [32, 32, 32],  # wrong arity
                "flash|cpu|32|256": [64, 64],       # the one valid entry
            }, f)
        assert autotune_lib.lookup("flash", 16, 128, gen="cpu") is None
        assert autotune_lib.lookup("flash", 16, 64, gen="cpu") is None
        assert autotune_lib.lookup("splash", 16, 128, gen="cpu") is None
        assert autotune_lib.lookup("flash", 32, 256, gen="cpu") == (64, 64)

    def test_stale_nondividing_entry_is_ignored_by_kernels(self):
        # A winner tuned for another shape whose blocks don't divide THESE
        # lengths must not break the kernel — heuristic wins silently.
        autotune_lib.record("flash", 16, 128, (96, 96), gen="cpu")
        autotune_lib.record("splash", 16, 128, (96, 96), gen="cpu")
        q, k, v = qkv(jax.random.PRNGKey(40))
        out = flash_attention(q, k, v)
        ref = blockwise_attention(q, k, v, block_size=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)
        out_s = splash_attention(q, k, v, window=48)
        ref_s = splash_reference(q, k, v, window=48)
        np.testing.assert_allclose(np.asarray(out_s), np.asarray(ref_s),
                                   atol=TOL)

    def test_tuned_blocks_are_picked_up(self):
        # The cache entry for exactly this (kernel, cpu, head_dim, seq) wins
        # over the heuristic — same numerics, different tiling.
        autotune_lib.record("flash", 16, 128, (32, 32), gen="cpu")
        q, k, v = qkv(jax.random.PRNGKey(41))
        out = flash_attention(q, k, v)
        ref = flash_attention(q, k, v, block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=TOL)

    def test_tune_sweeps_persists_and_reports(self):
        q, k, v = qkv(jax.random.PRNGKey(42), t=32, h=1, kh=1, b=1)
        report = autotune_lib.tune(
            "flash", q, k, v, gen="cpu", include_bwd=False, repeats=1
        )
        assert report["kernel"] == "flash" and report["gen"] == "cpu"
        assert report["blocks"] is not None
        assert report["sweep"]  # every candidate timed
        assert autotune_lib.lookup("flash", 16, 32, gen="cpu") == tuple(
            report["blocks"]
        )
        with open(autotune_lib.cache_path()) as f:
            assert "flash|cpu|16|32" in json.load(f)


class TestKernelExportsCovered:
    def test_every_kernel_export_has_a_parity_test(self):
        """Lint gate (not numerics): every public kernel in
        ``kernels.__all__`` must be referenced by name somewhere in the
        interpret-mode test suite, so a new export can't ship untested."""
        from dstack_tpu.workloads import kernels

        tests_dir = pathlib.Path(__file__).parent
        src = "\n".join(
            p.read_text() for p in sorted(tests_dir.glob("test_*.py"))
        )
        missing = [name for name in kernels.__all__ if name not in src]
        assert not missing, (
            f"kernels.__all__ entries with no test reference: {missing}"
        )


class TestAutotuneCLI:
    def test_train_main_autotune_runs_sweep(self, monkeypatch, tmp_path,
                                            capsys):
        monkeypatch.setenv(autotune_lib.ENV_DIR, str(tmp_path))
        monkeypatch.setattr(autotune_lib, "_memo", None)
        monkeypatch.setattr(sys, "argv", [
            "train", "--config", "test", "--steps", "1", "--seq", "32",
            "--batch", "8", "--attn-impl", "flash", "--autotune",
            "--prefetch", "0",
        ])
        train_lib.main()
        out = capsys.readouterr().out
        assert "autotune: flash" in out
        assert os.path.exists(autotune_lib.cache_path())
