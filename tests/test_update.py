"""In-place run update (parity: reference runs.py:896-944 update rules + update_run).

Only fields that need no re-provisioning may change on a live run: service
replica/scaling knobs (converged via replica scaling) and dev-env inactivity;
anything else must be stopped and re-applied."""

import pytest

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.server.services import proxy as proxy_service
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.common import FakeRunnerClient, api_server, drive, setup_mock_backend

pytestmark = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)

from tests.test_services import _APP, _drive_until_replicas, _stop_run


def service_spec(run_name: str, replicas=1, **conf) -> dict:
    return {
        "run_spec": {
            "run_name": run_name,
            "configuration": {
                "type": "service",
                "commands": [_APP],
                "port": 8000,
                "replicas": replicas,
                **conf,
            },
        }
    }


class TestInPlaceUpdate:
    async def test_manual_replica_update_scales_live_service(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        proxy_service.stats.reset()
        try:
            async with api_server() as api:
                await api.post("/api/project/main/runs/submit", service_spec("upsvc", 1))
                await _drive_until_replicas(api, "upsvc", 1)

                # The plan reports an in-place update for a replicas-only change.
                plan = await api.post(
                    "/api/project/main/runs/get_plan",
                    service_spec("upsvc", 2),
                )
                assert plan["action"] == "update"

                run = await api.post(
                    "/api/project/main/runs/update", service_spec("upsvc", 2)
                )
                assert run["status"] == "running"
                await _drive_until_replicas(api, "upsvc", 2)
                row = await api.db.fetchone("SELECT * FROM runs WHERE run_name = 'upsvc'")
                assert row["desired_replica_count"] == 2

                # Scale back down in place.
                await api.post("/api/project/main/runs/update", service_spec("upsvc", 1))
                await _drive_until_replicas(api, "upsvc", 1)
                run = await api.post("/api/project/main/runs/get", {"run_name": "upsvc"})
                assert run["status"] == "running"
                await _stop_run(api, "upsvc")
        finally:
            logs_service.set_log_storage(None)

    async def test_non_updatable_change_rejected(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                await api.post("/api/project/main/runs/submit", service_spec("fix", 1))
                await _drive_until_replicas(api, "fix", 1)
                # Changing the command is not an in-place update.
                bad = service_spec("fix", 1)
                bad["run_spec"]["configuration"]["commands"] = ["echo changed"]
                plan = await api.post("/api/project/main/runs/get_plan", bad)
                assert plan["action"] == "create"  # cannot update -> stop & re-apply
                resp = await api.post("/api/project/main/runs/update", bad, expect=400)
                assert "cannot update" in str(resp)
                await _stop_run(api, "fix")
        finally:
            logs_service.set_log_storage(None)

    async def test_dev_env_inactivity_update(self, monkeypatch):
        """inactivity_duration changes apply to the live dev env (the FSM reads the
        updated spec on its next pass)."""
        monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
        FakeRunnerClient.reset()
        backends_service.reset_compute_cache()
        async with api_server() as api:
            spec = {
                "run_spec": {
                    "run_name": "denv",
                    "configuration": {
                        "type": "dev-environment",
                        "inactivity_duration": "1h",
                    },
                }
            }
            await api.post("/api/project/main/runs/submit", spec)
            # FakeRunnerClient's script ends the job 'done'; just verify the spec
            # update path.
            new = {
                "run_spec": {
                    "run_name": "denv",
                    "configuration": {
                        "type": "dev-environment",
                        "inactivity_duration": "2h",
                    },
                }
            }
            run = await api.post("/api/project/main/runs/update", new)
            assert (
                run["run_spec"]["configuration"]["inactivity_duration"] == 7200
            )

    async def test_update_unknown_run_404(self):
        async with api_server() as api:
            await api.post(
                "/api/project/main/runs/update",
                service_spec("nope", 1),
                expect=404,
            )
