"""Gang-wide health (ISSUE 15): the straggler rule as a pure function (skew
matrix, hysteresis, gang shrink, single-host), window summarization, the
collection-pass flow (run_events + /metrics families through the strict
exposition parser + the per-host API), and the PR 11 lead-only invariants
that must SURVIVE the per-host join (goodput ledger and step histogram still
count one lineage, not N hosts)."""

import datetime
import json

import pytest

from dstack_tpu.server.services import gang_health
from dstack_tpu.server.services import metrics as metrics_service
from dstack_tpu.server.services.gang_health import (
    HostStats,
    RunState,
    evaluate_stragglers,
    summarize_host,
)
from dstack_tpu.utils.common import now_utc, to_iso
from tests.common import api_server
from tests.test_run_events import parse_exposition
from tests.test_workload_telemetry import _insert_running_job


def _iso(base, off: float) -> str:
    return to_iso(base + datetime.timedelta(seconds=off))


def _hosts(medians: dict) -> list:
    return [HostStats(host=h, median_step_s=m, steps=5) for h, m in medians.items()]


HEALTHY = {"h0": 1.0, "h1": 1.02, "h2": 0.98, "h3": 1.01}
SKEWED = {"h0": 1.0, "h1": 1.02, "h2": 0.98, "h3": 2.0}


# ---------------------------------------------------------------------------
# The pure rule


class TestStragglerRule:
    def test_skew_matrix_and_flag_after_m_windows(self):
        state = RunState()
        v1 = evaluate_stragglers(_hosts(SKEWED), state, k=1.5, clear_k=1.2, windows=2)
        # Skew math: gang median is the median of host medians; h3 is slowest.
        assert v1.slowest_host == "h3"
        # gang median = median(0.98, 1.0, 1.02, 2.0) = 1.01
        assert v1.skew_ratio == pytest.approx(2.0 / 1.01, rel=1e-3)
        assert v1.detected == [] and v1.cleared == []  # window 1 of 2
        assert state.over["h3"] == 1
        v2 = evaluate_stragglers(_hosts(SKEWED), state, k=1.5, clear_k=1.2, windows=2)
        assert [h for h, _ in v2.detected] == ["h3"]
        assert "h3" in v2.detected[0][1]  # message names the host
        assert state.flagged == {"h3"}
        # Already flagged: no duplicate event on the next window.
        v3 = evaluate_stragglers(_hosts(SKEWED), state, k=1.5, clear_k=1.2, windows=2)
        assert v3.detected == [] and v3.cleared == []

    def test_healthy_gang_never_flags(self):
        state = RunState()
        for _ in range(10):
            v = evaluate_stragglers(
                _hosts(HEALTHY), state, k=1.5, clear_k=1.2, windows=2
            )
            assert v.detected == [] and v.cleared == []
        assert not state.flagged
        assert v.skew_ratio == pytest.approx(1.02 / 1.005, rel=1e-3)

    def test_flapping_host_never_flags(self):
        """Alternating over/under the flag threshold resets the counter each
        healthy window — hysteresis means no event spam from a flapper."""
        state = RunState()
        for i in range(12):
            medians = dict(HEALTHY, h3=2.0 if i % 2 == 0 else 1.0)
            v = evaluate_stragglers(
                _hosts(medians), state, k=1.5, clear_k=1.2, windows=2
            )
            assert v.detected == [], f"window {i} flagged a flapper"
        assert not state.flagged

    def test_clear_needs_consecutive_windows_below_clear_threshold(self):
        state = RunState(flagged={"h3"})
        # Between clear_k (1.2) and k (1.5): stays flagged, emits nothing.
        mid = dict(HEALTHY, h3=1.3)
        v = evaluate_stragglers(_hosts(mid), state, k=1.5, clear_k=1.2, windows=2)
        assert v.cleared == [] and state.flagged == {"h3"}
        # One healthy window is not enough...
        v = evaluate_stragglers(_hosts(HEALTHY), state, k=1.5, clear_k=1.2, windows=2)
        assert v.cleared == [] and state.flagged == {"h3"}
        # ...and a relapse resets the under-counter...
        v = evaluate_stragglers(_hosts(mid), state, k=1.5, clear_k=1.2, windows=2)
        assert state.under["h3"] == 0
        # ...so clearing takes 2 consecutive healthy windows from here.
        evaluate_stragglers(_hosts(HEALTHY), state, k=1.5, clear_k=1.2, windows=2)
        v = evaluate_stragglers(_hosts(HEALTHY), state, k=1.5, clear_k=1.2, windows=2)
        assert [h for h, _ in v.cleared] == ["h3"]
        assert not state.flagged

    def test_single_host_never_flags(self):
        state = RunState()
        for median in (1.0, 50.0, 0.001):
            v = evaluate_stragglers(
                _hosts({"h0": median}), state, k=1.5, clear_k=1.2, windows=1
            )
            assert v.detected == [] and v.skew_ratio is None
        assert not state.flagged and not state.over

    def test_gang_shrink_clears_departed_straggler(self):
        """Elastic restart dropped the flagged host: the flag must clear
        (reason: departed) and its counters must not linger."""
        state = RunState(flagged={"h3"}, over={"h2": 1}, under={"h3": 1})
        survivors = {h: m for h, m in HEALTHY.items() if h != "h3"}
        v = evaluate_stragglers(_hosts(survivors), state, k=1.5, clear_k=1.2, windows=2)
        assert [h for h, _ in v.cleared] == ["h3"]
        assert "left the gang" in v.cleared[0][1]
        assert not state.flagged and "h3" not in state.under
        # h2 is still present AND healthy this window: its counter resets.
        assert state.over.get("h2") == 0

    def test_collection_gap_freezes_counters(self):
        """A window where <2 hosts reported steps must not decay progress
        toward a flag (or toward a clear) — counters freeze until data
        returns."""
        state = RunState()
        evaluate_stragglers(_hosts(SKEWED), state, k=1.5, clear_k=1.2, windows=2)
        assert state.over["h3"] == 1
        gap = [HostStats(host=h, median_step_s=None) for h in SKEWED]
        v = evaluate_stragglers(gap, state, k=1.5, clear_k=1.2, windows=2)
        assert v.skew_ratio is None and state.over["h3"] == 1
        v = evaluate_stragglers(_hosts(SKEWED), state, k=1.5, clear_k=1.2, windows=2)
        assert [h for h, _ in v.detected] == ["h3"]

    def test_two_host_gang_flags_against_pair_median(self):
        state = RunState()
        for _ in range(2):
            v = evaluate_stragglers(
                _hosts({"h0": 1.0, "h1": 4.0}), state, k=1.5, clear_k=1.2, windows=2
            )
        # median of (1.0, 4.0) = 2.5; 4.0/2.5 = 1.6 > 1.5 -> flags.
        assert [h for h, _ in v.detected] == ["h1"]


class TestSummarize:
    def test_summarize_host_window(self):
        points = [
            {"kind": "step", "step": 10, "step_time_s": 1.0,
             "collective_wait_s": 0.2, "input_wait_s": 0.1, "ts": "t1"},
            {"kind": "step", "step": 11, "step_time_s": 3.0,
             "collective_wait_s": 0.4, "mfu": 0.41, "ts": "t2"},
            {"kind": "step", "step": 12, "step_time_s": 2.0, "ts": "t3"},
            {"kind": "host", "cpu_percent": 73.5, "mem_used_bytes": 2 ** 30},
            {"kind": "step", "step": "junk", "step_time_s": "junk"},
        ]
        s = summarize_host("hX", points)
        assert s.median_step_s == 2.0
        assert s.last_step == 12
        assert s.steps == 3
        assert s.collective_wait_s == pytest.approx(0.3)
        assert s.input_wait_s == pytest.approx(0.1)
        assert s.mfu == 0.41
        assert s.cpu_percent == 73.5
        assert s.mem_bytes == 2 ** 30
        assert s.last_ts == "t3"

    def test_summarize_empty(self):
        s = summarize_host("hX", [])
        assert s.median_step_s is None and s.steps == 0


# ---------------------------------------------------------------------------
# The collection-pass flow: DB -> rule -> run_events -> /metrics -> API


async def _store_gang_window(db, job_ids, base, slow_job=None, slow_factor=2.0,
                             steps=5, start_step=1):
    """One window of step points for each job: job_ids[i] emits as host{i};
    slow_job's step times are slow_factor x. Also one host-hardware point per
    job (the agent's kind="host" sample)."""
    for i, jid in enumerate(job_ids):
        job = await db.fetchone("SELECT * FROM jobs WHERE id = ?", (jid,))
        step_time = 0.1 * (slow_factor if jid == slow_job else 1.0)
        points = [
            {"ts": _iso(base, s * 0.1), "kind": "step", "host": f"host{i}",
             "step": start_step + s, "step_time_s": step_time,
             "collective_wait_s": 0.001 if jid == slow_job else 0.05,
             "input_wait_s": 0.01, "mfu": 0.3}
            for s in range(steps)
        ] + [
            {"ts": _iso(base, steps * 0.1), "kind": "host", "host": f"host{i}",
             "cpu_percent": 50.0 + i, "mem_used_bytes": (i + 1) * 2 ** 30},
        ]
        await metrics_service.store_workload_points(db, job, points)


class TestGangHealthPass:
    @pytest.fixture(autouse=True)
    def _fresh_state(self):
        gang_health.reset()
        yield
        gang_health.reset()

    async def _gang(self, api, n=4, run_id="gg", run_name="gang-run"):
        proj = await api.db.fetchone("SELECT * FROM projects")
        job_ids = []
        for i in range(n):
            jid = f"{run_id}-j{i}"
            await _insert_running_job(
                api.db, proj, run_id, jid, run_name=run_name, job_num=i, jpd=False
            )
            job_ids.append(jid)
        return job_ids

    async def test_straggler_detected_within_two_passes_and_cleared(self):
        async with api_server() as api:
            job_ids = await self._gang(api)
            base = now_utc()
            # Two windows of skewed data -> flag on the SECOND pass (the
            # acceptance criterion: detection within 2 collection passes).
            await _store_gang_window(api.db, job_ids, base, slow_job=job_ids[3])
            await gang_health.check_gang_health(api.db)
            events = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status = 'straggler_detected'"
            )
            assert events == []
            await _store_gang_window(
                api.db, job_ids, base, slow_job=job_ids[3], start_step=6
            )
            await gang_health.check_gang_health(api.db)
            events = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status = 'straggler_detected'"
            )
            assert len(events) == 1
            assert events[0]["reason"] == "host3"  # attribution: the right host
            assert events[0]["actor"] == "gang_health"
            assert "host3" in events[0]["message"]

            # /metrics: every new family renders, parses strictly, and the
            # straggler gauge is 1 for host3 and 0 for the healthy hosts.
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            straggler = {
                l["host"]: v
                for _, l, v in families["dstack_tpu_run_straggler"]["samples"]
                if l.get("run") == "gang-run"
            }
            assert straggler == {"host0": 0.0, "host1": 0.0, "host2": 0.0, "host3": 1.0}
            skew = [
                v for _, l, v in families["dstack_tpu_run_step_skew_ratio"]["samples"]
                if l.get("run") == "gang-run"
            ]
            assert skew and skew[0] == pytest.approx(2.0, rel=0.01)
            cpu = {
                l["host"]: v
                for _, l, v in families["dstack_tpu_host_cpu_percent"]["samples"]
                if l.get("run") == "gang-run"
            }
            assert cpu["host0"] == 50.0 and cpu["host3"] == 53.0
            mem = {
                l["host"]: v
                for _, l, v in families["dstack_tpu_host_mem_bytes"]["samples"]
                if l.get("run") == "gang-run"
            }
            # %g exposition formatting keeps 6 significant digits.
            assert mem["host1"] == pytest.approx(2 * 2 ** 30, rel=1e-5)
            coll = {
                l["host"]: v
                for _, l, v in
                families["dstack_tpu_host_collective_wait_seconds"]["samples"]
                if l.get("run") == "gang-run"
            }
            # The victims wait on the fence; the straggler barely does.
            assert coll["host0"] > coll["host3"]

            # The API per-host table agrees with the gauge and the event.
            res = await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "gang-run"}
            )
            assert [h["host"] for h in res["hosts"]] == [
                "host0", "host1", "host2", "host3",
            ]
            flags = {h["host"]: h["straggler"] for h in res["hosts"]}
            assert flags == {"host0": False, "host1": False, "host2": False,
                             "host3": True}
            assert res["skew"]["slowest_host"] == "host3"
            assert res["skew"]["ratio"] == pytest.approx(2.0, rel=0.01)
            assert res["stragglers"] == ["host3"]
            h3 = res["hosts"][3]
            assert h3["median_step_s"] == pytest.approx(0.2)
            assert h3["last_step"] == 10
            assert h3["cpu_percent"] == 53.0

            # Recovery: the trailing window still holds the bad steps, so
            # enough healthy steps must land to pull the median back under
            # the clear threshold — then two consecutive healthy windows
            # emit straggler_cleared and zero the gauge.
            for start in (11, 41):
                await _store_gang_window(
                    api.db, job_ids, now_utc(), slow_job=None, start_step=start,
                    steps=30,
                )
                await gang_health.check_gang_health(api.db)
            cleared = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status = 'straggler_cleared'"
            )
            assert len(cleared) == 1 and cleared[0]["reason"] == "host3"
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            straggler = {
                l["host"]: v
                for _, l, v in families["dstack_tpu_run_straggler"]["samples"]
                if l.get("run") == "gang-run"
            }
            assert straggler["host3"] == 0.0

    async def test_single_host_run_never_flags_but_gets_host_row(self):
        async with api_server() as api:
            job_ids = await self._gang(api, n=1, run_id="solo", run_name="solo-run")
            for start in (1, 6, 11):
                await _store_gang_window(
                    api.db, job_ids, now_utc(), slow_job=job_ids[0], start_step=start
                )
                await gang_health.check_gang_health(api.db)
            events = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status LIKE 'straggler%'"
            )
            assert events == []
            res = await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "solo-run"}
            )
            assert len(res["hosts"]) == 1 and res["skew"] is None
            assert res["hosts"][0]["straggler"] is False

    async def test_gang_shrink_mid_run_clears_via_elastic_restart(self):
        """The flagged host's job leaves the running set (elastic restart onto
        fewer hosts): the next pass clears the flag with a departed event."""
        async with api_server() as api:
            job_ids = await self._gang(api, run_id="sh", run_name="shrink-run")
            for start in (1, 6):
                await _store_gang_window(
                    api.db, job_ids, now_utc(), slow_job=job_ids[3], start_step=start
                )
                await gang_health.check_gang_health(api.db)
            detected = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status = 'straggler_detected'"
            )
            assert len(detected) == 1
            # The gang shrinks: host3's job is gone.
            await api.db.execute(
                "UPDATE jobs SET status = 'failed' WHERE id = ?", (job_ids[3],)
            )
            await _store_gang_window(
                api.db, job_ids[:3], now_utc(), slow_job=None, start_step=11
            )
            await gang_health.check_gang_health(api.db)
            cleared = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status = 'straggler_cleared'"
            )
            assert len(cleared) == 1
            assert cleared[0]["reason"] == "host3"
            assert "left the gang" in cleared[0]["message"]

    async def test_emitter_counters_surface_as_run_counter(self):
        """Satellite: the emitter's own drop/flush-failure counters become
        per-run /metrics counters (summed across the gang's hosts)."""
        async with api_server() as api:
            job_ids = await self._gang(api, n=2, run_id="dr", run_name="drop-run")
            base = now_utc()
            await _store_gang_window(api.db, job_ids, base)
            for i, (jid, dropped) in enumerate(zip(job_ids, (7, 4))):
                job = await api.db.fetchone("SELECT * FROM jobs WHERE id = ?", (jid,))
                await metrics_service.store_workload_points(api.db, job, [
                    {"ts": _iso(base, 1), "kind": "emitter", "dropped": dropped - 1,
                     "write_errors": 0},
                    {"ts": _iso(base, 2), "kind": "emitter", "dropped": dropped,
                     "write_errors": i},
                ])
            await gang_health.check_gang_health(api.db)
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            dropped = {
                l["run"]: v
                for _, l, v in
                families["dstack_tpu_run_telemetry_dropped_points_total"]["samples"]
            }
            # Cumulative per job (max of each stream), summed across hosts.
            assert dropped["drop-run"] == 11.0
            werr = {
                l["run"]: v
                for _, l, v in
                families["dstack_tpu_run_telemetry_write_errors_total"]["samples"]
            }
            assert werr["drop-run"] == 1.0

    async def test_run_delete_forgets_state_and_families_render_empty(self):
        async with api_server() as api:
            job_ids = await self._gang(api, run_id="del", run_name="del-run")
            for start in (1, 6):
                await _store_gang_window(
                    api.db, job_ids, now_utc(), slow_job=job_ids[3], start_step=start
                )
                await gang_health.check_gang_health(api.db)
            assert gang_health.state_for("del").flagged == {"host3"}
            for status in ("jobs", "runs"):
                await api.db.execute(f"UPDATE {status} SET status = 'done'")
            await api.post("/api/project/main/runs/delete", {"runs_names": ["del-run"]})
            assert "del" not in gang_health._states
            # The snapshot self-heals on the next pass; the families still
            # advertise HELP/TYPE with zero samples (cold-server discovery).
            await gang_health.check_gang_health(api.db)
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            for fam in (
                "dstack_tpu_run_step_skew_ratio",
                "dstack_tpu_run_straggler",
                "dstack_tpu_host_cpu_percent",
                "dstack_tpu_host_mem_bytes",
                "dstack_tpu_host_collective_wait_seconds",
                "dstack_tpu_run_telemetry_dropped_points_total",
                "dstack_tpu_run_telemetry_write_errors_total",
            ):
                assert families[fam]["samples"] == [], fam

    async def test_lead_only_invariants_survive_the_per_host_join(self):
        """PR 11's contract: a 4-host gang must NOT multiply the goodput
        ledger or the step histogram, even though gang health now reads all
        four streams."""
        async with api_server() as api:
            job_ids = await self._gang(api, run_id="inv", run_name="inv-run")
            await _store_gang_window(api.db, job_ids, now_utc(), steps=6)
            await gang_health.check_gang_health(api.db)
            res = await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "inv-run"}
            )
            # Ledger: 6 lead steps at 0.1s, not 24.
            assert res["goodput"]["steps"] == 6
            assert res["goodput"]["productive_s"] <= 6 * 0.1 + 1e-6
            assert len(res["hosts"]) == 4  # while the per-host view sees all
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            counts = [
                v for nm, l, v in families["dstack_tpu_run_step_seconds"]["samples"]
                if nm.endswith("_count") and l.get("run") == "inv-run"
            ]
            assert counts == [6.0]


class TestReviewHardening:
    """Regression pins for the review findings: lease scoping, durable flag
    continuity across restart/handoff, monotonic loss counters."""

    @pytest.fixture(autouse=True)
    def _fresh_state(self):
        gang_health.reset()
        yield
        gang_health.reset()

    async def test_pass_skips_runs_leased_to_another_replica(self):
        from dstack_tpu.server.services import leases

        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            job_ids = []
            for i in range(2):
                jid = f"ls-j{i}"
                await _insert_running_job(
                    api.db, proj, "ls", jid, run_name="leased-run", job_num=i,
                    jpd=False,
                )
                job_ids.append(jid)
            for start in (1, 6):
                await _store_gang_window(
                    api.db, job_ids, now_utc(), slow_job=job_ids[1],
                    slow_factor=4.0, start_step=start,
                )
            # Another replica owns the run's lease: this replica's pass must
            # not advance the detector or emit events for it.
            with leases.as_replica("replica-other"):
                await leases.claim_runs(api.db, ["ls"])
            examined = await gang_health.check_gang_health(api.db)
            await gang_health.check_gang_health(api.db)
            assert examined == 0
            events = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status LIKE 'straggler%'"
            )
            assert events == [] and "ls" not in gang_health._states
            # The owner processes it.
            with leases.as_replica("replica-other"):
                await gang_health.check_gang_health(api.db)
                await gang_health.check_gang_health(api.db)
            events = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status = 'straggler_detected'"
            )
            assert len(events) == 1

    async def test_restart_seeds_flags_from_events_no_duplicate_detect(self):
        async with api_server() as api:
            job_ids = await TestGangHealthPass._gang(
                TestGangHealthPass(), api, run_id="rs", run_name="restart-run"
            )
            for start in (1, 6):
                await _store_gang_window(
                    api.db, job_ids, now_utc(), slow_job=job_ids[3],
                    start_step=start,
                )
                await gang_health.check_gang_health(api.db)
            detected = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status = 'straggler_detected'"
            )
            assert len(detected) == 1
            # Server restart: in-process state is gone, the skew persists.
            gang_health.reset()
            for start in (11, 16):
                await _store_gang_window(
                    api.db, job_ids, now_utc(), slow_job=job_ids[3],
                    start_step=start,
                )
                await gang_health.check_gang_health(api.db)
            detected = await api.db.fetchall(
                "SELECT * FROM run_events WHERE new_status = 'straggler_detected'"
            )
            assert len(detected) == 1, "restart re-raised an already-flagged host"
            # A state-less replica answers the API from the durable timeline.
            gang_health.reset()
            res = await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "restart-run"}
            )
            assert res["stragglers"] == ["host3"]

    async def test_loss_counters_never_decrease(self):
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await _insert_running_job(
                api.db, proj, "mono", "mono-j0", run_name="mono-run", jpd=False
            )
            base = now_utc()
            job = await api.db.fetchone("SELECT * FROM jobs WHERE id = 'mono-j0'")
            await metrics_service.store_workload_points(api.db, job, [
                {"ts": _iso(base, 0), "kind": "step", "step": 1, "step_time_s": 0.1},
                {"ts": _iso(base, 1), "kind": "emitter", "dropped": 9,
                 "write_errors": 2},
            ])
            await gang_health.check_gang_health(api.db)
            entry = next(e for e in gang_health.snapshot() if e["run"] == "mono-run")
            assert entry["dropped"] == 9 and entry["write_errors"] == 2
            # The emitter rows age out of the window / a fresh emitter
            # restarts at zero: the exported counter must hold its mark.
            await api.db.execute(
                "DELETE FROM workload_metrics_points WHERE kind = 'emitter'"
            )
            await metrics_service.store_workload_points(api.db, job, [
                {"ts": _iso(base, 2), "kind": "emitter", "dropped": 1,
                 "write_errors": 0},
            ])
            await gang_health.check_gang_health(api.db)
            entry = next(e for e in gang_health.snapshot() if e["run"] == "mono-run")
            assert entry["dropped"] == 9 and entry["write_errors"] == 2

    def test_identity_proc_falls_back_past_unparsable_var(self, monkeypatch):
        from dstack_tpu.workloads.telemetry import _host_identity

        monkeypatch.setenv("TPU_WORKER_ID", "worker-3")  # non-numeric launcher form
        monkeypatch.setenv("DSTACK_NODE_RANK", "3")
        assert _host_identity()["proc"] == 3

    async def test_agent_host_points_do_not_contaminate_goodput(self):
        """The agent appends a kind="host" point to EVERY sample — including
        before the workload's run_start and during a preemption's downtime.
        The ledger must read step/mark kinds only: a host point ahead of
        run_start would bill pull/startup as restart_s, and host points in a
        real restart gap would erase the restart_s PR 12 measures."""
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await _insert_running_job(
                api.db, proj, "gp", "gp-j0", run_name="gp-run", jpd=False
            )
            base = now_utc() - datetime.timedelta(seconds=60)
            job = await api.db.fetchone("SELECT * FROM jobs WHERE id = 'gp-j0'")
            await metrics_service.store_workload_points(api.db, job, [
                # Agent samples land 15s before the workload starts...
                {"ts": _iso(base, 0), "kind": "host", "host": "h", "cpu_percent": 1},
                {"ts": _iso(base, 15), "kind": "mark", "event": "run_start"},
                {"ts": _iso(base, 16), "kind": "step", "step": 1, "step_time_s": 1.0},
                # ...and keep landing inside a 20s restart gap.
                {"ts": _iso(base, 26), "kind": "host", "host": "h", "cpu_percent": 1},
                {"ts": _iso(base, 36), "kind": "mark", "event": "restart", "step": 1},
                {"ts": _iso(base, 37), "kind": "step", "step": 2, "step_time_s": 1.0},
            ])
            res = await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "gp-run"}
            )
            ledger = res["goodput"]
            # Wall = run_start..last step (21s), restart gap = 20s; the host
            # points must neither stretch the wall to the first agent sample
            # nor split the restart gap.
            assert ledger["wall_s"] == pytest.approx(22.0, abs=0.1)
            assert ledger["restart_s"] == pytest.approx(20.0, abs=0.1)
            assert ledger["productive_s"] == pytest.approx(2.0, abs=0.01)


# ---------------------------------------------------------------------------
# CLI surfaces: per-host table, `top`, and the --json satellite


async def _run_cli(api, argv) -> str:
    """Run the real CLI (argparse + sync requests client) against the
    in-process test server, off the event loop."""
    import asyncio
    import contextlib
    import io

    from dstack_tpu.api.client import Client
    from dstack_tpu.cli import main as cli_main

    url = str(api.client.make_url("")).rstrip("/")
    client = Client(url, api.token, project="main")

    def _run() -> str:
        old = cli_main._client
        cli_main._client = lambda: client
        try:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                cli_main.main(argv)
            return buf.getvalue()
        finally:
            cli_main._client = old

    return await asyncio.get_event_loop().run_in_executor(None, _run)


class TestCliSurfaces:
    @pytest.fixture(autouse=True)
    def _fresh_state(self):
        gang_health.reset()
        yield
        gang_health.reset()

    async def test_top_json_flags_and_tables(self):
        from dstack_tpu.server.background import tasks
        from tests.common import (
            FakeRunnerClient,
            drive,
            setup_mock_backend,
            tpu_task_spec,
        )

        class HoldAgent(FakeRunnerClient):
            def default_script(self):
                return [{"job_states": [{"state": "running"}], "logs": [], "offset": 1}]

        HoldAgent.reset()
        real = tasks.get_runner_client
        tasks.get_runner_client = HoldAgent.for_jpd
        try:
            async with api_server() as api:
                await setup_mock_backend(api)
                await api.post(
                    "/api/project/main/runs/submit", tpu_task_spec("cli-gang", "v5e-32")
                )
                await drive(api.db)
                rows = await api.db.fetchall(
                    "SELECT id FROM jobs WHERE status = 'running' ORDER BY job_num"
                )
                job_ids = [r["id"] for r in rows]
                assert len(job_ids) == 4
                for start in (1, 6):
                    await _store_gang_window(
                        api.db, job_ids, now_utc(), slow_job=job_ids[3],
                        start_step=start,
                    )
                    await gang_health.check_gang_health(api.db)

                top = await _run_cli(api, ["top", "--once"])
                for needle in ("RUN", "HOST", "SKEW", "cli-gang", "host3", "STRAGGLER"):
                    assert needle in top, f"top missing {needle!r}:\n{top}"

                mjson = json.loads(await _run_cli(api, ["metrics", "cli-gang", "--json"]))
                assert mjson["stragglers"] == ["host3"]
                assert mjson["skew"]["slowest_host"] == "host3"
                assert [h["host"] for h in mjson["hosts"]] == [
                    "host0", "host1", "host2", "host3",
                ]
                assert "job_metrics" in mjson

                ejson = json.loads(await _run_cli(api, ["events", "cli-gang", "--json"]))
                kinds = [e["new_status"] for e in ejson["events"]]
                assert "straggler_detected" in kinds
                assert ejson["phases"]["queue"] is not None
        finally:
            tasks.get_runner_client = real


# ---------------------------------------------------------------------------
# Trace-id propagation (satellite: server trace -> agent log)


class TestTracePropagation:
    async def test_runner_client_sends_trace_id_header(self):
        """Every runner call carries the scheduler's current trace id."""
        from aiohttp import web

        from dstack_tpu.core import tracing
        from dstack_tpu.server.services.runner.client import RunnerClient

        seen = {}

        async def handler(request):
            seen["trace"] = request.headers.get("X-Dstack-Trace-Id")
            return web.json_response({"timestamp": "t"})

        app = web.Application()
        app.router.add_get("/api/metrics", handler)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        try:
            client = RunnerClient("127.0.0.1", port)
            with tracing.span("collect"):
                tid = tracing.current_trace_id()
                await client.metrics()
            assert tid and seen["trace"] == tid
        finally:
            await runner.cleanup()

    async def test_agent_echoes_trace_id_into_its_log(self, tmp_path):
        """The C++ agent logs `[trace <id>] POST /api/submit` — a run_event's
        trace_id greps straight into the agent log on the host."""
        import asyncio
        import subprocess

        import aiohttp

        from dstack_tpu.utils.runner_binary import find_runner_binary

        binary = find_runner_binary()
        if not binary:
            pytest.skip("native agent unavailable")
        proc = subprocess.Popen(
            [binary, "--port", "0", "--base-dir", str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, bufsize=1,
        )
        loop = asyncio.get_event_loop()
        try:
            first = await asyncio.wait_for(
                loop.run_in_executor(None, proc.stdout.readline), 15
            )
            port = int(first.strip().rsplit(":", 1)[1])
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{port}/api/submit",
                    json={"job_spec": {"job_name": "t"}, "cluster_info": {},
                          "run_spec": {}, "secrets": {}},
                    headers={"X-Dstack-Trace-Id": "tr4ce1d"},
                ) as resp:
                    assert resp.status == 200
            line = await asyncio.wait_for(
                loop.run_in_executor(None, proc.stdout.readline), 15
            )
            assert "[trace tr4ce1d] POST /api/submit" in line, line
        finally:
            proc.kill()
            proc.wait()


# ---------------------------------------------------------------------------
# Emitter identity (the workload end of per-host attribution)


class TestEmitterIdentity:
    def test_points_carry_host_identity(self, tmp_path, monkeypatch):
        from dstack_tpu.workloads.telemetry import TelemetryEmitter

        monkeypatch.setenv("TPU_WORKER_ID", "3")
        monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
        em = TelemetryEmitter(str(tmp_path / "t.jsonl"), flush_interval=999)
        try:
            em.emit("step", step=1, step_time_s=0.5)
            em.set_identity(proc=7)  # jax.process_index refinement wins
            em.mark("run_end")
            em.flush()
        finally:
            em.close()
        lines = [
            json.loads(l)
            for l in (tmp_path / "t.jsonl").read_text().splitlines() if l
        ]
        step = next(p for p in lines if p["kind"] == "step")
        assert step["proc"] == 3 and step["slice"] == 1 and step["host"]
        end = next(p for p in lines if p.get("event") == "run_end")
        assert end["proc"] == 7
        # An explicit field beats the stamped identity.
        em2 = TelemetryEmitter(str(tmp_path / "t2.jsonl"), flush_interval=999)
        try:
            em2.emit("step", step=2, host="override")
            em2.flush()
        finally:
            em2.close()
        p = json.loads((tmp_path / "t2.jsonl").read_text().splitlines()[0])
        assert p["host"] == "override"
