"""Container execution layer: the C++ agent driving the Docker Engine API.

The real runner binary runs with --docker always/auto against tests.fake_docker — a
unix-socket engine that executes container commands via subprocess — so image pull
(with registry auth), create (device mapping / env / binds), log streaming, exit
codes, stop, and restart recovery are all exercised over the actual engine REST
protocol. Parity: reference shim/docker.go:240-875 (Submit/Run/Terminate lifecycle),
restore-from-labels docker.go:104.
"""

import asyncio
import os
import re
import signal
import subprocess
import tarfile
import tempfile

import pytest

from dstack_tpu.core.models.resources import ResourcesSpec
from dstack_tpu.core.models.runs import ClusterInfo, JobSpec, Requirements
from dstack_tpu.server.services.runner.client import RunnerClient
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.fake_docker import FakeDockerDaemon

pytestmark = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)

_LISTEN_RE = re.compile(r"listening on [\d.]+:(\d+)")


def _job_spec(commands, image="test/app:1.0", **kwargs) -> JobSpec:
    return JobSpec(
        job_name="cjob-0-0",
        commands=commands,
        image_name=image,
        requirements=Requirements(resources=ResourcesSpec()),
        **kwargs,
    )


class Runner:
    """A real runner process plus its client."""

    def __init__(self, proc: subprocess.Popen, port: int, base_dir: str) -> None:
        self.proc = proc
        self.port = port
        self.base_dir = base_dir
        self.client = RunnerClient("127.0.0.1", port)

    def kill(self) -> None:
        try:
            self.proc.kill()
            self.proc.wait(timeout=5)
        except ProcessLookupError:
            pass


def spawn_runner(docker_mode: str, docker_sock: str, base_dir=None) -> Runner:
    binary = find_runner_binary()
    base_dir = base_dir or tempfile.mkdtemp(prefix="dstack-tpu-ctest-")
    proc = subprocess.Popen(
        [
            binary,
            "--host", "127.0.0.1",
            "--port", "0",
            "--base-dir", base_dir,
            "--docker", docker_mode,
            "--docker-host", docker_sock,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    assert proc.stdout is not None
    for _ in range(20):
        line = proc.stdout.readline().decode()
        m = _LISTEN_RE.search(line)
        if m:
            return Runner(proc, int(m.group(1)), base_dir)
    raise AssertionError("runner did not start")


async def _pull_until_terminal(client: RunnerClient, timeout=20.0) -> dict:
    """Drains pull until a terminal state event appears; returns it with all logs."""
    offset = 0
    logs = []
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        res = await client.pull(offset)
        offset = res["offset"]
        logs.extend(l["message"] for l in res["logs"])
        for ev in res["job_states"]:
            if ev["state"] in ("done", "failed", "terminated", "aborted"):
                ev = dict(ev)
                ev["all_logs"] = "".join(logs)
                return ev
        await asyncio.sleep(0.1)
    raise AssertionError(f"no terminal state; logs so far: {''.join(logs)!r}")


class TestContainerPath:
    async def test_pull_create_run_collects_logs_and_exit(self, tmp_path):
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock)
        await daemon.start()
        runner = spawn_runner("always", sock)
        try:
            spec = _job_spec(
                ["echo container-marker-$((40+2))", "echo PJRT=$PJRT_DEVICE"],
                registry_auth={"username": "bot", "password": "hunter2"},
            )
            await runner.client.submit(spec, ClusterInfo(node_ips=["127.0.0.1"]))
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "done", final
            assert "container-marker-42" in final["all_logs"]
            # PJRT_DEVICE=TPU is injected into every container (shim parity).
            assert "PJRT=TPU" in final["all_logs"]
            # The pull carried the registry credentials as X-Registry-Auth.
            assert daemon.pulls == [
                {"image": "test/app", "tag": "1.0", "auth": {"username": "bot", "password": "hunter2"}}
            ]
            # Terminal cleanup removed the container.
            assert daemon.containers == {}
        finally:
            runner.kill()
            await daemon.stop()

    async def test_container_config_devices_and_labels(self, tmp_path):
        """The create request maps TPU devices, uses host networking, and labels the
        container for restart recovery."""
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock, images=["test/app:1.0"])
        await daemon.start()
        runner = spawn_runner("always", sock)
        try:
            await runner.client.submit(_job_spec(["true"]), ClusterInfo())
            await runner.client.run_job()
            await _pull_until_terminal(runner.client)
            [seen_config] = daemon.creates
            host = seen_config["HostConfig"]
            assert host["NetworkMode"] == "host"
            assert seen_config["Labels"] == {"dstack-tpu.task": "true", "dstack-tpu.job": "cjob-0-0"}
            assert "PJRT_DEVICE=TPU" in seen_config["Env"]
            # Device list mirrors the host's /dev/accel* (none on CI hosts, but the
            # key must exist with cgroup rwm entries when present).
            assert isinstance(host["Devices"], list)
            for d in host["Devices"]:
                assert d["CgroupPermissions"] == "rwm"
            # Resource caps derived from the requirements floor (default cpu>=2,
            # memory>=8GB).
            assert host["NanoCpus"] == 2_000_000_000
            assert host["Memory"] == 8 * 1024**3
        finally:
            runner.kill()
            await daemon.stop()

    async def test_code_archive_mounted_into_workdir(self, tmp_path):
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock, images=["test/app:1.0"])
        await daemon.start()
        runner = spawn_runner("always", sock)
        try:
            payload = tmp_path / "payload"
            payload.mkdir()
            (payload / "hello.txt").write_text("from-the-repo\n")
            tar_path = tmp_path / "code.tar.gz"
            with tarfile.open(tar_path, "w:gz") as tf:
                tf.add(payload / "hello.txt", arcname="hello.txt")
            await runner.client.submit(_job_spec(["cat hello.txt"]), ClusterInfo())
            await runner.client.upload_code(tar_path.read_bytes())
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "done", final
            assert "from-the-repo" in final["all_logs"]
        finally:
            runner.kill()
            await daemon.stop()

    async def test_nonzero_exit_fails_job(self, tmp_path):
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock, images=["test/app:1.0"])
        await daemon.start()
        runner = spawn_runner("always", sock)
        try:
            await runner.client.submit(_job_spec(["exit 3"]), ClusterInfo())
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "failed"
            assert final["exit_status"] == 3
        finally:
            runner.kill()
            await daemon.stop()

    async def test_pull_failure_fails_job(self, tmp_path):
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock)
        daemon.pull_error = "unauthorized: authentication required"
        await daemon.start()
        runner = spawn_runner("always", sock)
        try:
            await runner.client.submit(_job_spec(["true"]), ClusterInfo())
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "failed"
            assert "unauthorized" in final["message"]
        finally:
            runner.kill()
            await daemon.stop()

    async def test_stop_kills_container(self, tmp_path):
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock, images=["test/app:1.0"])
        await daemon.start()
        runner = spawn_runner("always", sock)
        try:
            await runner.client.submit(
                _job_spec(["echo started", "sleep 300"]), ClusterInfo()
            )
            await runner.client.run_job()
            # Wait until the container process is live, then stop.
            for _ in range(100):
                if any(c.running for c in daemon.containers.values()):
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("container never started")
            await runner.client.stop(abort=False)
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "terminated"
        finally:
            runner.kill()
            await daemon.stop()

    async def test_restart_recovery_reattaches(self, tmp_path):
        """Agent dies mid-job; a fresh agent re-attaches to the labeled container
        instead of double-running it (ref shim restoreStateFromContainers)."""
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock, images=["test/app:1.0"])
        await daemon.start()
        runner = spawn_runner("always", sock)
        base_dir = runner.base_dir
        spec = _job_spec(["echo recovery-marker", "sleep 1.2", "echo recovered-done"])
        try:
            await runner.client.submit(spec, ClusterInfo())
            await runner.client.run_job()
            for _ in range(100):
                if any(c.running for c in daemon.containers.values()):
                    break
                await asyncio.sleep(0.05)
            runner.kill()  # simulated agent crash; the container keeps running

            runner2 = spawn_runner("always", sock, base_dir=base_dir)
            try:
                # The control plane re-submits after a healthcheck reset (idempotent).
                await runner2.client.submit(spec, ClusterInfo())
                await runner2.client.run_job()
                final = await _pull_until_terminal(runner2.client)
                assert final["state"] == "done", final
                assert "re-attaching to container" in final["all_logs"]
                # Exactly one container existed for the job lifetime; recovery did not
                # create a second one.
                assert len(daemon.pulls) == 0
            finally:
                runner2.kill()
        finally:
            runner.kill()
            await daemon.stop()

    async def test_retry_does_not_reattach_previous_submission(self, tmp_path):
        """A retried submission (new job_submission_id) must NOT resurrect the
        previous attempt's leftover container — it replaces it and runs fresh."""
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock, images=["test/app:1.0"])
        await daemon.start()
        runner = spawn_runner("always", sock)
        spec1 = _job_spec(["echo attempt-one", "sleep 300"], job_submission_id="sub-1")
        try:
            await runner.client.submit(spec1, ClusterInfo())
            await runner.client.run_job()
            for _ in range(100):
                if any(c.running for c in daemon.containers.values()):
                    break
                await asyncio.sleep(0.05)
            runner.kill()  # crash mid-attempt; container lingers

            runner2 = spawn_runner("always", sock, base_dir=runner.base_dir)
            try:
                spec2 = _job_spec(["echo attempt-two"], job_submission_id="sub-2")
                await runner2.client.submit(spec2, ClusterInfo())
                await runner2.client.run_job()
                final = await _pull_until_terminal(runner2.client)
                assert final["state"] == "done", final
                assert "attempt-two" in final["all_logs"]
                assert "re-attaching" not in final["all_logs"]
                # Two creates: the retry replaced the stale same-name container.
                assert len(daemon.creates) == 2
            finally:
                runner2.kill()
        finally:
            runner.kill()
            await daemon.stop()

    async def test_empty_commands_run_image_entrypoint(self, tmp_path):
        """A job with an image and no commands runs the image's own entrypoint —
        the create request carries no Entrypoint/Cmd override."""
        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock, images=["entry/img:1"])
        daemon.image_defaults["entry/img:1"] = ["/bin/sh", "-c", "echo image-default-ran"]
        await daemon.start()
        runner = spawn_runner("always", sock)
        try:
            await runner.client.submit(_job_spec([], image="entry/img:1"), ClusterInfo())
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "done", final
            assert "image-default-ran" in final["all_logs"]
            [cfg] = daemon.creates
            assert "Entrypoint" not in cfg and "Cmd" not in cfg
        finally:
            runner.kill()
            await daemon.stop()

    async def test_auto_mode_without_engine_runs_on_host(self, tmp_path):
        runner = spawn_runner("auto", str(tmp_path / "nonexistent.sock"))
        try:
            await runner.client.submit(_job_spec(["echo host-fallback-ok"]), ClusterInfo())
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "done"
            assert "host-fallback-ok" in final["all_logs"]
            assert "docker engine unreachable" in final["all_logs"]
        finally:
            runner.kill()


class TestContainerE2E:
    async def test_local_backend_runs_job_in_container(self, tmp_path, monkeypatch):
        """Full control-plane path: submit a run with image:, the scheduler provisions
        a local runner in --docker always mode, the job executes inside a (fake-engine)
        container, logs land in log storage."""
        from dstack_tpu.server import settings
        from dstack_tpu.server.background import tasks
        from dstack_tpu.server.services import logs as logs_service
        from tests.common import api_server
        from tests.test_e2e_local import _drive_until

        sock = str(tmp_path / "docker.sock")
        daemon = FakeDockerDaemon(sock, images=["my-registry.io/jax-tpu:2.0"])
        await daemon.start()
        monkeypatch.setattr(settings, "LOCAL_DOCKER_MODE", "always")
        monkeypatch.setenv("DOCKER_HOST", f"unix://{sock}")
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path / "logs")))
        try:
            async with api_server() as api:
                spec = {
                    "run_spec": {
                        "run_name": "cont-e2e",
                        "configuration": {
                            "type": "task",
                            "image": "my-registry.io/jax-tpu:2.0",
                            "commands": ["echo in-container-$((6*7))"],
                        },
                    }
                }
                await api.post("/api/project/main/runs/submit", spec)
                run = await _drive_until(api, "cont-e2e", "done")
                assert run["status"] == "done"
                job = await api.db.fetchone("SELECT * FROM jobs")
                events = logs_service.get_log_storage().poll_logs(
                    job["project_id"], "cont-e2e", job["id"]
                )
                text = "".join(e.message for e in events)
                assert "in-container-42" in text
        finally:
            await daemon.stop()
            logs_service.set_log_storage(None)
