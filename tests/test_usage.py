"""Fleet accounting and scheduling explainability (ISSUE 19): the chip-seconds
ledger (services/usage.py meter), the /api/usage/get readout, the placement
decision log (placement_attempt run_events + WAITING status_message +
pending-reason gauges), the fleet/project utilization gauges on /metrics, and
the sweep hygiene that keeps all of it from outliving its run or project."""

import json

import pytest

from dstack_tpu.core import tracing
from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from dstack_tpu.server.services import usage as usage_service
from dstack_tpu.utils.common import from_iso
from tests.common import (
    FakeRunnerClient,
    api_server,
    drive,
    setup_mock_backend,
    tpu_task_spec,
)
from tests.test_run_events import parse_exposition


@pytest.fixture(autouse=True)
def _fake_runner(monkeypatch):
    FakeRunnerClient.reset()
    backends_service.reset_compute_cache()
    monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
    tracing.reset()
    usage_service.reset()
    yield
    FakeRunnerClient.reset()
    tracing.reset()
    usage_service.reset()


def _stuck_spec(name: str) -> dict:
    """A run no offer can satisfy (max_price below every catalog price) that
    stays queued on the no-capacity retry window instead of failing."""
    return tpu_task_spec(
        name,
        "v5e-8",
        max_price=0.0001,
        retry={"on_events": ["no-capacity"], "duration": 3600},
    )


class TestChipSecondsMetering:
    async def test_meter_attributes_lifecycle_window(self):
        """One completed v5e-8 run (8 chips, 1 host): the ledger row equals
        chips x the job's provisioning->finished window exactly — metering
        accrues from lifecycle rows, not tick deltas, so a run shorter than
        one metering interval still bills its full window."""
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("acct", "v5e-8")
            )
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "acct"})
            assert run["status"] == "done"

            touched = await usage_service.meter(api.db)
            assert touched == 1

            sample = await api.db.fetchone(
                "SELECT SUM(chip_seconds) AS cs, SUM(dollars) AS d,"
                " SUM(goodput_chip_seconds) AS gcs FROM usage_samples"
            )
            anchor = await api.db.fetchone(
                "SELECT MIN(timestamp) AS ts FROM run_events"
                " WHERE job_id IS NOT NULL AND new_status = 'provisioning'"
            )
            job = await api.db.fetchone(
                "SELECT finished_at FROM jobs WHERE finished_at IS NOT NULL"
            )
            window = (
                from_iso(job["finished_at"]) - from_iso(anchor["ts"])
            ).total_seconds()
            assert window > 0
            assert sample["cs"] == pytest.approx(8 * window, rel=1e-6)
            assert sample["d"] > 0
            # No workload telemetry -> goodput weight defaults to 1.0.
            assert sample["gcs"] == pytest.approx(sample["cs"], rel=1e-6)

            # Idempotent: the cursor advanced past the job's window, so a
            # second tick adds nothing.
            assert await usage_service.meter(api.db) == 0
            again = await api.db.fetchone(
                "SELECT SUM(chip_seconds) AS cs FROM usage_samples"
            )
            assert again["cs"] == pytest.approx(sample["cs"], rel=1e-9)

    async def test_usage_api_rows_totals_and_since(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("acct-api", "v5e-8")
            )
            await drive(api.db)
            await usage_service.meter(api.db)

            data = await api.post("/api/usage/get", {})
            assert len(data["runs"]) == 1
            row = data["runs"][0]
            assert row["project"] == "main"
            assert row["run_name"] == "acct-api"
            assert row["user"] == "admin"
            assert row["chip_seconds"] > 0
            assert row["dollars"] > 0
            assert row["queue_wait_s"] is not None and row["queue_wait_s"] >= 0
            totals = data["projects"]
            assert totals[0]["project"] == "main" and totals[0]["runs"] == 1
            assert totals[0]["chip_seconds"] == pytest.approx(row["chip_seconds"])
            assert data["fleet"]["total_chips"] >= 0

            # A since filter past every bucket excludes the ledger rows but
            # still reports the fleet summary.
            far = "2999-01-01T00:00:00+00:00"
            later = await api.post("/api/usage/get", {"since": far})
            assert later["runs"] == [] and later["since"] == far

            # Unknown project filter is a clean 404, not an empty readout.
            await api.post("/api/usage/get", {"project": "ghost"}, expect=404)


class TestPlacementDecisionLog:
    async def test_unplaceable_run_records_attempt_and_waits(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", _stuck_spec("stuck"))
            await tasks.process_submitted_jobs(api.db)

            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "stuck"}
            )
            attempts = [
                e for e in data["events"] if e["new_status"] == "placement_attempt"
            ]
            assert len(attempts) == 1
            ev = attempts[0]
            assert ev["actor"] == "scheduler"
            assert ev["reason"] == "no_offers"
            assert ev["job_id"] is None
            payload = json.loads(ev["message"])
            assert payload["offers"] == 0
            assert payload["reasons"] == {"no_offers": 1}

            # The run surfaces WHY it waits (ps -v WAITING column source).
            run = await api.post(
                "/api/project/main/runs/get", {"run_name": "stuck"}
            )
            assert run["status"] == "submitted"
            assert run["status_message"] == "waiting: no_offers"

            # Identical consecutive attempts stay silent (per-pass dedup).
            await tasks.process_submitted_jobs(api.db)
            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "stuck"}
            )
            assert (
                len([
                    e for e in data["events"]
                    if e["new_status"] == "placement_attempt"
                ])
                == 1
            )

            # And the pending-reason gauge is live on /metrics.
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            pending = families["dstack_tpu_run_pending_reason"]["samples"]
            assert (
                "dstack_tpu_run_pending_reason",
                {"reason": "no_offers", "run": "stuck"},
                1.0,
            ) in pending
            queued = families["dstack_tpu_project_queued_runs"]["samples"]
            assert (
                "dstack_tpu_project_queued_runs", {"project": "main"}, 1.0
            ) in queued

    async def test_placement_clears_waiting_state(self):
        """A run that eventually places must lose its pending-reason series
        and its WAITING message the moment placement succeeds."""
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("clears", "v5e-8")
            )
            # Fake a stale waiting state from an earlier failed pass.
            usage_service.set_pending(
                "clears", "rid", "main", 0, {"no_offers": 1}
            )
            await api.db.execute(
                "UPDATE runs SET status_message = 'waiting: no_offers'"
                " WHERE run_name = 'clears'"
            )
            await tasks.process_submitted_jobs(api.db)
            assert usage_service.pending_snapshot() == []
            run = await api.post(
                "/api/project/main/runs/get", {"run_name": "clears"}
            )
            assert run["status_message"] is None

    async def test_meter_prunes_stale_pending_entries(self):
        """Defensive prune: a registry entry whose run is no longer waiting
        (e.g. stopped outside the placement pass) dies on the next tick."""
        async with api_server() as api:
            await setup_mock_backend(api)
            usage_service.set_pending("ghost", "rid", "main", 0, {"no_offers": 1})
            await usage_service.meter(api.db)
            assert usage_service.pending_snapshot() == []

    def test_primary_reason_precedence(self):
        # Highest count wins; ties break in taxonomy precedence order.
        assert (
            usage_service.set_pending(
                "r", "id", "p", 3, {"no_capacity": 2, "slice_busy": 1}
            )
            == "no_capacity"
        )
        assert (
            usage_service.set_pending(
                "r", "id", "p", 3, {"breaker_open": 1, "no_offers": 1}
            )
            == "breaker_open"
        )
        usage_service.reset()


class TestFleetGauges:
    async def test_cold_scrape_renders_families(self):
        """A cold server advertises every fleet-accounting family with typed
        headers; dstack_tpu_fleet_chips emits all three states at 0 so
        dashboards discover the state label set before any instance exists."""
        async with api_server() as api:
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
        chips = families["dstack_tpu_fleet_chips"]
        assert chips["type"] == "gauge"
        assert {labels["state"] for _, labels, _ in chips["samples"]} == {
            "allocated", "idle", "provisioning",
        }
        assert all(v == 0.0 for _, _, v in chips["samples"])
        assert families["dstack_tpu_project_allocated_chips"]["type"] == "gauge"
        assert families["dstack_tpu_project_queued_runs"]["type"] == "gauge"
        assert families["dstack_tpu_project_chip_seconds_total"]["type"] == "counter"
        assert families["dstack_tpu_run_pending_reason"]["type"] == "gauge"

    async def test_fleet_and_project_series_after_run(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("gauges", "v5e-8")
            )
            await drive(api.db)
            await usage_service.meter(api.db)

            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            # The run's slice stays pooled after the run: 8 chips, none busy.
            by_state = {
                labels["state"]: v
                for _, labels, v in families["dstack_tpu_fleet_chips"]["samples"]
            }
            assert sum(by_state.values()) == 8.0
            assert by_state["allocated"] == 0.0
            # The ledger backs the per-project counter.
            counter = families["dstack_tpu_project_chip_seconds_total"]["samples"]
            assert len(counter) == 1
            name, labels, value = counter[0]
            assert labels == {"project": "main"} and value > 0

            summary = await usage_service.fleet_summary(api.db)
            assert summary["total_chips"] == 8
            assert summary["queued_runs"] == 0
            assert summary["dollars_per_hour"] > 0


class TestSweepHygiene:
    async def test_run_delete_sweeps_ledger_and_pending(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post(
                "/api/project/main/runs/submit", tpu_task_spec("swept", "v5e-8")
            )
            await drive(api.db)
            await usage_service.meter(api.db)
            rows = await api.db.fetchall("SELECT * FROM usage_samples")
            assert rows
            usage_service.set_pending("swept", "rid", "main", 0, {"no_offers": 1})

            await api.post(
                "/api/project/main/runs/delete", {"runs_names": ["swept"]}
            )
            assert await api.db.fetchall("SELECT * FROM usage_samples") == []
            assert usage_service.pending_snapshot() == []

            # The per-project counter series disappears on the next scrape.
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            assert families["dstack_tpu_project_chip_seconds_total"]["samples"] == []

    async def test_project_delete_sweeps_ledger_and_pending(self):
        async with api_server() as api:
            await api.post("/api/projects/create", {"project_name": "acct2"})
            proj = await api.db.fetchone(
                "SELECT id FROM projects WHERE name = 'acct2'"
            )
            await api.db.execute(
                "INSERT INTO usage_samples (run_id, project_id, user_id, bucket,"
                " chip_seconds, dollars, goodput_chip_seconds, last_sampled_at)"
                " VALUES ('r1', ?, NULL, '2026-01-01T00:00:00+00:00',"
                " 10, 0.1, 10, '2026-01-01T00:30:00+00:00')",
                (proj["id"],),
            )
            usage_service.set_pending("p2-run", "r1", "acct2", 0, {"no_offers": 1})

            await api.post("/api/projects/delete", {"projects_names": ["acct2"]})
            assert await api.db.fetchall("SELECT * FROM usage_samples") == []
            assert usage_service.pending_snapshot() == []
