"""HF fine-tuning sugar (ref api/huggingface/__init__.py:6) and the env-gated
error-report hook (ref server/app.py:81-89 Sentry init)."""

import asyncio
import json
import logging

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from dstack_tpu.api.huggingface import SFTFineTuningTask
from dstack_tpu.core.models.configurations import TaskConfiguration
from dstack_tpu.server.services import error_reporting


class TestSftSugar:
    def test_builds_a_valid_task_configuration(self):
        task = SFTFineTuningTask(
            model_name="google/gemma-2b",
            dataset_name="tatsu-lab/alpaca",
            env={"HF_TOKEN": "hf_x"},
            tpu="v5litepod-8",
            new_model_name="me/gemma-2b-alpaca",
            max_seq_length=2048,
            max_steps=100,
        )
        assert isinstance(task, TaskConfiguration)
        assert task.type == "task"
        joined = "\n".join(task.commands)
        assert "trl sft" in joined
        assert "--model_name_or_path google/gemma-2b" in joined
        assert "--dataset_name tatsu-lab/alpaca" in joined
        assert "--use_peft" in joined  # LoRA default
        assert "--bf16 True" in joined  # MXU-native dtype default
        assert "--push_to_hub" in joined
        assert "--hub_model_id me/gemma-2b-alpaca" in joined
        assert "--max_steps 100" in joined
        assert task.resources.tpu is not None
        # Round-trips through the submit payload shape.
        spec = {"run_name": "sft", "configuration": json.loads(task.model_dump_json())}
        assert spec["configuration"]["type"] == "task"

    def test_requires_hf_token(self):
        with pytest.raises(ValueError, match="HF_TOKEN"):
            SFTFineTuningTask("m", "d", env={})

    def test_wandb_requires_key(self):
        with pytest.raises(ValueError, match="WANDB_API_KEY"):
            SFTFineTuningTask("m", "d", env={"HF_TOKEN": "x"}, report_to="wandb")
        task = SFTFineTuningTask(
            "m", "d", env={"HF_TOKEN": "x", "WANDB_API_KEY": "y"}, report_to="wandb"
        )
        assert "--report_to wandb" in "\n".join(task.commands)

    def test_no_lora_drops_peft_flags(self):
        task = SFTFineTuningTask("m", "d", env={"HF_TOKEN": "x"}, lora=False)
        assert "--use_peft" not in "\n".join(task.commands)


class TestErrorReporting:
    async def test_error_records_reach_the_collector(self, monkeypatch):
        received = []

        async def collect(request):
            received.append(await request.json())
            return web.json_response({"ok": True})

        app = web.Application()
        app.router.add_post("/errors", collect)
        server = TestServer(app)
        await server.start_server()
        url = f"http://127.0.0.1:{server.port}/errors"
        monkeypatch.setenv("DSTACK_TPU_ERROR_REPORT_URL", url)
        monkeypatch.delenv("DSTACK_TPU_SENTRY_DSN", raising=False)
        try:
            assert error_reporting.setup() == "http"
            log = logging.getLogger("dstack_tpu.test.reporting")
            try:
                raise RuntimeError("scheduler exploded")
            except RuntimeError:
                log.exception("unhandled server error: GET /api/x")
            log.info("informational — must NOT be reported")
            for _ in range(100):
                if received:
                    break
                await asyncio.sleep(0.05)
            assert len(received) == 1
            payload = received[0]
            assert payload["message"] == "unhandled server error: GET /api/x"
            assert "scheduler exploded" in payload["traceback"]
            assert payload["level"] == "ERROR"
            assert payload["release"]
        finally:
            error_reporting.teardown()
            await server.close()

    async def test_unconfigured_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("DSTACK_TPU_ERROR_REPORT_URL", raising=False)
        monkeypatch.delenv("DSTACK_TPU_SENTRY_DSN", raising=False)
        assert error_reporting.setup() is None

    async def test_dead_collector_never_breaks_logging(self, monkeypatch):
        monkeypatch.setenv("DSTACK_TPU_ERROR_REPORT_URL", "http://127.0.0.1:1/x")
        monkeypatch.delenv("DSTACK_TPU_SENTRY_DSN", raising=False)
        try:
            assert error_reporting.setup() == "http"
            log = logging.getLogger("dstack_tpu.test.reporting2")
            for _ in range(10):
                log.error("boom %d", 1)
            await asyncio.sleep(0.2)  # pump thread must survive failures
        finally:
            error_reporting.teardown()
