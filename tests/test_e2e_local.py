"""True end-to-end: real server + real scheduler loops + real native runner binary.

The local backend spawns dstack-tpu-runner (C++) on an ephemeral port; the control plane
drives it over actual HTTP — the same protocol used against cloud instances. Parity:
the reference has no dockerized e2e in CI (SURVEY §4); this is stronger."""

import asyncio
import json

import pytest

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.common import api_server

pytestmark = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)


async def _drive_until(api, run_name, want_status, timeout=30.0):
    deadline = asyncio.get_event_loop().time() + timeout
    run = None
    while asyncio.get_event_loop().time() < deadline:
        await tasks.process_submitted_jobs(api.db)
        await tasks.process_running_jobs(api.db)
        await tasks.process_terminating_jobs(api.db)
        await tasks.process_runs(api.db)
        await tasks.process_instances(api.db)
        run = await api.post(f"/api/project/main/runs/get", {"run_name": run_name})
        if run["status"] == want_status:
            return run
        if run["status"] in ("failed", "terminated", "done"):
            break
        await asyncio.sleep(0.2)
    raise AssertionError(f"run {run_name} ended at {run and run['status']}, wanted {want_status}")


class TestE2ELocal:
    async def test_task_runs_on_real_runner(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                spec = {
                    "run_spec": {
                        "run_name": "e2e",
                        "configuration": {
                            "type": "task",
                            "commands": [
                                "echo e2e-marker-$((40+2))",
                                "python3 -c 'import os; print(\"rank\", os.environ[\"DSTACK_NODE_RANK\"])'",
                            ],
                            "env": {"MY_VAR": "my-value"},
                        },
                    }
                }
                await api.post("/api/project/main/runs/submit", spec)
                run = await _drive_until(api, "e2e", "done")
                assert run["status"] == "done"

                job = await api.db.fetchone("SELECT * FROM jobs")
                events = logs_service.get_log_storage().poll_logs(
                    job["project_id"], "e2e", job["id"]
                )
                text = "".join(e.message for e in events)
                assert "e2e-marker-42" in text
                assert "rank 0" in text

                # Slice returned to the pool; expire it and confirm the runner process
                # is torn down.
                inst = await api.db.fetchone("SELECT * FROM instances")
                assert inst["status"] == "idle"
                jpd = json.loads(inst["job_provisioning_data"])
                pid = json.loads(jpd["backend_data"])["runner_pid"]
                import os

                os.kill(pid, 0)  # alive
                await api.db.execute(
                    "UPDATE instances SET idle_since = '2020-01-01T00:00:00+00:00'"
                )
                for _ in range(4):
                    await tasks.process_instances(api.db)
                inst = await api.db.fetchone("SELECT * FROM instances")
                assert inst["status"] == "terminated"
                await asyncio.sleep(0.3)
                with pytest.raises(ProcessLookupError):
                    os.kill(pid, 0)
        finally:
            logs_service.set_log_storage(None)

    async def test_failing_task_reports_exit_status(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                spec = {
                    "run_spec": {
                        "run_name": "e2e-fail",
                        "configuration": {
                            "type": "task",
                            "commands": ["echo about-to-fail", "exit 7"],
                        },
                    }
                }
                await api.post("/api/project/main/runs/submit", spec)
                with pytest.raises(AssertionError):
                    await _drive_until(api, "e2e-fail", "done", timeout=15)
                run = await api.post("/api/project/main/runs/get", {"run_name": "e2e-fail"})
                assert run["status"] == "failed"
                sub = run["jobs"][0]["job_submissions"][-1]
                assert sub["exit_status"] == 7
                assert sub["termination_reason"] == "container_exited_with_error"
        finally:
            logs_service.set_log_storage(None)

    async def test_stop_kills_running_job(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                spec = {
                    "run_spec": {
                        "run_name": "e2e-stop",
                        "configuration": {"type": "task", "commands": ["sleep 300"]},
                    }
                }
                await api.post("/api/project/main/runs/submit", spec)
                await _drive_until(api, "e2e-stop", "running")
                await api.post("/api/project/main/runs/stop", {"runs_names": ["e2e-stop"]})
                run = await _drive_until(api, "e2e-stop", "terminated")
                assert run["status"] == "terminated"
        finally:
            logs_service.set_log_storage(None)
