"""Request metrics middleware, utilization-kill, local offer filtering, tunnel
reaping.

Parity: reference app.py:81-89 (request duration middleware),
process_running_jobs.py:764 (utilization enforcement — TPU duty-cycle here)."""

import json
import os

import pytest

from dstack_tpu.core.models.runs import JobProvisioningData
from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import metrics as metrics_service
from dstack_tpu.server.services import request_metrics
from dstack_tpu.server.services.runner import ssh as runner_ssh
from dstack_tpu.utils.common import now_utc, to_iso
from tests.common import api_server


class TestRequestMetrics:
    async def test_middleware_counts_and_exports(self):
        request_metrics.reset()
        async with api_server() as api:
            await api.post("/api/project/main/runs/list")
            await api.post("/api/project/main/runs/list")
            await api.post("/api/project/main/runs/get", {"run_name": "ghost"}, expect=404)
            snap = {k: c for k, c, _ in request_metrics.snapshot()}
            assert snap[("POST", "/api/project/{project_name}/runs/list", 200)] == 2
            assert snap[("POST", "/api/project/{project_name}/runs/get", 404)] == 1

            resp = await api.client.get("/metrics")
            text = await resp.text()
            assert "dstack_tpu_http_requests_total{" in text
            assert 'route="/api/project/{project_name}/runs/list"' in text
            assert "dstack_tpu_http_request_duration_seconds_total" in text


class TestUtilizationPolicy:
    async def test_low_duty_cycle_terminates_run(self):
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await api.db.execute(
                "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at,"
                " status, run_spec) VALUES ('r1', ?, ?, 'hot', '2026-01-01', 'running', '{}')",
                (proj["id"], proj["owner_id"]),
            )
            spec = {
                "job_name": "hot-0-0",
                "image_name": "x",
                "requirements": {"resources": {}},
                "utilization_policy": {"min_tpu_utilization": 40, "time_window": "1m"},
            }
            await api.db.execute(
                "INSERT INTO jobs (id, project_id, run_id, run_name, job_spec, status,"
                " submitted_at) VALUES ('j1', ?, 'r1', 'hot', ?, 'running', '2026-01-01')",
                (proj["id"], json.dumps(spec)),
            )
            # 70s of samples at 5% duty — below the 40% floor for the window.
            import datetime

            for age in (58, 30, 5):
                ts = to_iso(now_utc() - datetime.timedelta(seconds=age))
                await api.db.execute(
                    "INSERT INTO job_metrics_points (job_id, timestamp, cpu_usage_micro,"
                    " memory_usage_bytes, tpu) VALUES ('j1', ?, 0, 0, ?)",
                    (ts, json.dumps({"duty_cycle_percent": 5.0})),
                )
            await metrics_service.enforce_utilization_policies(api.db)
            run = await api.db.fetchone("SELECT * FROM runs WHERE id = 'r1'")
            assert run["status"] == "terminating"
            assert run["termination_reason"] == "terminated_due_to_utilization_policy"

    async def test_busy_tpu_not_killed(self):
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await api.db.execute(
                "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at,"
                " status, run_spec) VALUES ('r2', ?, ?, 'busy', '2026-01-01', 'running', '{}')",
                (proj["id"], proj["owner_id"]),
            )
            spec = {
                "job_name": "busy-0-0",
                "image_name": "x",
                "requirements": {"resources": {}},
                "utilization_policy": {"min_tpu_utilization": 40, "time_window": "1m"},
            }
            await api.db.execute(
                "INSERT INTO jobs (id, project_id, run_id, run_name, job_spec, status,"
                " submitted_at) VALUES ('j2', ?, 'r2', 'busy', ?, 'running', '2026-01-01')",
                (proj["id"], json.dumps(spec)),
            )
            import datetime

            # One high sample inside the window keeps the run alive; missing TPU
            # data must also never kill.
            for age, duty in ((58, 5.0), (30, 85.0), (5, 5.0)):
                ts = to_iso(now_utc() - datetime.timedelta(seconds=age))
                await api.db.execute(
                    "INSERT INTO job_metrics_points (job_id, timestamp, cpu_usage_micro,"
                    " memory_usage_bytes, tpu) VALUES ('j2', ?, 0, 0, ?)",
                    (ts, json.dumps({"duty_cycle_percent": duty})),
                )
            await metrics_service.enforce_utilization_policies(api.db)
            run = await api.db.fetchone("SELECT * FROM runs WHERE id = 'r2'")
            assert run["status"] == "running"


class TestLocalOfferFiltering:
    async def test_oversized_request_gets_no_local_offer(self):
        from dstack_tpu.backends.local import LocalCompute
        from dstack_tpu.core.models.resources import ResourcesSpec
        from dstack_tpu.core.models.runs import Requirements

        compute = LocalCompute()
        cpus = os.cpu_count() or 1
        huge = Requirements(resources=ResourcesSpec(cpu=cpus * 10, memory="4096GB"))
        assert await compute.get_offers(huge) == []
        sane = Requirements(resources=ResourcesSpec(cpu=1, memory="1GB"))
        offers = await compute.get_offers(sane)
        assert len(offers) == 1
        assert offers[0].instance.resources.memory_gb > 0


class TestTunnelReaping:
    async def test_stale_tunnels_closed(self):
        class FakeTunnel:
            def __init__(self):
                self.closed = False
                self.is_open = True
                self.forwards = []

            async def close(self):
                self.closed = True

        live = FakeTunnel()
        stale = FakeTunnel()
        stale_app = FakeTunnel()
        runner_ssh._pool.clear()
        runner_ssh._pool["inst-live:0"] = live
        runner_ssh._pool["inst-gone:0"] = stale
        runner_ssh._pool["inst-gone:0:app8000"] = stale_app
        try:
            await runner_ssh.reap_tunnels({"inst-live:0"})
            assert not live.closed
            assert stale.closed and stale_app.closed
            assert set(runner_ssh._pool) == {"inst-live:0"}
        finally:
            runner_ssh._pool.clear()
