#!/usr/bin/env python3
"""Fake OpenSSH client for tests: a real TCP forwarder behind the ssh CLI surface.

Supports the subset SSHTunnel/ssh_exec emit:
  ssh [-o k=v]... [-p port] [-i file] [-J jump] -N -L 127.0.0.1:L:H:P... user@host
  ssh [options] user@host <command>

Tunnel mode (-N -L): listens on each local port and forwards byte streams to the
target given by FAKE_SSH_FORWARD_TARGET (host:port) — standing in for "the runner
port on the SSH destination". This proves control-plane traffic actually rides the
tunnel: tests give the destination an unresolvable hostname, so only the tunnel path
can reach the runner.

Exec mode prints FAKE_SSH_EXEC_OUTPUT and exits 0 (provisioning tests patch
ssh_exec at the Python level instead; this keeps the binary surface honest).
"""

import asyncio
import os
import sys


def parse(argv):
    forwards, dest, command, n_flag = [], None, None, False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-o", "-p", "-i", "-J"):
            i += 2
            continue
        if a == "-N":
            n_flag = True
            i += 1
            continue
        if a == "-L":
            spec = argv[i + 1]
            parts = spec.split(":")
            # [bind:]L:H:P
            local = int(parts[1] if len(parts) == 4 else parts[0])
            forwards.append(local)
            i += 2
            continue
        if dest is None:
            dest = a
        else:
            command = " ".join(argv[i:])
            break
        i += 1
    return forwards, dest, command, n_flag


async def pump(reader, writer):
    try:
        while True:
            data = await reader.read(65536)
            if not data:
                break
            writer.write(data)
            await writer.drain()
    except (ConnectionError, asyncio.CancelledError):
        pass
    finally:
        try:
            writer.close()
        except Exception:
            pass


async def serve_forward(local_port, target_host, target_port):
    async def handle(reader, writer):
        try:
            r2, w2 = await asyncio.open_connection(target_host, target_port)
        except OSError:
            writer.close()
            return
        await asyncio.gather(pump(reader, w2), pump(r2, writer))

    server = await asyncio.start_server(handle, "127.0.0.1", local_port)
    async with server:
        await server.serve_forever()


def main():
    forwards, dest, command, n_flag = parse(sys.argv[1:])
    if command is not None:
        sys.stdout.write(os.environ.get("FAKE_SSH_EXEC_OUTPUT", ""))
        return 0
    if n_flag and forwards:
        target = os.environ.get("FAKE_SSH_FORWARD_TARGET", "")
        if not target:
            sys.stderr.write("fake_ssh: FAKE_SSH_FORWARD_TARGET not set\n")
            return 255
        host, _, port = target.rpartition(":")

        async def run_all():
            await asyncio.gather(*(serve_forward(lp, host, int(port)) for lp in forwards))

        try:
            asyncio.run(run_all())
        except KeyboardInterrupt:
            pass
        return 0
    sys.stderr.write("fake_ssh: unsupported invocation\n")
    return 255


if __name__ == "__main__":
    sys.exit(main())
