"""MoE (expert parallel over `ep`) and pipeline parallelism (`pp`).

Runs on the virtual 8-device CPU mesh (conftest). Correctness bar: routing
respects capacity, the sharded MoE step compiles and trains, and the
pipelined forward/backward agree numerically with the dense model — same
params, same block code (model.transformer_block)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Pin eager/non-mesh computation to CPU: the repo's dev chip (axon) is the
# default device and its fp32 matmuls run bf16 passes, which would make the
# dense-vs-pipelined comparisons fail on precision, not correctness
# (same pattern as tests/test_workloads.py).
jax.config.update("jax_default_device", jax.devices("cpu")[0])

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import moe as moe_lib
from dstack_tpu.workloads import pipeline as pp_lib
from dstack_tpu.workloads.config import get_config


def tiny_moe(**over):
    cfg = moe_lib.MOE_PRESETS["moe_test"]
    over.setdefault("max_seq_len", 64)
    return dataclasses.replace(cfg, **over)


class TestRouting:
    def test_capacity_and_gates(self):
        g, s, e, k, cap = 2, 16, 4, 2, 6
        logits = jax.random.normal(jax.random.PRNGKey(0), (g, s, e))
        combine, dispatch, aux = moe_lib.top_k_routing(logits, k, cap)
        assert combine.shape == (g, s, e, cap)
        # No expert ever exceeds its capacity slots, and each (expert, slot)
        # is claimed by at most one token.
        per_slot = jnp.sum(dispatch, axis=1)  # [G, E, C]
        assert int(jnp.max(per_slot)) <= 1
        assert int(jnp.max(jnp.sum(dispatch, axis=(1, 3)))) <= cap
        # A token's combine weights sum to <= 1 (== 1 when nothing dropped).
        token_mass = jnp.sum(combine, axis=(2, 3))
        assert float(jnp.max(token_mass)) <= 1.0 + 1e-5
        assert float(jnp.min(token_mass)) >= 0.0
        # Uniform-random logits are near-balanced: aux ~ 1.0 (its minimum).
        assert 0.8 <= float(aux) <= 1.6

    def test_tight_capacity_drops_tokens(self):
        g, s, e, k = 1, 32, 4, 2
        # Everyone wants expert 0 -> capacity 2 must drop most tokens there.
        logits = jnp.zeros((g, s, e)).at[..., 0].set(10.0)
        combine, dispatch, aux = moe_lib.top_k_routing(logits, k, 2)
        assert int(jnp.sum(dispatch[..., 0, :])) == 2  # exactly capacity
        assert float(aux) > 1.5  # imbalance is penalized


class TestMoeModel:
    def test_single_device_forward_and_loss(self):
        cfg = tiny_moe()
        params = moe_lib.init_moe_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        logits, aux = moe_lib.forward(params, tokens, cfg)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert bool(jnp.isfinite(logits).all())
        loss = moe_lib.loss_fn(params, tokens, tokens, cfg)
        assert bool(jnp.isfinite(loss))

    def test_param_count_vs_active(self):
        cfg = tiny_moe()
        assert cfg.num_params() > cfg.active_params()  # MoE's whole point

    def test_chunked_loss_matches_full(self):
        cfg = tiny_moe()
        params = moe_lib.init_moe_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        full = moe_lib.loss_fn(params, tokens, tokens, cfg)
        chunked = moe_lib.loss_fn(
            params, tokens, tokens, dataclasses.replace(cfg, loss_chunk=8)
        )
        assert abs(float(full) - float(chunked)) < 1e-3

    def test_expert_parallel_train_step(self):
        import optax

        cfg = tiny_moe()
        mesh = moe_lib.make_moe_mesh(dp=2, fsdp=1, ep=2, tp=2, sp=1,
                                     devices=jax.devices("cpu")[:8])
        assert dict(mesh.shape) == {"dp": 2, "fsdp": 1, "ep": 2, "tp": 2, "sp": 1}
        optimizer = optax.adamw(1e-3)
        with mesh:
            params = moe_lib.shard_moe_params(
                moe_lib.init_moe_params(cfg, jax.random.PRNGKey(0)), mesh
            )
            # Experts really are sharded over ep: each shard holds E/ep experts.
            w = params["w_gate"]
            e_shard = w.sharding.shard_shape(w.shape)[1]
            assert e_shard == cfg.n_experts // 2
            opt_state = optimizer.init(params)
            step = moe_lib.make_moe_train_step(cfg, optimizer, mesh)
            bspec = jax.sharding.NamedSharding(mesh, moe_lib.MOE_BATCH)
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size),
                bspec,
            )
            losses = []
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state, tokens, tokens)
                losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # it learns the (repeated) batch


class TestPipeline:
    def _cfg(self):
        # fp32 end-to-end so pipelined vs dense comparison is tight.
        return get_config(
            "test", n_layers=4, dtype="float32", param_dtype="float32",
            remat=False, max_seq_len=32,
        )

    def test_pipelined_forward_matches_dense(self):
        cfg = self._cfg()
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)

        dense = model_lib.forward(params, tokens, cfg)

        mesh = pp_lib.make_pp_mesh(dp=2, pp=2, devices=jax.devices("cpu")[:4])
        with mesh:
            sharded = pp_lib.shard_params_pp(params, mesh)
            piped = jax.jit(
                lambda p, tk: pp_lib.pipelined_forward(p, tk, cfg, mesh, n_micro=2)
            )(sharded, tokens)
        np.testing.assert_allclose(np.asarray(piped), np.asarray(dense),
                                   rtol=1e-4, atol=1e-4)

    def test_pipelined_backward_matches_dense(self):
        # remat=True here: the checkpointed stage scan must stay numerically
        # identical (and it is the configuration pp exists to serve).
        cfg = dataclasses.replace(self._cfg(), remat=True)
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)

        dense_loss, dense_grads = jax.value_and_grad(model_lib.loss_fn)(
            params, tokens, tokens, cfg
        )
        mesh = pp_lib.make_pp_mesh(dp=2, pp=2, devices=jax.devices("cpu")[:4])
        with mesh:
            sharded = pp_lib.shard_params_pp(params, mesh)
            piped_loss, piped_grads = jax.jit(
                jax.value_and_grad(
                    lambda p, tk, tg: pp_lib.pipelined_loss_fn(
                        p, tk, tg, cfg, mesh, n_micro=2
                    )
                )
            )(sharded, tokens, tokens)
        assert abs(float(piped_loss) - float(dense_loss)) < 1e-4
        for key in ("wq", "w_down", "lm_head", "embed"):
            np.testing.assert_allclose(
                np.asarray(piped_grads[key]), np.asarray(dense_grads[key]),
                rtol=2e-3, atol=2e-4,
            )

    def test_four_stage_pipeline(self):
        cfg = self._cfg()
        params = model_lib.init_params(cfg, jax.random.PRNGKey(2))
        tokens = jax.random.randint(jax.random.PRNGKey(3), (6, 16), 0, cfg.vocab_size)
        dense = model_lib.forward(params, tokens, cfg)
        mesh = pp_lib.make_pp_mesh(dp=2, pp=4, devices=jax.devices("cpu")[:8])
        with mesh:
            sharded = pp_lib.shard_params_pp(params, mesh)
            piped = pp_lib.pipelined_forward(sharded, tokens, cfg, mesh, n_micro=3)
        np.testing.assert_allclose(np.asarray(piped), np.asarray(dense),
                                   rtol=1e-4, atol=1e-4)

    def test_bad_shapes_rejected(self):
        cfg = self._cfg()
        mesh = pp_lib.make_pp_mesh(dp=2, pp=4, devices=jax.devices("cpu")[:8])
        params = model_lib.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="not divisible"):
            pp_lib.pipelined_forward(
                params, jnp.zeros((5, 16), jnp.int32), cfg, mesh, n_micro=2
            )
        cfg3 = dataclasses.replace(cfg, n_layers=3)
        with pytest.raises(ValueError, match="n_layers"):
            pp_lib.pipelined_forward(
                model_lib.init_params(cfg3, jax.random.PRNGKey(0)),
                jnp.zeros((4, 16), jnp.int32), cfg3, mesh, n_micro=2,
            )
