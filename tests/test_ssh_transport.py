"""SSH tunnel transport + SSH fleet provisioning tests.

The tunnel tests run a REAL forwarder: tests/fake_ssh.py stands in for OpenSSH and
actually proxies TCP, while the runner stands behind an unresolvable hostname — so a
passing healthcheck proves the scheduler reached the runner ONLY via the tunnel
(VERDICT r1 item 3). The fleet tests drive the real process_instances loop with the
SSH executor faked at the Python seam, spawning the real C++ runner."""

from __future__ import annotations

import asyncio
import json
import os
import shlex
import subprocess
import sys
import tempfile
from pathlib import Path

import pytest

from dstack_tpu.backends.remote import provisioning
from dstack_tpu.core.models.configurations import SSHHostParams
from dstack_tpu.core.models.instances import InstanceType, HostResources
from dstack_tpu.core.models.runs import JobProvisioningData
from dstack_tpu.core.services.ssh import tunnel as tunnel_mod
from dstack_tpu.core.services.ssh.tunnel import Forward, SSHTunnel, allocate_local_port
from dstack_tpu.server.services.runner import ssh as runner_ssh
from dstack_tpu.server.services.runner.client import get_runner_client
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.common import api_server, drive

FAKE_SSH = str(Path(__file__).parent / "fake_ssh.py")


def spawn_runner(tmp: str):
    """Start the real C++ runner on an ephemeral port; returns (proc, port)."""
    binary = find_runner_binary()
    assert binary, "runner binary must build"
    proc = subprocess.Popen(
        [binary, "--host", "127.0.0.1", "--port", "0", "--base-dir", tmp],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )
    import re

    for _ in range(40):
        line = proc.stdout.readline().decode(errors="replace")
        m = re.search(r"listening on [\d.]+:(\d+)", line)
        if m:
            return proc, int(m.group(1))
    raise AssertionError("runner did not report a port")


@pytest.fixture()
def real_runner(tmp_path):
    proc, port = spawn_runner(str(tmp_path))
    yield port
    proc.terminate()
    proc.wait(timeout=5)


@pytest.fixture()
def fake_ssh_env(monkeypatch, real_runner):
    monkeypatch.setenv("DSTACK_TPU_SSH_BINARY", FAKE_SSH)
    monkeypatch.setenv("FAKE_SSH_FORWARD_TARGET", f"127.0.0.1:{real_runner}")
    yield real_runner


class TestSSHTunnel:
    async def test_tunnel_forwards_real_traffic(self, fake_ssh_env):
        port = allocate_local_port()
        tunnel = SSHTunnel(
            hostname="tpu-host.invalid",  # unresolvable: only the tunnel can reach it
            username="root",
            forwards=[Forward(port, "127.0.0.1", 10999)],
        )
        async with tunnel:
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://127.0.0.1:{port}/api/healthcheck") as resp:
                    assert resp.status == 200
                    body = await resp.json()
                    assert "status" in body or body

    async def test_tunnel_command_shape(self):
        t = SSHTunnel(
            hostname="h",
            username="u",
            port=2222,
            identity_file="/id",
            forwards=[Forward(1234, "127.0.0.1", 10999)],
        )
        cmd = t.command("ssh")
        joined = " ".join(cmd)
        assert "-N" in cmd
        assert "-L 127.0.0.1:1234:127.0.0.1:10999" in joined
        assert "-p 2222" in joined
        assert "-i /id" in joined
        assert joined.endswith("u@h")
        assert "ExitOnForwardFailure=yes" in joined

    async def test_open_fails_fast_on_dead_ssh(self, monkeypatch, tmp_path):
        bad = tmp_path / "ssh"
        bad.write_text("#!/bin/sh\nexit 255\n")
        bad.chmod(0o755)
        monkeypatch.setenv("DSTACK_TPU_SSH_BINARY", str(bad))
        from dstack_tpu.core.errors import SSHError

        t = SSHTunnel(hostname="h", forwards=[Forward(allocate_local_port(), "x", 1)])
        with pytest.raises(SSHError):
            await t.open()


class TestRunnerClientViaTunnel:
    async def test_scheduler_reaches_runner_only_via_tunnel(self, fake_ssh_env):
        """get_runner_client on a cloud jpd must transparently tunnel."""
        jpd = JobProvisioningData(
            backend="gcp",
            instance_type=InstanceType(name="v5e-8", resources=HostResources()),
            instance_id="slice-tunnel-test",
            hostname="tpu-host.invalid",
            region="us-central1",
            worker_num=0,
        )
        client = get_runner_client(jpd, None)
        health = await client.healthcheck()
        assert health is not None
        # Tunnel is pooled: a second client reuses the same local endpoint.
        client2 = get_runner_client(jpd, None)
        await client2._ensure_base()
        await client._ensure_base()
        assert client2.base == client.base
        await runner_ssh.close_tunnel(jpd)

    async def test_local_backend_stays_direct(self):
        jpd = JobProvisioningData(
            backend="local",
            instance_type=InstanceType(name="local", resources=HostResources()),
            instance_id="local-x",
            hostname="127.0.0.1",
            region="local",
            backend_data=json.dumps({"runner_port": 1234}),
        )
        client = get_runner_client(jpd, None)
        assert client.base == "http://127.0.0.1:1234"


class FakeSSHHost:
    """Python-seam fake for provisioning.ssh_exec simulating one remote host."""

    def __init__(self, tmp: str, with_tpu: bool = True):
        self.tmp = tmp
        self.with_tpu = with_tpu
        self.commands = []
        self.authorized_keys = b""
        self.proc = None
        self.port = None

    async def ssh_exec(self, hostname, command, *, input_data=None, **kwargs):
        self.commands.append((hostname, command))
        if "authorized_keys" in command:
            self.authorized_keys += input_data or b""
            return 0, b"", b""
        if "echo cpus=" in command:
            tpu_lines = "accel=4\nlibtpu=/usr/lib/libtpu.so" if self.with_tpu else "accel=0\nlibtpu="
            out = f"cpus=8\nmem_mb=16384\ndisk_gb=100\n{tpu_lines}\nvfio=0\narch=x86_64\n"
            return 0, out.encode(), b""
        if "cat > /usr/local/bin/dstack-tpu-runner" in command:
            Path(self.tmp, "dstack-tpu-runner").write_bytes(input_data or b"")
            os.chmod(Path(self.tmp, "dstack-tpu-runner"), 0o755)
            return 0, b"", b""
        if "nohup" in command or "systemctl" in command:
            self.proc, self.port = spawn_runner(self.tmp)
            return 0, b"", b""
        return 0, b"", b""

    def close(self):
        if self.proc is not None:
            self.proc.terminate()
            self.proc.wait(timeout=5)


class TestSSHFleetProvisioning:
    async def test_ssh_fleet_end_to_end(self, monkeypatch, tmp_path):
        """Fleet with one SSH host: probe -> install -> start -> pooled idle."""
        host = FakeSSHHost(str(tmp_path))
        monkeypatch.setattr(provisioning, "ssh_exec", host.ssh_exec)
        # Direct HTTP after provisioning (no ssh binary for the tunnel pool).
        monkeypatch.setattr(runner_ssh, "tunnel_required", lambda jpd: False)

        async def fake_provision(host_params, runner_binary, **kw):
            jpd, info = await real_provision(host_params, runner_binary, **kw)
            # The fake host's runner listens on an ephemeral port, not 10999.
            data = json.loads(jpd.backend_data)
            data["runner_port"] = host.port
            return jpd.model_copy(
                update={"hostname": "127.0.0.1", "backend_data": json.dumps(data)}
            ), info

        real_provision = provisioning.provision_ssh_host
        from dstack_tpu.server.background import tasks as tasks_mod

        monkeypatch.setattr(
            "dstack_tpu.backends.remote.provisioning.provision_ssh_host", fake_provision
        )

        try:
            async with api_server() as api:
                await api.post(
                    "/api/project/main/fleets/apply_plan",
                    {
                        "spec": {
                            "configuration": {
                                "type": "fleet",
                                "name": "onprem",
                                "ssh_config": {
                                    "user": "root",
                                    "hosts": ["tpu-host-a"],
                                },
                            }
                        }
                    },
                )
                await drive(api.db, passes=6)
                rows = await api.db.fetchall("SELECT * FROM instances WHERE deleted = 0")
                assert len(rows) == 1
                row = rows[0]
                assert row["status"] == "idle", row["status"]
                assert row["backend"] == "ssh"
                itype = InstanceType.model_validate(json.loads(row["instance_type"]))
                assert itype.resources.cpus == 8
                assert itype.resources.tpu is not None and itype.resources.tpu.chips == 4
                # Probe, install, start all went through the SSH seam.
                cmds = " || ".join(c for _, c in host.commands)
                assert "echo cpus=" in cmds
                assert "cat > /usr/local/bin/dstack-tpu-runner" in cmds
                # The server tunnel identity was authorized on the host
                # (ADVICE r2: tunnels authenticate with the server key, not the
                # fleet's provisioning identity).
                assert host.authorized_keys.strip(), "server public key not installed"
                fleet_row = await api.db.fetchone("SELECT * FROM fleets WHERE name = 'onprem'")
                assert fleet_row["status"] == "active"
        finally:
            host.close()

    async def test_ssh_host_unreachable_times_out(self, monkeypatch):
        async def failing_exec(*a, **k):
            from dstack_tpu.core.errors import SSHError

            raise SSHError("connection refused")

        monkeypatch.setattr(provisioning, "ssh_exec", failing_exec)
        monkeypatch.setattr(
            "dstack_tpu.server.settings.PROVISIONING_TIMEOUT", 0.0
        )
        async with api_server() as api:
            await api.post(
                "/api/project/main/fleets/apply_plan",
                {
                    "spec": {
                        "configuration": {
                            "type": "fleet",
                            "name": "bad-fleet",
                            "ssh_config": {"hosts": ["unreachable-host"]},
                        }
                    }
                },
            )
            await drive(api.db, passes=4)
            row = await api.db.fetchone("SELECT * FROM instances WHERE deleted = 0")
            assert row["status"] in ("terminating", "terminated")


class TestHostInfoParsing:
    def test_parse_and_instance_type(self):
        info = provisioning.parse_host_info(
            "cpus=208\nmem_mb=458752\ndisk_gb=500\naccel=4\nvfio=0\nlibtpu=/usr/lib/libtpu.so\narch=x86_64"
        )
        itype = provisioning.host_info_to_instance_type(info)
        assert itype.resources.cpus == 208
        assert itype.resources.tpu.chips == 4
        assert abs(itype.resources.memory_gb - 448.0) < 1

    def test_no_tpu_host(self):
        itype = provisioning.host_info_to_instance_type(
            provisioning.parse_host_info("cpus=4\nmem_mb=8192\naccel=0\nvfio=0")
        )
        assert itype.resources.tpu is None
