"""Workload telemetry pipeline: emitter contract (never blocks / never throws),
goodput ledger, metrics-collection rotation, batched utilization enforcement,
the workload->runner->server flow, and the on-demand profiler — through fakes
at the service layer and through the REAL C++ agent end to end.

The emitter contract tests are the load-bearing ones: telemetry sits inside
the train step, so a full buffer, an unwritable sidecar, or an unserializable
field must degrade to a counter bump, never an exception or a stall."""

import asyncio
import datetime
import json
import os
import time

import pytest

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import metrics as metrics_service
from dstack_tpu.utils.common import now_utc, to_iso
from dstack_tpu.utils.runner_binary import find_runner_binary
from dstack_tpu.workloads.telemetry import NullEmitter, TelemetryEmitter
from tests.common import api_server
from tests.test_run_events import parse_exposition


def _iso(base, off: float) -> str:
    return to_iso(base + datetime.timedelta(seconds=off))


class FakeProfiler:
    def __init__(self):
        self.started_dirs = []
        self.stopped = 0

    def start(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        with open(os.path.join(logdir, "trace.data"), "w") as f:
            f.write("fake-trace")
        self.started_dirs.append(logdir)

    def stop(self):
        self.stopped += 1


# ---------------------------------------------------------------------------
# Emitter contract


class TestEmitter:
    def test_full_buffer_drops_and_counts(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        # Flush interval far beyond the test: the buffer can only drain via
        # explicit flush, so capacity overflow is deterministic.
        e = TelemetryEmitter(path, capacity=4, flush_interval=3600)
        try:
            for i in range(20):
                e.step(i, 0.01)  # must never raise
            assert e.dropped == 16
            e.flush()
            lines = [json.loads(l) for l in open(path).read().splitlines()]
            steps = [p for p in lines if p["kind"] == "step"]
            assert len(steps) == 4
            # The drop counter itself reached the sidecar as an emitter point.
            emitter_points = [p for p in lines if p["kind"] == "emitter"]
            assert emitter_points and emitter_points[-1]["dropped"] == 16
        finally:
            e.close()

    def test_write_errors_swallowed_and_counted(self, tmp_path):
        # The sidecar path IS a directory: every flush write fails.
        bad = tmp_path / "isdir"
        bad.mkdir()
        e = TelemetryEmitter(str(bad), capacity=64, flush_interval=3600)
        try:
            e.mark("run_start")
            e.step(1, 0.01)
            e.flush()  # must not raise
            assert e.write_errors >= 1
            assert e.dropped >= 2  # the lost batch is counted as dropped
            e.step(2, 0.01)  # emitter still alive after the failure
        finally:
            e.close()

    def test_unserializable_field_never_raises(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        e = TelemetryEmitter(path, flush_interval=3600)
        try:
            circular = {}
            circular["self"] = circular
            e.emit("step", step=1, bad=circular)  # json.dumps raises ValueError
            e.step(2, 0.01)
            e.flush()
            lines = [json.loads(l) for l in open(path).read().splitlines()]
            assert any(p.get("step") == 2 for p in lines)
            assert e.dropped == 1
        finally:
            e.close()

    def test_background_flush_and_close(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        e = TelemetryEmitter(path, flush_interval=0.02)
        e.mark("compile_start")
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline and not os.path.exists(path):
            time.sleep(0.02)
        assert os.path.exists(path), "background thread never flushed"
        e.step(1, 0.5, loss=1.0)
        e.close()
        e.close()  # idempotent
        points = [json.loads(l) for l in open(path).read().splitlines()]
        assert [p["kind"] for p in points] == ["mark", "step"]
        assert points[1]["step_time_s"] == 0.5

    def test_null_emitter_when_env_unset(self, monkeypatch):
        from dstack_tpu.workloads import telemetry as tl

        monkeypatch.delenv(tl.ENV_PATH, raising=False)
        prev = tl.configure(None)
        try:
            e = tl.get_emitter()
            assert isinstance(e, NullEmitter) and not e.enabled
            e.step(1, 0.1)
            e.mark("run_start")
            e.flush()
            e.close()
        finally:
            tl.configure(prev)

    def test_control_file_triggers_profiler(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        prof = FakeProfiler()
        e = TelemetryEmitter(path, flush_interval=0.02, profiler=prof)
        try:
            # The agent's protocol: atomic write of <path>.ctl.
            ctl = path + ".ctl"
            with open(ctl + ".tmp", "w") as f:
                f.write(json.dumps({"id": 1, "cmd": "profile", "seconds": 0.1}))
            os.replace(ctl + ".tmp", ctl)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and prof.stopped == 0:
                time.sleep(0.02)
            assert prof.stopped == 1
            e.flush()
            points = [json.loads(l) for l in open(path).read().splitlines()]
            events = [p.get("event") for p in points if p["kind"] == "mark"]
            assert "profile_start" in events and "profile_end" in events
            end = next(p for p in points if p.get("event") == "profile_end")
            assert end["profile_id"] == 1
            assert end["artifact"] == prof.started_dirs[0]
            assert os.path.exists(os.path.join(end["artifact"], "trace.data"))
            # Same command id again (mtime touch): no re-trigger.
            os.utime(ctl)
            time.sleep(0.2)
            assert prof.stopped == 1
        finally:
            e.close()

    def test_profile_request_mid_capture_queues_not_drops(self, tmp_path):
        """A second request arriving during a capture must run AFTER it, not
        be consumed into the id guard and vanish (the CLI would then wait for
        a profile_end that never comes)."""
        path = str(tmp_path / "t.jsonl")
        prof = FakeProfiler()
        e = TelemetryEmitter(path, flush_interval=0.02, profiler=prof)
        try:
            with open(path + ".ctl", "w") as f:
                f.write(json.dumps({"id": 1, "cmd": "profile", "seconds": 0.3}))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not prof.started_dirs:
                time.sleep(0.01)
            # Capture 1 in flight: overwrite the ctl with request 2.
            with open(path + ".ctl", "w") as f:
                f.write(json.dumps({"id": 2, "cmd": "profile", "seconds": 0.1}))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and prof.stopped < 2:
                time.sleep(0.02)
            assert prof.stopped == 2, "queued capture never ran"
            e.flush()
            points = [json.loads(l) for l in open(path).read().splitlines()]
            ends = [p for p in points if p.get("event") == "profile_end"]
            assert [p["profile_id"] for p in ends] == [1, 2]
        finally:
            e.close()

    def test_profiler_failure_is_counted_not_fatal(self, tmp_path):
        class BrokenProfiler:
            def start(self, logdir):
                raise RuntimeError("no profiler here")

            def stop(self):
                pass

        path = str(tmp_path / "t.jsonl")
        e = TelemetryEmitter(path, flush_interval=0.02, profiler=BrokenProfiler())
        try:
            with open(path + ".ctl", "w") as f:
                f.write(json.dumps({"id": 7, "cmd": "profile", "seconds": 1}))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and e.profile_errors == 0:
                time.sleep(0.02)
            assert e.profile_errors >= 1
            e.step(1, 0.01)  # emitter still functional
            e.flush()
            points = [json.loads(l) for l in open(path).read().splitlines()]
            assert any(p.get("event") == "profile_error" for p in points)
        finally:
            e.close()

    def test_emit_cost_stays_microscopic(self, tmp_path):
        """The <1%-overhead budget: emit() is a dict build + deque append.
        Asserted loosely (50µs/point averaged over 5k) so CI noise can't flake
        it, while a regression to file IO or locking on the hot path fails."""
        e = TelemetryEmitter(str(tmp_path / "t.jsonl"), capacity=10000,
                             flush_interval=3600)
        try:
            t0 = time.perf_counter()
            for i in range(5000):
                e.step(i, 0.001, tokens_per_sec=1.0, loss=0.5, input_wait_s=0.0)
            per_point = (time.perf_counter() - t0) / 5000
            assert per_point < 50e-6, f"emit() costs {per_point * 1e6:.1f}µs"
        finally:
            e.close()


# ---------------------------------------------------------------------------
# Goodput ledger


class TestGoodput:
    def test_compile_stall_debits_goodput(self):
        base = now_utc()
        points = [
            {"ts": _iso(base, 0), "kind": "mark", "event": "run_start"},
            {"ts": _iso(base, 0), "kind": "mark", "event": "compile_start"},
            {"ts": _iso(base, 4), "kind": "mark", "event": "compile_end", "compile_s": 4.0},
        ] + [
            {"ts": _iso(base, 4 + i), "kind": "step", "step": i + 2,
             "step_time_s": 1.0, "input_wait_s": 0.0}
            for i in range(1, 7)
        ]
        ledger = metrics_service.compute_goodput(points)
        assert ledger["compile_s"] == 4.0
        assert ledger["productive_s"] == 6.0
        assert ledger["wall_s"] == 10.0
        assert ledger["ratio"] == 0.6
        # Same steps without the stall: goodput jumps — the stall is debited.
        no_stall = metrics_service.compute_goodput(points[3:])
        assert no_stall["ratio"] > ledger["ratio"]

    def test_input_wait_not_productive(self):
        base = now_utc()
        points = [
            {"ts": _iso(base, i), "kind": "step", "step": i, "step_time_s": 1.0,
             "input_wait_s": 0.4}
            for i in range(1, 6)
        ]
        ledger = metrics_service.compute_goodput(points)
        assert ledger["input_wait_s"] == pytest.approx(2.0)
        assert ledger["productive_s"] == pytest.approx(3.0)

    def test_restart_gap_attributed(self):
        base = now_utc()
        points = [
            {"ts": _iso(base, 0), "kind": "mark", "event": "run_start"},
            {"ts": _iso(base, 1), "kind": "step", "step": 2, "step_time_s": 1.0},
            # 10s of downtime, then the restarted process comes up.
            {"ts": _iso(base, 11), "kind": "mark", "event": "run_start"},
            {"ts": _iso(base, 12), "kind": "step", "step": 2, "step_time_s": 1.0},
        ]
        ledger = metrics_service.compute_goodput(points)
        assert ledger["restart_s"] == pytest.approx(10.0)
        # The restarted process RE-RAN step 2 (no checkpoint): that step is
        # rework, not productive — net forward progress is one step.
        assert ledger["productive_s"] == pytest.approx(1.0)
        assert ledger["rework_s"] == pytest.approx(1.0)
        assert ledger["ratio"] == pytest.approx(1.0 / 12.0, abs=1e-3)

    def test_no_steps_or_no_points_means_no_ratio(self):
        assert metrics_service.compute_goodput([])["ratio"] is None
        base = now_utc()
        marks_only = [
            {"ts": _iso(base, 0), "kind": "mark", "event": "run_start"},
            {"ts": _iso(base, 5), "kind": "engine", "queue_depth": 3},
        ]
        assert metrics_service.compute_goodput(marks_only)["ratio"] is None

    def test_dangling_compile_counts_to_window_edge(self):
        base = now_utc()
        points = [
            {"ts": _iso(base, 0), "kind": "step", "step": 1, "step_time_s": 0.5},
            {"ts": _iso(base, 1), "kind": "mark", "event": "compile_start"},
            {"ts": _iso(base, 9), "kind": "step", "step": 2, "step_time_s": 0.5},
        ]
        ledger = metrics_service.compute_goodput(points)
        assert ledger["compile_s"] == pytest.approx(8.0)


# ---------------------------------------------------------------------------
# Collection rotation (sampling-starvation fix) + batched utilization


async def _insert_running_job(db, proj, run_id, job_id, run_name=None,
                              job_num=0, replica_num=0, spec=None, jpd=True):
    run_name = run_name or run_id
    await db.execute(
        "INSERT OR IGNORE INTO runs (id, project_id, user_id, run_name, submitted_at,"
        " status, run_spec) VALUES (?, ?, ?, ?, '2026-01-01', 'running', '{}')",
        (run_id, proj["id"], proj["owner_id"], run_name),
    )
    jpd_json = None
    if jpd:
        jpd_json = json.dumps(
            {
                "backend": "local",
                "instance_type": {
                    "name": "local",
                    "resources": {"cpus": 1, "memory_gb": 1, "disk_gb": 1},
                },
                "instance_id": job_id,
                "hostname": "127.0.0.1",
                "region": "local",
                "ssh_port": 0,
                "backend_data": json.dumps({"runner_port": 1}),
            }
        )
    await db.execute(
        "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, replica_num,"
        " job_spec, status, submitted_at, job_provisioning_data)"
        " VALUES (?, ?, ?, ?, ?, ?, ?, 'running', '2026-01-01', ?)",
        (job_id, proj["id"], run_id, run_name, job_num, replica_num,
         json.dumps(spec or {"job_name": f"{run_name}-0-0"}), jpd_json),
    )


class TestCollectionRotation:
    async def test_150_running_jobs_fully_rotate(self, monkeypatch):
        """>MAX_JOBS_PER_PASS running jobs: two passes must cover ALL of them
        (the old last_processed_at ordering resampled the same 100 forever)."""
        sampled = []

        class FakeAgent:
            def __init__(self, job_key):
                self.job_key = job_key

            async def metrics(self):
                sampled.append(self.job_key)
                return {
                    "timestamp": to_iso(now_utc()),
                    "cpu_usage_micro": 1,
                    "memory_usage_bytes": 1,
                }

        monkeypatch.setattr(
            metrics_service, "get_runner_client",
            lambda jpd, jrd: FakeAgent(jpd.instance_id),
        )
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            for i in range(150):
                await _insert_running_job(api.db, proj, f"r{i:03d}", f"j{i:03d}")

            n1 = await metrics_service.collect_job_metrics(api.db)
            first = set(sampled)
            assert n1 == metrics_service.MAX_JOBS_PER_PASS == len(first)

            sampled.clear()
            n2 = await metrics_service.collect_job_metrics(api.db)
            second = set(sampled)
            assert n2 == metrics_service.MAX_JOBS_PER_PASS
            # Pass 2 starts with the 50 never-sampled jobs, then wraps to the
            # oldest-sampled — union covers the whole fleet.
            assert first | second == {f"j{i:03d}" for i in range(150)}
            assert len(second - first) == 50

            # Pass 3 keeps rotating (never wedges on one subset).
            sampled.clear()
            await metrics_service.collect_job_metrics(api.db)
            assert len(set(sampled) - second) == 50

    async def test_unreachable_job_rotates_to_back(self, monkeypatch):
        """A dead agent's job must not hold its place at the head of the
        sampling order (cursor advances for picked-but-unreachable too)."""
        calls = []

        class DeadAgent:
            def __init__(self, key):
                self.key = key

            async def metrics(self):
                calls.append(self.key)
                return None

        monkeypatch.setattr(
            metrics_service, "get_runner_client", lambda jpd, jrd: DeadAgent(jpd.instance_id)
        )
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await _insert_running_job(api.db, proj, "ra", "ja")
            await metrics_service.collect_job_metrics(api.db)
            row = await api.db.fetchone("SELECT metrics_sampled_at FROM jobs WHERE id = 'ja'")
            assert row["metrics_sampled_at"] is not None


class TestBatchedUtilization:
    async def test_single_window_query_for_many_jobs(self):
        """The N+1 fix: one grouped query fetches every candidate's window, and
        enforcement behavior is unchanged (breach kills, busy survives)."""
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            policy_spec = {
                "job_name": "x-0-0",
                "image_name": "x",
                "requirements": {"resources": {}},
                "utilization_policy": {"min_tpu_utilization": 40, "time_window": "1m"},
            }
            for i in range(20):
                await _insert_running_job(
                    api.db, proj, f"pr{i}", f"pj{i}", spec=dict(policy_spec), jpd=False
                )
                duty = 5.0 if i < 10 else 90.0  # first 10 runs breach
                for age in (58, 30, 5):
                    ts = to_iso(now_utc() - datetime.timedelta(seconds=age))
                    await api.db.execute(
                        "INSERT INTO job_metrics_points (job_id, timestamp,"
                        " cpu_usage_micro, memory_usage_bytes, tpu)"
                        " VALUES (?, ?, 0, 0, ?)",
                        (f"pj{i}", ts, json.dumps({"duty_cycle_percent": duty})),
                    )

            point_queries = []
            orig_fetchall = api.db.fetchall

            async def counting_fetchall(sql, params=()):
                if "job_metrics_points" in sql:
                    point_queries.append(sql)
                return await orig_fetchall(sql, params)

            api.db.fetchall = counting_fetchall
            try:
                await metrics_service.enforce_utilization_policies(api.db)
            finally:
                api.db.fetchall = orig_fetchall
            assert len(point_queries) == 1, point_queries

            for i in range(20):
                run = await api.db.fetchone("SELECT status FROM runs WHERE id = ?", (f"pr{i}",))
                if i < 10:
                    assert run["status"] == "terminating", f"pr{i} should breach"
                else:
                    assert run["status"] == "running", f"pr{i} should survive"


# ---------------------------------------------------------------------------
# Workload points flow: store -> API -> Prometheus -> sweep


class TestWorkloadFlow:
    async def test_store_query_prometheus_and_delete(self):
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await _insert_running_job(api.db, proj, "wf", "wfj", run_name="wf-run", jpd=False)
            job = await api.db.fetchone("SELECT * FROM jobs WHERE id = 'wfj'")
            base = now_utc() - datetime.timedelta(seconds=30)
            points = [
                {"ts": _iso(base, 0), "kind": "mark", "event": "run_start"},
                {"ts": _iso(base, 0), "kind": "mark", "event": "compile_start"},
                {"ts": _iso(base, 3), "kind": "mark", "event": "compile_end", "compile_s": 3.0},
            ] + [
                {"ts": _iso(base, 3 + i), "kind": "step", "step": i + 1,
                 "step_time_s": 0.8, "tokens_per_sec": 512.0, "mfu": 0.31,
                 "loss": 3.1 - i * 0.1, "input_wait_s": 0.1}
                for i in range(1, 8)
            ] + [
                {"ts": _iso(base, 11), "kind": "engine", "queue_depth": 4,
                 "prefix_hit_rate": 0.8, "spec_accept_rate": 0.5},
                {"ts": _iso(base, 12), "kind": "emitter", "dropped": 2, "write_errors": 0},
                "not-a-dict",  # malformed entries are skipped, not fatal
                {"kind": 123},
            ]
            n = await metrics_service.store_workload_points(api.db, job, points)
            assert n == 12

            res = await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "wf-run"}
            )
            assert res["run_name"] == "wf-run"
            assert res["latest"]["step"] == 8
            assert res["latest"]["mfu"] == 0.31
            assert res["engine"]["queue_depth"] == 4
            assert res["dropped"] == 2
            assert len(res["points"]) == 7
            ledger = res["goodput"]
            assert ledger["compile_s"] == 3.0
            assert ledger["ratio"] is not None
            # compile debited: wall 12s, productive 7*0.8-0.7
            assert ledger["ratio"] < 0.6

            await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "ghost"}, expect=404
            )

            resp = await api.client.get("/metrics")
            text = await resp.text()
            families = parse_exposition(text)  # strict: every family well-formed
            for fam in (
                "dstack_tpu_run_mfu",
                "dstack_tpu_run_tokens_per_sec",
                "dstack_tpu_run_goodput_ratio",
            ):
                samples = families[fam]["samples"]
                assert any(l == {"run": "wf-run"} for _, l, _ in samples), fam
            hist = families["dstack_tpu_run_step_seconds"]["samples"]
            counts = [v for nm, l, v in hist if nm.endswith("_count") and l.get("run") == "wf-run"]
            assert counts == [7.0]

            # Delete sweeps the DB points AND the per-run histogram series.
            await api.db.execute("UPDATE runs SET status = 'done' WHERE id = 'wf'")
            await api.db.execute("UPDATE jobs SET status = 'done' WHERE id = 'wfj'")
            await api.post("/api/project/main/runs/delete", {"runs_names": ["wf-run"]})
            left = await api.db.fetchone("SELECT COUNT(*) AS n FROM workload_metrics_points")
            assert left["n"] == 0
            resp = await api.client.get("/metrics")
            text = await resp.text()
            assert 'dstack_tpu_run_step_seconds_bucket{le="0.005",run="wf-run"}' not in text

    async def test_ttl_sweep_covers_workload_points(self):
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await _insert_running_job(api.db, proj, "tt", "ttj", jpd=False)
            job = await api.db.fetchone("SELECT * FROM jobs WHERE id = 'ttj'")
            old = now_utc() - datetime.timedelta(hours=2)
            await metrics_service.store_workload_points(
                api.db, job, [{"ts": to_iso(old), "kind": "step", "step_time_s": 1.0}]
            )
            await metrics_service.sweep_metrics(api.db)
            left = await api.db.fetchone("SELECT COUNT(*) AS n FROM workload_metrics_points")
            assert left["n"] == 0

    async def test_gang_lead_lineage_only(self):
        """A 2-host gang emits 2 copies of the step stream; the ledger and
        step series must come from job 0 only (no 2x productive time)."""
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await _insert_running_job(api.db, proj, "g", "gj0", run_name="gang", job_num=0, jpd=False)
            await _insert_running_job(api.db, proj, "g", "gj1", run_name="gang", job_num=1, jpd=False)
            base = now_utc()
            stream = [
                {"ts": _iso(base, i), "kind": "step", "step": i, "step_time_s": 1.0}
                for i in range(1, 5)
            ]
            for jid in ("gj0", "gj1"):
                job = await api.db.fetchone("SELECT * FROM jobs WHERE id = ?", (jid,))
                await metrics_service.store_workload_points(api.db, job, stream)
            res = await api.post("/api/project/main/runs/get_metrics", {"run_name": "gang"})
            assert res["goodput"]["productive_s"] == pytest.approx(4.0)
            assert len(res["points"]) == 4
            # The step histogram follows the same rule: 4 observations, not 8.
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            hist = families["dstack_tpu_run_step_seconds"]["samples"]
            counts = [v for nm, l, v in hist
                      if nm.endswith("_count") and l.get("run") == "gang"]
            assert counts == [4.0]

    async def test_goodput_gauge_spans_prior_submissions(self):
        """/metrics goodput must include a preempted submission's lineage —
        restart downtime is exactly what the gauge exists to show."""
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await _insert_running_job(api.db, proj, "pre", "prej0", run_name="pre-run", jpd=False)
            # The preempted submission's job row is terminal, but its points remain.
            await api.db.execute(
                "INSERT INTO jobs (id, project_id, run_id, run_name, job_num,"
                " replica_num, submission_num, job_spec, status, submitted_at)"
                " VALUES ('preold', ?, 'pre', 'pre-run', 0, 0, 0, '{}', 'failed',"
                " '2026-01-01')",
                (proj["id"],),
            )
            base = now_utc() - datetime.timedelta(seconds=60)
            old_job = await api.db.fetchone("SELECT * FROM jobs WHERE id = 'preold'")
            await metrics_service.store_workload_points(api.db, old_job, [
                {"ts": _iso(base, 0), "kind": "mark", "event": "run_start"},
                {"ts": _iso(base, 1), "kind": "step", "step": 2, "step_time_s": 1.0},
            ])
            new_job = await api.db.fetchone("SELECT * FROM jobs WHERE id = 'prej0'")
            await metrics_service.store_workload_points(api.db, new_job, [
                # 20s restart gap before the new process came up.
                {"ts": _iso(base, 21), "kind": "mark", "event": "run_start"},
                {"ts": _iso(base, 22), "kind": "step", "step": 2, "step_time_s": 1.0},
            ])
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())
            gauges = families["dstack_tpu_run_goodput_ratio"]["samples"]
            val = next(v for _, l, v in gauges if l.get("run") == "pre-run")
            # 1s of NET progress over a 22s wall: the restart gap debits the
            # gauge, and the replayed step 2 counts as rework, not goodput.
            assert val == pytest.approx(1.0 / 22.0, abs=1e-3)


class TestProfileEndpoint:
    async def test_profile_routes_to_running_jobs_agent(self, monkeypatch):
        acks = []

        class FakeAgent:
            async def profile(self, seconds=5.0):
                acks.append(seconds)
                return {"id": 3, "seconds": seconds, "status": "requested",
                        "artifact_dir": "/agent/telemetry/profile/3"}

        monkeypatch.setattr(
            metrics_service, "get_runner_client", lambda jpd, jrd: FakeAgent()
        )
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await _insert_running_job(api.db, proj, "pf", "pfj", run_name="pf-run")
            res = await api.post(
                "/api/project/main/runs/profile", {"run_name": "pf-run", "seconds": 2.5}
            )
            assert acks == [2.5]
            assert res["artifact_dir"] == "/agent/telemetry/profile/3"
            assert res["job_num"] == 0

    async def test_profile_without_running_job_is_client_error(self):
        async with api_server() as api:
            proj = await api.db.fetchone("SELECT * FROM projects")
            await api.db.execute(
                "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at,"
                " status, run_spec) VALUES ('nr', ?, ?, 'idle-run', '2026-01-01', 'done', '{}')",
                (proj["id"], proj["owner_id"]),
            )
            await api.post(
                "/api/project/main/runs/profile", {"run_name": "idle-run"}, expect=400
            )
            await api.post(
                "/api/project/main/runs/profile", {"run_name": "nope"}, expect=404
            )


# ---------------------------------------------------------------------------
# End to end through the REAL C++ agent (local backend, host exec)


pytestmark_e2e = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)

_PROFILE_JOB = """\
import os, time
from dstack_tpu.workloads.telemetry import TelemetryEmitter
class P:
    def __init__(self): self.stopped = False
    def start(self, d):
        os.makedirs(d, exist_ok=True)
        open(os.path.join(d, "trace.data"), "w").write("job-trace")
    def stop(self): self.stopped = True
p = P()
e = TelemetryEmitter(os.environ["DSTACK_TPU_TELEMETRY_PATH"], flush_interval=0.1, profiler=p)
e.mark("run_start", workload="profile-e2e")
t0 = time.time()
i = 0
while time.time() - t0 < 45:
    i += 1
    e.step(i, 0.05, tokens_per_sec=100.0)
    time.sleep(0.05)
    if p.stopped:
        time.sleep(0.5)  # let the profile_end mark flush
        break
e.mark("run_end")
e.close()
"""


async def _drive_collect(api, run_name, until, timeout=90.0):
    """Collect + scheduler passes until `until(run_json)` or terminal state.
    Collection runs FIRST each round so the final sidecar flush is tailed
    while the job row still says running."""
    deadline = asyncio.get_event_loop().time() + timeout
    run = None
    while asyncio.get_event_loop().time() < deadline:
        await metrics_service.collect_job_metrics(api.db)
        await tasks.process_submitted_jobs(api.db)
        await tasks.process_running_jobs(api.db)
        await tasks.process_terminating_jobs(api.db)
        await tasks.process_runs(api.db)
        await tasks.process_instances(api.db)
        run = await api.post("/api/project/main/runs/get", {"run_name": run_name})
        if until(run):
            return run
        if run["status"] in ("failed", "terminated", "done"):
            return run
        await asyncio.sleep(0.2)
    raise AssertionError(f"timed out; run is {run and run['status']}")


def _repo_root() -> str:
    import dstack_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(dstack_tpu.__file__)))


@pytestmark_e2e
class TestE2EWorkloadTelemetry:
    async def test_train_telemetry_reaches_server_through_agent(self):
        """The acceptance path: a real train workload on the real agent; step
        points, MFU, goodput (with the compile stall debited) all land."""
        async with api_server() as api:
            spec = {
                "run_spec": {
                    "run_name": "e2e-telemetry",
                    "configuration": {
                        "type": "task",
                        "commands": [
                            "python3 -m dstack_tpu.workloads.train"
                            " --config test --steps 12 --batch 2 --seq 32"
                        ],
                        "env": {
                            "PYTHONPATH": _repo_root(),
                            "JAX_PLATFORMS": "cpu",
                            "DSTACK_TPU_OVERLAP_FLAGS": "0",
                        },
                    },
                }
            }
            await api.post("/api/project/main/runs/submit", spec)
            run = await _drive_collect(
                api, "e2e-telemetry", lambda r: r["status"] == "done", timeout=150
            )
            assert run["status"] == "done", run["status"]

            res = await api.post(
                "/api/project/main/runs/get_metrics", {"run_name": "e2e-telemetry"}
            )
            assert res["latest"] is not None, res
            assert res["latest"]["step"] == 12
            assert res["latest"]["tokens_per_sec"] > 0
            assert res["latest"]["mfu"] is not None
            ledger = res["goodput"]
            assert ledger["steps"] == 11  # first step is the compile
            assert ledger["compile_s"] > 0, "compile stall must be debited"
            assert ledger["ratio"] is not None

            resp = await api.client.get("/metrics")
            text = await resp.text()
            parse_exposition(text)
            assert 'dstack_tpu_run_step_seconds_count{run="e2e-telemetry"}' in text

    async def test_profile_roundtrip_produces_artifact(self):
        """dstack-tpu profile end to end: server -> agent control file -> the
        live workload's emitter -> trace artifact on the runner host -> the
        profile_end mark back through the metrics channel."""
        async with api_server() as api:
            spec = {
                "run_spec": {
                    "run_name": "e2e-profile",
                    "configuration": {
                        "type": "task",
                        "commands": [f"python3 -c '{_PROFILE_JOB}'"],
                        "env": {"PYTHONPATH": _repo_root()},
                    },
                }
            }
            await api.post("/api/project/main/runs/submit", spec)
            # Wait for the workload to be alive and emitting.
            await _drive_collect(
                api, "e2e-profile",
                lambda r: r["status"] == "running", timeout=60,
            )

            deadline = asyncio.get_event_loop().time() + 30
            while asyncio.get_event_loop().time() < deadline:
                await metrics_service.collect_job_metrics(api.db)
                res = await api.post(
                    "/api/project/main/runs/get_metrics", {"run_name": "e2e-profile"}
                )
                if res["latest"] is not None:  # the workload is alive and emitting
                    break
                await asyncio.sleep(0.2)

            ack = await api.post(
                "/api/project/main/runs/profile",
                {"run_name": "e2e-profile", "seconds": 0.5},
            )
            assert ack["status"] == "requested"
            artifact_dir = ack["artifact_dir"]

            deadline = asyncio.get_event_loop().time() + 45
            mark = None
            while asyncio.get_event_loop().time() < deadline:
                await metrics_service.collect_job_metrics(api.db)
                await tasks.process_running_jobs(api.db)
                res = await api.post(
                    "/api/project/main/runs/get_metrics", {"run_name": "e2e-profile"}
                )
                mark = res.get("profile")
                if mark and mark.get("event") == "profile_end":
                    break
                await asyncio.sleep(0.3)
            assert mark and mark["event"] == "profile_end", f"no profile_end mark: {mark}"
            # Host jobs: the workload's artifact path IS the host path the
            # agent advertised, and the trace is retrievable there.
            assert mark["artifact"] == artifact_dir
            assert os.path.exists(os.path.join(artifact_dir, "trace.data"))

            # Teardown: stop the run (it would otherwise loop for its full 45s).
            await api.post(
                "/api/project/main/runs/stop",
                {"runs_names": ["e2e-profile"], "abort": True},
            )
            await _drive_collect(
                api, "e2e-profile",
                lambda r: r["status"] in ("terminated", "aborted", "failed", "done"),
                timeout=30,
            )
