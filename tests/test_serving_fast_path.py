"""Serving fast path: route-table cache, pooled keep-alive upstream
connections, and the per-run state sweeps that keep the proxy's memory bounded.

These tests drive the REAL in-server proxy (router -> route table -> pooled
forward) against local stub replicas — no native runner, no cloud: the service
run + running replica rows are written straight into the DB, exactly the shape
the scheduler leaves behind."""

import asyncio
import json

import pytest

from dstack_tpu.core.models.runs import JobStatus, JobTerminationReason
from dstack_tpu.core.services import http_forward
from dstack_tpu.server import settings
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.server.services import proxy as proxy_service
from dstack_tpu.server.services.jobs import set_job_status
from tests.common import api_server


async def seed_service(db, run_name: str, replica_port: int, auth: bool = False,
                       rate_limits=None):
    """Insert a ready service run + one running replica pointing at
    127.0.0.1:replica_port (local backend: the proxy dials it directly)."""
    proj = await db.fetchone("SELECT * FROM projects LIMIT 1")
    conf = {
        "type": "service",
        "commands": ["serve"],
        "port": 8000,
        "auth": auth,
    }
    if rate_limits:
        conf["rate_limits"] = rate_limits
    await db.execute(
        "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at, status,"
        " run_spec) VALUES (?, ?, ?, ?, '2026-01-01', 'running', ?)",
        (f"run-{run_name}", proj["id"], proj["owner_id"], run_name,
         json.dumps({"run_name": run_name, "configuration": conf})),
    )
    job_spec = {
        "job_name": f"{run_name}-0-0",
        "image_name": "stub",
        "requirements": {"resources": {}},
        "service_port": 8000,
    }
    jpd = {
        "backend": "local",
        "instance_type": {"name": "local", "resources": {"cpus": 1, "memory_gb": 1, "disk_gb": 1}},
        "instance_id": f"i-{run_name}",
        "hostname": "127.0.0.1",
        "region": "local",
    }
    jrd = {"ports_mapping": {"8000": replica_port}, "probe_ready": True}
    await db.execute(
        "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, job_spec, status,"
        " submitted_at, job_provisioning_data, job_runtime_data)"
        " VALUES (?, ?, ?, ?, 0, ?, 'running', '2026-01-01', ?, ?)",
        (f"job-{run_name}", proj["id"], f"run-{run_name}", run_name,
         json.dumps(job_spec), json.dumps(jpd), json.dumps(jrd)),
    )
    return f"run-{run_name}", f"job-{run_name}"


class _StubReplica:
    """Minimal keep-alive HTTP/1.1 server that counts distinct TCP connections
    — the ground truth for connection reuse through the pooled session."""

    def __init__(self) -> None:
        self.connections = 0
        self.requests = 0
        self._server = None
        self._writers = []
        self.port = None

    async def _handle(self, reader, writer):
        self.connections += 1
        self._writers.append(writer)
        try:
            while True:
                data = b""
                while b"\r\n\r\n" not in data:
                    chunk = await reader.read(4096)
                    if not chunk:
                        return
                    data += chunk
                self.requests += 1
                body = b"pong"
                writer.write(
                    b"HTTP/1.1 200 OK\r\nContent-Length: 4\r\n"
                    b"Connection: keep-alive\r\n\r\n" + body
                )
                await writer.drain()
        finally:
            writer.close()

    async def start(self):
        self._server = await asyncio.start_server(self._handle, "127.0.0.1", 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        self._server.close()
        # Established keep-alive connections outlive the listener; kill them
        # too so "replica died" means the pooled sockets actually go dark.
        for writer in self._writers:
            writer.close()
        await self._server.wait_closed()


class _Fixture:
    """Pin the route cache TTL high and reset proxy state around each test."""

    def __enter__(self):
        self._ttl = settings.PROXY_ROUTE_CACHE_TTL
        settings.PROXY_ROUTE_CACHE_TTL = 3600.0
        proxy_service.route_table.clear()
        proxy_service.stats.reset()
        proxy_service.rate_limiter.reset()
        proxy_service._rr.clear()
        http_forward.set_pooling(True)
        return self

    def __exit__(self, *exc):
        settings.PROXY_ROUTE_CACHE_TTL = self._ttl
        proxy_service.route_table.clear()
        proxy_service.stats.reset()
        proxy_service.rate_limiter.reset()
        proxy_service._rr.clear()
        http_forward.set_pooling(True)
        return False


class TestRouteCache:
    async def test_steady_state_issues_zero_db_queries(self):
        """The acceptance bar: after the first (cache-building) request, N
        proxied requests to a ready service touch the DB zero times."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "fast", stub.port)
                    resp = await api.client.get("/proxy/services/main/fast/ping")
                    assert resp.status == 200 and await resp.text() == "pong"

                    counts = {"queries": 0}
                    orig_all, orig_one = api.db.fetchall, api.db.fetchone

                    async def counted_all(*a, **k):
                        counts["queries"] += 1
                        return await orig_all(*a, **k)

                    async def counted_one(*a, **k):
                        counts["queries"] += 1
                        return await orig_one(*a, **k)

                    api.db.fetchall, api.db.fetchone = counted_all, counted_one
                    try:
                        for _ in range(20):
                            resp = await api.client.get("/proxy/services/main/fast/ping")
                            assert resp.status == 200
                    finally:
                        api.db.fetchall, api.db.fetchone = orig_all, orig_one
                    assert counts["queries"] == 0, (
                        f"steady-state proxying hit the DB {counts['queries']} times"
                    )
                    # The window fed the autoscaler along the way: RPS and latency.
                    assert proxy_service.stats.rps("run-fast") > 0
                    assert proxy_service.stats.avg_latency("run-fast") is not None
            finally:
                await stub.stop()

    async def test_invalidation_on_replica_stop_and_start(self):
        """A replica stopping (job leaves RUNNING) must drop the cached route
        immediately — not after the TTL — and its restart must restore it."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    run_id, job_id = await seed_service(api.db, "flap", stub.port)
                    resp = await api.client.get("/proxy/services/main/flap/ping")
                    assert resp.status == 200

                    job_row = await api.db.fetchone(
                        "SELECT * FROM jobs WHERE id = ?", (job_id,)
                    )
                    await set_job_status(
                        api.db, job_row, JobStatus.TERMINATING,
                        JobTerminationReason.TERMINATED_BY_USER,
                    )
                    resp = await api.client.get("/proxy/services/main/flap/ping")
                    assert resp.status == 503, (
                        "stopped replica still served from a stale cached route"
                    )

                    await set_job_status(api.db, job_row, JobStatus.RUNNING)
                    resp = await api.client.get("/proxy/services/main/flap/ping")
                    assert resp.status == 200
            finally:
                await stub.stop()

    async def test_run_deletion_sweeps_all_per_run_state(self):
        """forget_run: route entry, rr cursor, stats window, persisted marks,
        and rate-limit buckets all go when the run does."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    run_id, job_id = await seed_service(
                        api.db, "doomed", stub.port,
                        rate_limits=[{"prefix": "/", "rps": 1000, "burst": 100}],
                    )
                    for _ in range(3):
                        resp = await api.client.get("/proxy/services/main/doomed/ping")
                        assert resp.status == 200
                    proxy_service.stats.persisted[(run_id, 0)] = 3

                    assert run_id in proxy_service._rr
                    assert proxy_service.stats.rps(run_id) > 0
                    assert proxy_service.route_table.get("main", "doomed") is not None

                    # Finish the jobs, then delete through the real service path.
                    await api.db.execute(
                        "UPDATE jobs SET status = 'done' WHERE run_id = ?", (run_id,)
                    )
                    await api.db.execute(
                        "UPDATE runs SET status = 'done' WHERE id = ?", (run_id,)
                    )
                    from dstack_tpu.server.services import runs as runs_service

                    proj = await api.db.fetchone("SELECT * FROM projects LIMIT 1")
                    await runs_service.delete_runs(api.db, proj, ["doomed"])

                    assert run_id not in proxy_service._rr
                    assert run_id not in proxy_service.stats._requests
                    assert run_id not in proxy_service.stats._latencies
                    assert not any(
                        k[0] == run_id for k in proxy_service.stats.persisted
                    )
                    assert not any(
                        k[0] == run_id for k in proxy_service.rate_limiter._buckets
                    )
                    assert proxy_service.route_table.get("main", "doomed") is None
            finally:
                await stub.stop()

    async def test_ttl_fallback_bounds_staleness(self):
        """With hooks out of the picture (direct UPDATE, no set_job_status),
        the TTL still expires the stale route."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "ttl", stub.port)
                    resp = await api.client.get("/proxy/services/main/ttl/ping")
                    assert resp.status == 200
                    # Bypass every hook: raw status flip.
                    await api.db.execute(
                        "UPDATE jobs SET status = 'terminating' WHERE run_id = ?",
                        ("run-ttl",),
                    )
                    # Cached route still serves (that's the point of the cache)...
                    resp = await api.client.get("/proxy/services/main/ttl/ping")
                    assert resp.status == 200
                    # ...until the TTL expires it.
                    settings.PROXY_ROUTE_CACHE_TTL = 0.01
                    await asyncio.sleep(0.05)
                    resp = await api.client.get("/proxy/services/main/ttl/ping")
                    assert resp.status == 503
            finally:
                await stub.stop()


class TestPooledUpstream:
    async def test_keepalive_reuses_one_tcp_connection(self):
        """N sequential proxied requests ride ONE upstream TCP connection."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "pooled", stub.port)
                    for _ in range(8):
                        resp = await api.client.get("/proxy/services/main/pooled/ping")
                        assert resp.status == 200
                        assert await resp.text() == "pong"
                    assert stub.requests == 8
                    assert stub.connections == 1, (
                        f"expected 1 keep-alive connection, saw {stub.connections}"
                    )
            finally:
                await stub.stop()

    async def test_legacy_mode_dials_per_request(self):
        """set_pooling(False) restores the old one-connection-per-request path
        (what bench_proxy measures against)."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "unpooled", stub.port)
                    http_forward.set_pooling(False)
                    for _ in range(3):
                        resp = await api.client.get("/proxy/services/main/unpooled/ping")
                        assert resp.status == 200
                    assert stub.connections == 3
            finally:
                await stub.stop()

    async def test_sse_streams_unbuffered_through_pool(self):
        """Chunked/SSE output must flow through the pooled session chunk by
        chunk: the client sees the first event while the upstream is still
        holding the stream open."""
        from aiohttp import web as aioweb

        with _Fixture():
            release = asyncio.Event()

            async def sse(request):
                resp = aioweb.StreamResponse(
                    headers={"Content-Type": "text/event-stream"}
                )
                await resp.prepare(request)
                await resp.write(b"data: one\n\n")
                # Hold the stream open until the client confirms receipt of the
                # first event — if forwarding buffered, this deadlocks (and the
                # wait_for below fails the test instead of hanging it).
                await asyncio.wait_for(release.wait(), timeout=10)
                await resp.write(b"data: two\n\n")
                await resp.write_eof()
                return resp

            upstream = aioweb.Application()
            upstream.router.add_get("/{tail:.*}", sse)
            runner = aioweb.AppRunner(upstream)
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            try:
                async with api_server() as api:
                    await seed_service(api.db, "sse", port)
                    resp = await api.client.get("/proxy/services/main/sse/events")
                    assert resp.status == 200
                    first = await asyncio.wait_for(
                        resp.content.readuntil(b"\n\n"), timeout=5
                    )
                    assert first == b"data: one\n\n"
                    release.set()
                    rest = await resp.content.read()
                    assert rest == b"data: two\n\n"
            finally:
                await runner.cleanup()

    async def test_dead_endpoint_invalidates_route(self):
        """A cached endpoint that stops answering 502s once, then the rebuilt
        route reflects reality (no more running replicas -> 503)."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "dark", stub.port)
                    resp = await api.client.get("/proxy/services/main/dark/ping")
                    assert resp.status == 200
                    await stub.stop()
                    resp = await api.client.get("/proxy/services/main/dark/ping")
                    assert resp.status == 502
                    # Entry was dropped: the rebuild still sees a 'running' job
                    # row, resolves the (dead) endpoint, and 502s again — but
                    # through a FRESH entry each time, never a pinned socket.
                    assert proxy_service.route_table.get("main", "dark") is None
            finally:
                pass


class TestFileLogOffsets:
    def _events(self, n, start=0):
        return [
            logs_service.LogEvent.model_validate(
                {"timestamp": "2026-01-01T00:00:00+00:00", "message": f"line-{i}\n",
                 "log_source": "stdout"}
            )
            for i in range(start, start + n)
        ]

    def test_tail_poll_seeks_instead_of_rescanning(self, tmp_path):
        storage = logs_service.FileLogStorage(str(tmp_path))
        storage.write_logs("p", "r", "j", self._events(5))
        first = storage.poll_logs("p", "r", "j", start_line=0, limit=1000)
        assert [e.message for e in first] == [f"line-{i}\n" for i in range(5)]
        line_i, byte_off = storage._offsets[("p", "r", "j")]
        assert line_i == 5 and byte_off > 0

        storage.write_logs("p", "r", "j", self._events(3, start=5))
        tail = storage.poll_logs("p", "r", "j", start_line=5, limit=1000)
        assert [e.message for e in tail] == [f"line-{i}\n" for i in range(5, 8)]
        assert storage._offsets[("p", "r", "j")][0] == 8

    def test_memo_validated_against_truncation(self, tmp_path):
        storage = logs_service.FileLogStorage(str(tmp_path))
        storage.write_logs("p", "r", "j", self._events(10))
        assert len(storage.poll_logs("p", "r", "j")) == 10
        # Truncate behind the memo's back (rotation): the next poll must fall
        # back to a full scan, not seek past EOF.
        path = tmp_path / "p" / "r" / "j.jsonl"
        path.write_text("")
        storage.write_logs("p", "r", "j", self._events(2))
        assert len(storage.poll_logs("p", "r", "j", start_line=0)) == 2

    def test_rewind_behind_memo_rescans(self, tmp_path):
        storage = logs_service.FileLogStorage(str(tmp_path))
        storage.write_logs("p", "r", "j", self._events(6))
        assert len(storage.poll_logs("p", "r", "j", start_line=4)) == 2
        # A caller starting over still gets everything.
        assert len(storage.poll_logs("p", "r", "j", start_line=0)) == 6

    def test_missing_file_clears_memo(self, tmp_path):
        storage = logs_service.FileLogStorage(str(tmp_path))
        assert storage.poll_logs("p", "r", "j") == []
        assert ("p", "r", "j") not in storage._offsets

    def test_mid_line_memo_recovers_via_rescan(self, tmp_path):
        """An equal-or-larger file replacement defeats the shrink check and
        leaves the memo pointing mid-line; the poll must rescan from the top
        instead of raising (and must keep doing so correctly afterwards)."""
        storage = logs_service.FileLogStorage(str(tmp_path))
        storage.write_logs("p", "r", "j", self._events(6))
        # Plant what a same-size rotation produces: a memo whose byte offset
        # lands inside a JSON line (byte 10 is always mid-first-line).
        storage._offsets[("p", "r", "j")] = (2, 10)
        events = storage.poll_logs("p", "r", "j", start_line=2)
        assert [e.message for e in events] == [f"line-{i}\n" for i in range(2, 6)]
        # The memo was rebuilt sane: a tail poll works without rescanning.
        storage.write_logs("p", "r", "j", self._events(1, start=6))
        tail = storage.poll_logs("p", "r", "j", start_line=6)
        assert [e.message for e in tail] == ["line-6\n"]


class TestRouteTableFences:
    def test_build_fence_is_per_run(self):
        """The endpoint-resolve fence trips only on THIS run's invalidation;
        unrelated runs' scheduler churn must not evict fresh entries (a global
        fence would collapse the hit rate on a busy control plane)."""
        with _Fixture():
            table = proxy_service.RouteTable()
            seq = table.mark_build("rid")
            table.invalidate_run("some-other-run")
            assert table.run_seq("rid") == seq  # unrelated churn: no trip
            table.invalidate_run("rid")
            assert table.run_seq("rid") != seq  # own transition: fence trips
            table.forget_seq("rid")
            assert "rid" not in table._run_seq  # swept with the run

    async def test_own_invalidation_during_endpoint_resolve_discards_entry(self):
        """If the run transitions while its endpoints are being resolved, the
        built route serves that request only — it is not cached."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    run_id, _ = await seed_service(api.db, "fenced", stub.port)
                    entry = await proxy_service.resolve_route(api.db, "main", "fenced")
                    # Simulate a transition landing mid-resolve.
                    proxy_service.route_table.mark_build(run_id)
                    proxy_service.route_table.invalidate_run(run_id)
                    entry2 = await proxy_service.resolve_route(api.db, "main", "fenced")
                    orig = proxy_service.list_service_replicas

                    async def racing_list(*a, **k):
                        proxy_service.route_table.invalidate_run(run_id)
                        return await orig(*a, **k)

                    proxy_service.list_service_replicas = racing_list
                    try:
                        await proxy_service._populate_endpoints(api.db, entry2)
                    finally:
                        proxy_service.list_service_replicas = orig
                    assert entry2.endpoints  # this request is still served
                    assert proxy_service.route_table.get("main", "fenced") is None
            finally:
                await stub.stop()

    async def test_unauthenticated_requests_resolve_no_endpoints(self):
        """auth-protected services: a 401'd request must not trigger replica
        listing or tunnel establishment (endpoints stay unpopulated)."""
        with _Fixture():
            stub = await _StubReplica().start()
            try:
                async with api_server() as api:
                    await seed_service(api.db, "locked", stub.port, auth=True)
                    resp = await api.client.get("/proxy/services/main/locked/ping")
                    assert resp.status == 401
                    entry = proxy_service.route_table.get("main", "locked")
                    assert entry is not None and entry.endpoints is None
                    assert stub.connections == 0
                    # An authorized request populates and forwards.
                    resp = await api.client.get(
                        "/proxy/services/main/locked/ping",
                        headers={"Authorization": f"Bearer {api.token}"},
                    )
                    assert resp.status == 200
                    assert entry.endpoints
            finally:
                await stub.stop()


class TestLatencyWindow:
    def test_avg_latency_over_window(self):
        stats = proxy_service.ServiceStats()
        stats.record_latency("r1", 0.10)
        stats.record_latency("r1", 0.30)
        assert stats.avg_latency("r1") == pytest.approx(0.20)
        assert stats.avg_latency("r2") is None
        stats.drop_run("r1")
        assert stats.avg_latency("r1") is None
