"""Run-event-type lint (ISSUE 19 satellite): every event type (and scheduler
reason) the server records into the run_events timeline must appear in the
events reference in docs/guides/observability.md.

Mirrors tests/test_metrics_lint.py for metric names: a new record_event_tx
call site with an undocumented event type fails here, not when an operator
reads an unexplained row in `dstack-tpu events`. The scan is AST-based — it
collects string literals passed as the event-type argument (and `reason=`
keyword) of record_event / record_event_tx / _record_*event* calls under
dstack_tpu/server/, so dynamically forwarded statuses (variables) are exempt
while every hand-named event type is covered."""

import ast
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SERVER = REPO / "dstack_tpu" / "server"
DOCS = REPO / "docs" / "guides" / "observability.md"

# Run statuses flow through record_event_tx as the event type; they are
# documented as the run FSM, not as bespoke event types, so the lint only
# requires them to appear somewhere in the guide (they all do — the phases
# table walks the FSM).
_EVENT_ARG_INDEX = {"record_event": 2, "record_event_tx": 1}


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _recorded_literals() -> set:
    """Every string literal used as an event type or scheduler reason in a
    record_event(_tx) call under dstack_tpu/server/."""
    literals = set()
    for path in sorted(SERVER.rglob("*.py")):
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            is_recorder = name in _EVENT_ARG_INDEX or (
                name.startswith("_record_") and "event" in name
            )
            if not is_recorder:
                continue
            # The positional event-type argument: record_event_tx(conn, run_id,
            # new_status, ...) — index counted after the conn argument, which
            # record_event (db variant) doesn't take.
            idx = _EVENT_ARG_INDEX.get(name, 1)
            for candidate in (idx, idx + 1):
                if candidate < len(node.args):
                    arg = node.args[candidate]
                    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                        literals.add(arg.value)
            for kw in node.keywords:
                if kw.arg == "reason" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    literals.add(kw.value.value)
    return literals


class TestEventTypeLint:
    def test_scan_sees_known_event_types(self):
        literals = _recorded_literals()
        # Sanity: the scan actually catches both a status-typed event literal
        # and the bespoke scheduler event types.
        assert "submitted" in literals
        assert "placement_attempt" in literals
        assert "backend_circuit_open" in literals
        assert "straggler_detected" in literals

    def test_every_recorded_event_type_is_documented(self):
        literals = _recorded_literals()
        doc_text = DOCS.read_text(encoding="utf-8")
        missing = sorted(lit for lit in literals if lit not in doc_text)
        assert not missing, (
            "event types/reasons recorded in dstack_tpu/server but absent"
            f" from docs/guides/observability.md: {missing}"
        )
