"""Multislice gang scheduling (BASELINE config #5).

One run spanning N slices: the replica's jobs fan out slice-by-slice, every
worker receives the MegaScale DCN contract (MEGASCALE_NUM_SLICES / SLICE_ID /
coordinator anchored at slice 0 worker 0), and any slice failure requeues the
WHOLE multislice gang — a MegaScale program cannot survive a partial restart.
Parity: reference cluster env contract (executor.go:262-274) extended to
multislice, which the reference does not orchestrate at all."""

import pytest

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from tests.common import (
    FakeRunnerClient,
    api_server,
    drive,
    setup_mock_backend,
)


@pytest.fixture(autouse=True)
def _fake_runner(monkeypatch):
    FakeRunnerClient.reset()
    backends_service.reset_compute_cache()
    monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
    yield


def multislice_spec(run_name: str, count: int = 2, **conf) -> dict:
    return {
        "run_spec": {
            "run_name": run_name,
            "configuration": {
                "type": "task",
                "commands": ["python train.py"],
                # v5p 8 chips = 2 hosts per slice; count slices.
                "resources": {"tpu": {"generation": "v5p", "chips": 8, "count": count}},
                **conf,
            },
        }
    }


class TestMultislice:
    async def test_two_slice_gang_runs_with_megascale_env(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", multislice_spec("ms", 2))
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "ms"})
            assert run["status"] == "done", run.get("termination_reason")

            # Two distinct slices provisioned, 2 workers each.
            compute = dict(
                await backends_service.get_project_computes(
                    api.db, await api.db.fetchone("SELECT * FROM projects")
                )
            )["mock"]
            assert len(compute.created) == 2
            inst = await api.db.fetchall("SELECT * FROM instances")
            assert len(inst) == 4
            assert len({r["slice_id"] for r in inst}) == 2

            # Every worker got the MegaScale contract; slice ids split 2/2; the
            # coordinator anchors at slice 0 worker 0 for everyone.
            fakes = sorted(
                FakeRunnerClient.registry.values(), key=lambda f: f.cluster_info.node_rank
            )
            assert len(fakes) == 4
            infos = [f.cluster_info for f in fakes]
            assert [i.slice_id for i in infos] == [0, 0, 1, 1]
            assert all(i.num_slices == 2 for i in infos)
            assert all(i.megascale_coordinator_address for i in infos)
            assert len({i.megascale_coordinator_address for i in infos}) == 1
            # Within each slice the TPU worker ids restart at 0.
            assert [i.tpu_worker_id for i in infos] == [0, 1, 0, 1]
            # The global rank spans both slices.
            assert [i.node_rank for i in infos] == [0, 1, 2, 3]
            assert all(i.nodes_num == 4 for i in infos)

    async def test_four_slice_gang_runs_with_megascale_env(self):
        """A 4-slice MegaScale gang: 8 workers, slice ids 0..3, one shared
        coordinator anchored at slice 0 worker 0."""
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", multislice_spec("ms4", 4))
            await drive(api.db, passes=20)
            run = await api.post("/api/project/main/runs/get", {"run_name": "ms4"})
            assert run["status"] == "done", run.get("termination_reason")

            inst = await api.db.fetchall("SELECT * FROM instances")
            assert len(inst) == 8
            assert len({r["slice_id"] for r in inst}) == 4

            fakes = sorted(
                FakeRunnerClient.registry.values(), key=lambda f: f.cluster_info.node_rank
            )
            infos = [f.cluster_info for f in fakes]
            assert [i.slice_id for i in infos] == [0, 0, 1, 1, 2, 2, 3, 3]
            assert all(i.num_slices == 4 for i in infos)
            assert len({i.megascale_coordinator_address for i in infos}) == 1
            assert [i.tpu_worker_id for i in infos] == [0, 1] * 4
            assert [i.node_rank for i in infos] == list(range(8))
            assert all(i.nodes_num == 8 for i in infos)

    def test_four_slice_mesh_trains(self):
        """Compute side of the 4-slice contract: one train step over a 4-slice
        mesh (dp spans slices over DCN, fsdp/tp stay on-slice) runs and the
        sharded program compiles without falling back to replication."""
        import jax
        import jax.numpy as jnp

        from dstack_tpu.workloads import train as train_lib
        from dstack_tpu.workloads.config import get_config
        from dstack_tpu.workloads.sharding import batch_sharding, make_multislice_mesh

        devices = jax.devices("cpu")[:8]
        mesh = make_multislice_mesh(4, fsdp=1, tp=2, devices=devices)
        assert mesh.shape["dp"] == 4
        cfg = get_config("test")
        optimizer = train_lib.make_optimizer()
        with mesh:
            state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
            step_fn = train_lib.make_train_step(cfg, optimizer, mesh)
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (8, 128), 0, cfg.vocab_size),
                batch_sharding(mesh),
            )
            state, metrics = step_fn(state, tokens, tokens)
            loss = float(metrics["loss"])
        assert loss > 0 and loss == loss

    async def test_single_slice_has_no_megascale_env(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", multislice_spec("ss", 1))
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "ss"})
            assert run["status"] == "done"
            infos = [f.cluster_info for f in FakeRunnerClient.registry.values()]
            assert all(i.num_slices == 1 for i in infos)
            assert all(i.megascale_coordinator_address is None for i in infos)

    async def test_slice_failure_requeues_entire_multislice_gang(self, monkeypatch):
        """A failure on any worker of any slice resubmits ALL slices' jobs."""
        monkeypatch.setattr("dstack_tpu.server.settings.RETRY_BACKOFF_BASE", 0.0)
        async with api_server() as api:
            await setup_mock_backend(api)
            orig_for_jpd = FakeRunnerClient.for_jpd
            injected = []

            def failing_for_jpd(jpd, jrd):
                fake = orig_for_jpd(jpd, jrd)
                # Fail one worker of one slice, first attempt only.
                if jpd.worker_num == 1 and not injected and fake.submitted is None:
                    injected.append(True)
                    fake.script = [
                        {
                            "job_states": [{"state": "failed", "exit_status": 1}],
                            "logs": [],
                            "offset": 1,
                        }
                    ]
                return fake

            monkeypatch.setattr(tasks, "get_runner_client", failing_for_jpd)
            await api.post(
                "/api/project/main/runs/submit",
                multislice_spec("msr", 2, retry={"on_events": ["error"], "duration": "1h"}),
            )
            await drive(api.db, passes=25)
            run = await api.post("/api/project/main/runs/get", {"run_name": "msr"})
            assert run["status"] == "done"
            rows = await api.db.fetchall(
                "SELECT * FROM jobs WHERE run_name = 'msr' ORDER BY submission_num, job_num"
            )
            # All 4 jobs of submission 0, then ALL 4 requeued as submission 1 —
            # including the slices that had not failed.
            assert len(rows) == 8
            assert [r["submission_num"] for r in rows] == [0, 0, 0, 0, 1, 1, 1, 1]
            final = [r for r in rows if r["submission_num"] == 1]
            assert all(r["status"] == "done" for r in final)
