"""Router/API tests (parity: reference src/tests/_internal/server/routers/)."""

import pytest

from tests.common import TASK_SPEC, api_server, tpu_task_spec


class TestAuth:
    async def test_healthcheck_public(self):
        async with api_server() as api:
            resp = await api.client.get("/healthcheck")
            assert resp.status == 200

    async def test_missing_token(self):
        async with api_server() as api:
            await api.post("/api/users/get_my_user", token="", expect=401)

    async def test_invalid_token(self):
        async with api_server() as api:
            await api.post("/api/users/get_my_user", token="bogus", expect=401)

    async def test_admin_user(self):
        async with api_server() as api:
            me = await api.post("/api/users/get_my_user")
            assert me["username"] == "admin"
            assert me["global_role"] == "admin"


class TestUsers:
    async def test_create_list_delete(self):
        async with api_server() as api:
            created = await api.post("/api/users/create", {"username": "alice"})
            assert created["username"] == "alice"
            assert created["creds"]["token"]
            users = await api.post("/api/users/list")
            assert {u["username"] for u in users} == {"admin", "alice"}
            # alice is not an admin
            await api.post(
                "/api/users/create", {"username": "bob"}, token=created["creds"]["token"], expect=403
            )
            await api.post("/api/users/delete", {"users": ["alice"]})
            users = await api.post("/api/users/list")
            assert {u["username"] for u in users} == {"admin"}

    async def test_duplicate_username(self):
        async with api_server() as api:
            await api.post("/api/users/create", {"username": "alice"})
            await api.post("/api/users/create", {"username": "alice"}, expect=409)

    async def test_refresh_token(self):
        async with api_server() as api:
            created = await api.post("/api/users/create", {"username": "alice"})
            old = created["creds"]["token"]
            refreshed = await api.post("/api/users/refresh_token", {"username": "alice"})
            assert refreshed["creds"]["token"] != old
            # old token no longer works
            await api.post("/api/users/get_my_user", token=old, expect=401)
            me = await api.post(
                "/api/users/get_my_user", token=refreshed["creds"]["token"]
            )
            assert me["username"] == "alice"


class TestProjects:
    async def test_default_project(self):
        async with api_server() as api:
            projects = await api.post("/api/projects/list")
            assert [p["project_name"] for p in projects] == ["main"]

    async def test_create_and_members(self):
        async with api_server() as api:
            await api.post("/api/projects/create", {"project_name": "research"})
            alice = await api.post("/api/users/create", {"username": "alice"})
            atoken = alice["creds"]["token"]
            # alice sees no projects yet
            projects = await api.post("/api/projects/list", token=atoken)
            assert projects == []
            # non-member cannot read the project
            await api.post("/api/projects/research/get", token=atoken, expect=403)
            await api.post(
                "/api/projects/research/set_members",
                {"members": [{"username": "admin", "project_role": "admin"}, {"username": "alice", "project_role": "user"}]},
            )
            proj = await api.post("/api/projects/research/get", token=atoken)
            assert {m["user"]["username"] for m in proj["members"]} == {"admin", "alice"}
            # member but not admin: cannot set members
            await api.post(
                "/api/projects/research/set_members",
                {"members": []},
                token=atoken,
                expect=403,
            )

    async def test_duplicate_project(self):
        async with api_server() as api:
            await api.post("/api/projects/create", {"project_name": "p1"})
            await api.post("/api/projects/create", {"project_name": "p1"}, expect=409)

    async def test_delete_project(self):
        async with api_server() as api:
            await api.post("/api/projects/create", {"project_name": "p1"})
            await api.post("/api/projects/delete", {"projects_names": ["p1"]})
            projects = await api.post("/api/projects/list")
            assert "p1" not in [p["project_name"] for p in projects]


class TestBackends:
    async def test_local_backend_present(self):
        async with api_server() as api:
            backends = await api.post("/api/project/main/backends/list")
            assert any(b["type"] == "local" for b in backends)

    async def test_create_mock_backend(self):
        async with api_server() as api:
            await api.post("/api/project/main/backends/create", {"type": "mock"})
            backends = await api.post("/api/project/main/backends/list")
            assert any(b["type"] == "mock" for b in backends)


class TestRuns:
    async def test_get_plan_cpu_task(self):
        async with api_server() as api:
            plan = await api.post("/api/project/main/runs/get_plan", TASK_SPEC)
            assert plan["effective_run_name"] == "test-run"
            assert len(plan["job_plans"]) == 1
            assert plan["action"] == "create"
            # local backend offers a CPU instance
            assert plan["total_offers"] >= 1

    async def test_get_plan_tpu_task_no_tpu_backend(self):
        async with api_server() as api:
            plan = await api.post("/api/project/main/runs/get_plan", tpu_task_spec())
            assert plan["total_offers"] == 0  # local backend can't serve TPUs

    async def test_get_plan_tpu_task_with_mock(self):
        async with api_server() as api:
            await api.post("/api/project/main/backends/create", {"type": "mock"})
            plan = await api.post("/api/project/main/runs/get_plan", tpu_task_spec())
            assert plan["total_offers"] > 0
            offer = plan["offers"][0]
            assert offer["slice_name"] == "v5p-16"
            assert offer["hosts_per_slice"] == 2
            # multi-host slice -> one job per host in the plan
            assert len(plan["job_plans"]) == 2

    async def test_submit_and_get(self):
        async with api_server() as api:
            run = await api.post("/api/project/main/runs/apply_plan", TASK_SPEC)
            assert run["status"] == "submitted"
            got = await api.post("/api/project/main/runs/get", {"run_name": "test-run"})
            assert got["id"] == run["id"]
            assert len(got["jobs"]) == 1
            runs = await api.post("/api/project/main/runs/list")
            assert len(runs) == 1

    async def test_submit_duplicate_active(self):
        async with api_server() as api:
            await api.post("/api/project/main/runs/apply_plan", TASK_SPEC)
            await api.post("/api/project/main/runs/apply_plan", TASK_SPEC, expect=409)

    async def test_submit_generates_name(self):
        async with api_server() as api:
            spec = {"run_spec": {"configuration": {"type": "task", "commands": ["true"]}}}
            run = await api.post("/api/project/main/runs/apply_plan", spec)
            assert run["run_spec"]["run_name"]

    async def test_stop_run(self):
        async with api_server() as api:
            await api.post("/api/project/main/runs/apply_plan", TASK_SPEC)
            await api.post("/api/project/main/runs/stop", {"runs_names": ["test-run"]})
            got = await api.post("/api/project/main/runs/get", {"run_name": "test-run"})
            assert got["status"] == "terminating"
            assert got["termination_reason"] == "stopped_by_user"

    async def test_delete_requires_finished(self):
        async with api_server() as api:
            await api.post("/api/project/main/runs/apply_plan", TASK_SPEC)
            await api.post(
                "/api/project/main/runs/delete", {"runs_names": ["test-run"]}, expect=400
            )

    async def test_get_missing_run(self):
        async with api_server() as api:
            await api.post("/api/project/main/runs/get", {"run_name": "nope"}, expect=404)

    async def test_tpu_submit_creates_gang(self):
        async with api_server() as api:
            await api.post("/api/project/main/backends/create", {"type": "mock"})
            run = await api.post(
                "/api/project/main/runs/apply_plan", tpu_task_spec(run_name="gang", tpu="v5e-16")
            )
            assert len(run["jobs"]) == 2  # v5e-16 = 2 hosts
            specs = [j["job_spec"] for j in run["jobs"]]
            assert [s["job_num"] for s in specs] == [0, 1]
            assert all(s["jobs_per_replica"] == 2 for s in specs)

    async def test_nodes_conflicting_with_slice(self):
        async with api_server() as api:
            await api.post(
                "/api/project/main/runs/get_plan",
                tpu_task_spec(run_name="x", tpu="v5p-16", nodes=5),
                expect=400,
            )


class TestRegressions:
    async def test_resubmit_finished_name_twice(self):
        # Two generations of soft-deleted rows with the same name must not collide.
        async with api_server() as api:
            for _ in range(3):
                run = await api.post("/api/project/main/runs/apply_plan", TASK_SPEC)
                db = api.client.server.app["db"]
                await db.execute(
                    "UPDATE runs SET status = 'done' WHERE id = ?", (run["id"],)
                )

    async def test_project_name_reusable_after_delete(self):
        async with api_server() as api:
            await api.post("/api/projects/create", {"project_name": "p1"})
            await api.post("/api/projects/delete", {"projects_names": ["p1"]})
            created = await api.post("/api/projects/create", {"project_name": "p1"})
            assert created["project_name"] == "p1"

    async def test_delete_user_with_resources_deactivates(self):
        async with api_server() as api:
            alice = await api.post("/api/users/create", {"username": "alice"})
            atok = alice["creds"]["token"]
            await api.post("/api/projects/create", {"project_name": "ap"}, token=atok)
            await api.post("/api/users/delete", {"users": ["alice"]})
            # token revoked, but project ownership intact (no 500)
            await api.post("/api/users/get_my_user", token=atok, expect=401)
            proj = await api.post("/api/projects/ap/get")
            assert proj["owner"]["username"] == "alice"

    async def test_set_members_ghost_preserves_members(self):
        async with api_server() as api:
            await api.post("/api/projects/create", {"project_name": "p2"})
            await api.post(
                "/api/projects/p2/set_members",
                {"members": [{"username": "ghost"}]},
                expect=404,
            )
            proj = await api.post("/api/projects/p2/get")
            assert len(proj["members"]) == 1  # admin still a member

    async def test_failed_submit_leaves_no_orphan_run(self):
        async with api_server() as api:
            spec = {
                "run_spec": {
                    "run_name": "orphan",
                    "configuration": {
                        "type": "task",
                        "commands": ["x"],
                        "env": ["UNSET_VAR"],  # bare env var -> configurator error
                    },
                }
            }
            await api.post("/api/project/main/runs/apply_plan", spec, expect=400)
            await api.post("/api/project/main/runs/get", {"run_name": "orphan"}, expect=404)

    async def test_profile_duration_strings(self):
        async with api_server() as api:
            spec = {
                "run_spec": {
                    "run_name": "durs",
                    "configuration": {"type": "task", "commands": ["x"], "max_duration": "2h"},
                    "profile": {"stop_duration": "10m"},
                }
            }
            run = await api.post("/api/project/main/runs/apply_plan", spec)
            js = run["jobs"][0]["job_spec"]
            assert js["max_duration"] == 7200
            assert js["stop_duration"] == 600

    async def test_update_user_partial(self):
        async with api_server() as api:
            await api.post("/api/users/create", {"username": "root2", "global_role": "admin"})
            updated = await api.post(
                "/api/users/update", {"username": "root2", "email": "x@y.z"}
            )
            assert updated["global_role"] == "admin"  # not demoted
            assert updated["email"] == "x@y.z"


class TestOffersCatalog:
    async def test_catalog_pricing_sorted(self):
        async with api_server() as api:
            await api.post("/api/project/main/backends/create", {"type": "mock"})
            plan = await api.post(
                "/api/project/main/runs/get_plan", tpu_task_spec(run_name="o", tpu="v5e-8")
            )
            prices = [o["price"] for o in plan["offers"]]
            assert prices == sorted(prices)
            # spot offers cheaper than on-demand
            assert any(o["spot"] for o in plan["offers"])


class TestDashboard:
    async def test_dashboard_served_at_root(self):
        from tests.common import api_server

        async with api_server() as api:
            resp = await api.client.get("/")
            assert resp.status == 200
            text = await resp.text()
            # The SPA shell: title + module entry (views live in app.js,
            # covered by tests/test_frontend.py).
            assert "dstack-tpu" in text and "/statics/app.js" in text


class TestApiCompatibility:
    async def test_version_header_enforced_by_major(self):
        from tests.common import api_server

        async with api_server() as api:
            headers = {"Authorization": f"Bearer {api.token}"}
            # Same major: fine (any minor).
            resp = await api.client.post(
                "/api/project/main/runs/list", json={},
                headers={**headers, "x-api-version": "1.7"},
            )
            assert resp.status == 200
            # Different major: clear rejection.
            resp = await api.client.post(
                "/api/project/main/runs/list", json={},
                headers={**headers, "x-api-version": "2.0"},
            )
            assert resp.status == 400
            assert "incompatible" in await resp.text()
            # No header (curl/probes): passes.
            resp = await api.client.post(
                "/api/project/main/runs/list", json={}, headers=headers
            )
            assert resp.status == 200


class TestRunsPagination:
    """Keyset pagination on runs/list (reference schemas/runs.py:16-18)."""

    async def test_cursor_walks_all_pages_without_overlap(self):
        from tests.common import api_server

        async with api_server() as api:
            for i in range(7):
                await api.post(
                    "/api/project/main/runs/submit",
                    {"run_spec": {"run_name": f"pg-{i}", "configuration": {
                        "type": "task", "commands": ["true"]}}},
                )
            seen = []
            cursor = {}
            while True:
                page = await api.post(
                    "/api/project/main/runs/list", {"limit": 3, **cursor}
                )
                if not page:
                    break
                seen.extend(r["run_spec"]["run_name"] for r in page)
                assert len(page) <= 3
                cursor = {
                    "prev_submitted_at": page[-1]["submitted_at"],
                    "prev_run_id": page[-1]["id"],
                }
            assert sorted(seen) == sorted(f"pg-{i}" for i in range(7))
            assert len(seen) == len(set(seen)), "pages overlapped"

    async def test_bad_cursor_is_client_error(self):
        from tests.common import api_server

        async with api_server() as api:
            headers = {"Authorization": f"Bearer {api.token}"}
            for bad_body in (
                {"prev_submitted_at": "not-a-time"},
                {"prev_submitted_at": 123},     # non-string cursor
                {"limit": "abc"},               # non-numeric limit
            ):
                resp = await api.client.post(
                    "/api/project/main/runs/list", json=bad_body, headers=headers
                )
                assert resp.status == 400, bad_body
            # Negative limit must not become sqlite's "unlimited".
            resp = await api.client.post(
                "/api/project/main/runs/list", json={"limit": -1}, headers=headers
            )
            assert resp.status == 200
            assert len(await resp.json()) <= 1

    async def test_only_active_filter(self):
        from tests.common import api_server
        from tests.test_services import _drive

        async with api_server() as api:
            await api.post(
                "/api/project/main/runs/submit",
                {"run_spec": {"run_name": "act-1", "configuration": {
                    "type": "task", "commands": ["true"]}}},
            )
            import asyncio
            import time

            deadline = time.time() + 30
            while time.time() < deadline:
                await _drive(api)
                run = await api.post("/api/project/main/runs/get", {"run_name": "act-1"})
                if run["status"] in ("done", "failed"):
                    break
                await asyncio.sleep(0.05)
            assert run["status"] == "done"
            active = await api.post("/api/project/main/runs/list", {"only_active": True})
            assert all(r["run_spec"]["run_name"] != "act-1" for r in active)
