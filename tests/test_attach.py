"""Attach bridge + real dev environments.

Parity: reference attach port-forward (api/_public/runs.py:244-351, attach.py:28)
and dev-env inactivity stop (configurators/dev.py, shim connections.go). The
bridge is WS-over-the-control-plane (server/services/attach.py): a local TCP
listener pipes through the server to the worker's port, and bridge activity
drives the dev env's inactivity clock.
"""

import asyncio

import aiohttp
import pytest

from dstack_tpu.api.attach import forward_port
from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import attach as attach_service
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.common import api_server
from tests.test_services import _drive, _drive_until_replicas

pytestmark = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)


class TestAttachBridge:
    async def test_dev_env_end_to_end(self, tmp_path):
        """Dev env boots a real IDE-backend socket; a local forwarded port reaches
        it through the WS bridge; after detach + idle timeout the env stops."""
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        attach_service.activity.reset()
        try:
            async with api_server() as api:
                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "dev",
                            "configuration": {
                                "type": "dev-environment",
                                "ide": "vscode",
                                "init": ["echo init-ran"],
                                "inactivity_duration": "1s",
                            },
                        }
                    },
                )
                await _drive_until_replicas(api, "dev", 1)

                server_url = str(api.client.make_url("")).rstrip("/")
                local_srv = await forward_port(
                    server_url, api.token, "main", "dev", 0, 8010
                )
                local_port = local_srv.sockets[0].getsockname()[1]

                # A REAL IDE serves through the forwarded port: with no
                # code-server and no egress in this env, the configurator's
                # chain lands on the repo's web IDE (dstack_tpu/ide.py), not a
                # bare http.server listing (retry while the socket binds).
                status = None
                ide_header = None
                async with aiohttp.ClientSession() as session:
                    for _ in range(60):
                        try:
                            async with session.get(
                                f"http://127.0.0.1:{local_port}/healthcheck",
                                timeout=aiohttp.ClientTimeout(total=3),
                            ) as resp:
                                status = resp.status
                                ide_header = resp.headers.get("X-Dstack-IDE")
                                health = await resp.json()
                                if status == 200:
                                    break
                        except aiohttp.ClientError:
                            pass
                        await asyncio.sleep(0.2)
                assert status == 200
                assert ide_header == "dstack-tpu", "expected the IDE, not http.server"
                assert health["ide"] == "dstack-tpu"

                # It is an editor, not a listing: create a file over the
                # bridge, read it back.
                async with aiohttp.ClientSession() as session:
                    async with session.put(
                        f"http://127.0.0.1:{local_port}/api/file?path=notes/hello.py",
                        data=b"print('edited in the dev env')",
                    ) as resp:
                        assert resp.status == 200
                    async with session.get(
                        f"http://127.0.0.1:{local_port}/api/file?path=notes/hello.py"
                    ) as resp:
                        assert await resp.text() == "print('edited in the dev env')"

                # While a bridge was open, inactivity was pinned at 0.
                run_row = await api.db.fetchone("SELECT * FROM runs WHERE run_name = 'dev'")
                # (connections are transient HTTP GETs; at least the registry saw them)
                assert attach_service.activity.inactivity_secs(run_row["id"]) is not None

                # Detach and idle out: the run stops itself.
                local_srv.close()
                await local_srv.wait_closed()
                await asyncio.sleep(1.3)
                for _ in range(60):
                    await _drive(api)
                    run = await api.post("/api/project/main/runs/get", {"run_name": "dev"})
                    if run["status"] in ("terminated", "failed", "done"):
                        break
                    await asyncio.sleep(0.1)
                assert run["status"] == "terminated"
                assert run["termination_reason"] == "inactivity_duration_exceeded"

                # inactivity_secs was persisted to the job for API display.
                job = await api.db.fetchone(
                    "SELECT * FROM jobs WHERE run_name = 'dev' ORDER BY submission_num DESC"
                )
                assert job["inactivity_secs"] is not None and job["inactivity_secs"] >= 1
        finally:
            logs_service.set_log_storage(None)

    async def test_bridge_rejects_unauthenticated(self, tmp_path):
        async with api_server() as api:
            async with aiohttp.ClientSession() as session:
                url = str(api.client.make_url("/api/project/main/runs/nope/attach/80"))
                async with session.get(url) as resp:
                    assert resp.status in (401, 403)

    async def test_never_attached_dev_env_times_out_from_start(self, tmp_path):
        """A dev env nobody ever attached to still idles out (clock anchored at
        job start)."""
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        attach_service.activity.reset()
        try:
            async with api_server() as api:
                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "lonely",
                            "configuration": {
                                "type": "dev-environment",
                                "inactivity_duration": "1s",
                            },
                        }
                    },
                )
                await _drive_until_replicas(api, "lonely", 1)
                await asyncio.sleep(1.2)
                for _ in range(60):
                    await _drive(api)
                    run = await api.post(
                        "/api/project/main/runs/get", {"run_name": "lonely"}
                    )
                    if run["status"] in ("terminated", "failed", "done"):
                        break
                    await asyncio.sleep(0.1)
                assert run["status"] == "terminated"
                assert run["termination_reason"] == "inactivity_duration_exceeded"
        finally:
            logs_service.set_log_storage(None)
