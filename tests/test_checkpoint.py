"""Async distributed checkpointing: round-trip bit-exactness, elastic
reshard-on-load, data-source resume, commit protocol, and the goodput
ledger's checkpoint/rework attribution.

Numerics on the virtual 8-device CPU mesh (conftest); the tiny config keeps
each jit under a second so the reshard test can afford two meshes."""

import json
import os
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

jax.config.update("jax_default_device", jax.devices("cpu")[0])

from dstack_tpu.workloads import data as data_lib
from dstack_tpu.workloads import train as train_lib
from dstack_tpu.workloads.checkpoint import CheckpointManager, leaf_entries
from dstack_tpu.workloads.config import get_config
from dstack_tpu.workloads.sharding import BATCH_SPEC, make_mesh


def tiny_cfg(**over):
    over.setdefault("max_seq_len", 32)
    over.setdefault("d_model", 64)
    over.setdefault("n_layers", 2)
    over.setdefault("n_heads", 4)
    over.setdefault("n_kv_heads", 2)
    over.setdefault("d_ff", 128)
    over.setdefault("vocab_size", 256)
    over.setdefault("remat", False)
    return get_config("test", **over)


class CaptureEmitter:
    def __init__(self):
        self.points = []

    def emit(self, kind, **fields):
        self.points.append({"kind": kind, **fields})

    def mark(self, event, **fields):
        self.emit("mark", event=event, **fields)

    def step(self, step, step_time_s, **fields):
        self.emit("step", step=step, step_time_s=step_time_s, **fields)

    def marks(self, event):
        return [p for p in self.points if p.get("event") == event]


def make_state(cfg, mesh, mu_dtype=None):
    optimizer = train_lib.make_optimizer(mu_dtype=mu_dtype)
    state = train_lib.init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
    return optimizer, state


class TestRoundTrip:
    def test_bit_exact_same_mesh(self, tmp_path):
        """Every leaf — params, both Adam moments (bf16 mu included), the
        step counter — restores bit-identically on the 8-dev mesh."""
        cfg = tiny_cfg()
        mesh = make_mesh(dp=2, fsdp=4, devices=jax.devices()[:8])
        optimizer, state = make_state(cfg, mesh, mu_dtype="bfloat16")
        # Perturb every leaf so the state is not all-init (distinct values per
        # leaf, nonzero moments) without paying a train-step compile.
        with mesh:
            counter = iter(range(1, 10_000))

            def bump(x):
                if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.floating):
                    return x + jnp.asarray(next(counter) * 0.125, x.dtype)
                return x

            state = jax.tree.map(bump, state)
            state.step = jnp.int32(1)

        emitter = CaptureEmitter()
        mgr = CheckpointManager(str(tmp_path), telemetry=emitter,
                                process_index=0, process_count=1)
        mgr.save(1, state, data_offset=1, mesh_shape=dict(mesh.shape), block=True)
        assert mgr.latest_step() == 1
        assert mgr.save_errors == 0, mgr.last_error

        _, template = make_state(cfg, mesh, mu_dtype="bfloat16")
        restored, manifest = mgr.restore(template)
        assert manifest["step"] == 1
        assert manifest["data_offset"] == 1
        assert manifest["mesh"] == dict(mesh.shape)
        for (key, a), (_, b) in zip(leaf_entries(state), leaf_entries(restored)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=key
            )
            if hasattr(a, "dtype"):
                assert np.asarray(b).dtype == np.asarray(a).dtype, key
        # The telemetry bracket landed: start + end (with the measured
        # blocked window) + the writer's durability mark.
        assert emitter.marks("checkpoint_start")
        end = emitter.marks("checkpoint_end")
        assert end and end[0]["blocked_s"] >= 0
        assert emitter.marks("checkpoint_saved")

    def test_restored_shardings_match_template(self, tmp_path):
        cfg = tiny_cfg()
        mesh = make_mesh(dp=2, fsdp=4, devices=jax.devices()[:8])
        _, state = make_state(cfg, mesh)
        mgr = CheckpointManager(str(tmp_path), process_index=0, process_count=1)
        mgr.save(3, state, block=True)
        _, template = make_state(cfg, mesh)
        restored, _ = mgr.restore(template)
        for (key, t), (_, r) in zip(leaf_entries(template), leaf_entries(restored)):
            if isinstance(t, jax.Array):
                assert r.sharding == t.sharding, key

    def test_structure_mismatch_raises(self, tmp_path):
        cfg = tiny_cfg()
        mesh = make_mesh(dp=2, fsdp=4, devices=jax.devices()[:8])
        _, state = make_state(cfg, mesh)
        mgr = CheckpointManager(str(tmp_path), process_index=0, process_count=1)
        mgr.save(1, state, block=True)
        with pytest.raises(ValueError, match="structure mismatch"):
            mgr.restore({"just": jnp.zeros((2,))})

    def test_shape_mismatch_raises(self, tmp_path):
        cfg = tiny_cfg()
        mesh = make_mesh(dp=2, fsdp=4, devices=jax.devices()[:8])
        _, state = make_state(cfg, mesh)
        mgr = CheckpointManager(str(tmp_path), process_index=0, process_count=1)
        mgr.save(1, state, block=True)
        _, template = make_state(tiny_cfg(d_ff=256), mesh)
        with pytest.raises(ValueError, match="shape"):
            mgr.restore(template)


class TestElasticReshard:
    def test_save_dp2_fsdp4_resume_dp4_fsdp2_loss_identical(self, tmp_path):
        """The acceptance criterion: a run checkpointed on one topology
        resumes on a different one with the SAME loss trajectory as an
        uninterrupted run — asserted step by step, not eyeballed."""
        cfg = tiny_cfg(dtype="float32", param_dtype="float32")
        devices = jax.devices()[:8]
        mesh_a = make_mesh(dp=2, fsdp=4, devices=devices)
        mesh_b = make_mesh(dp=4, fsdp=2, devices=devices)
        batch, seq, total, cut = 8, 32, 6, 3
        # One optimizer + one jitted step per mesh, shared by the reference
        # and interrupted runs (same jit object -> one compile each).
        optimizer = train_lib.make_optimizer()
        step_fns = {
            mesh_a: train_lib.make_train_step(cfg, optimizer, mesh_a),
            mesh_b: train_lib.make_train_step(cfg, optimizer, mesh_b),
        }

        def run_steps(mesh, state, start, stop, losses):
            with mesh:
                feed = data_lib.input_pipeline(
                    mesh, BATCH_SPEC, batch, seq, cfg.vocab_size,
                    prefetch=0, start_batch=start,
                )
                try:
                    for step in range(start + 1, stop + 1):
                        tok, tgt = next(feed)
                        state, m = step_fns[mesh](state, tok, tgt)
                        losses[step] = float(m["loss"])
                finally:
                    feed.close()
            return state

        def fresh_state(mesh):
            return train_lib.init_train_state(
                cfg, jax.random.PRNGKey(0), optimizer, mesh
            )

        # Uninterrupted reference on mesh A.
        ref_losses = {}
        run_steps(mesh_a, fresh_state(mesh_a), 0, total, ref_losses)

        # Interrupted: steps 1..cut on mesh A, checkpoint, resume on mesh B.
        losses = {}
        state_a = run_steps(mesh_a, fresh_state(mesh_a), 0, cut, losses)
        mgr = CheckpointManager(str(tmp_path), process_index=0, process_count=1)
        mgr.save(cut, state_a, data_offset=cut, mesh_shape=dict(mesh_a.shape),
                 block=True)

        restored, manifest = mgr.restore(fresh_state(mesh_b))
        assert manifest["mesh"] == dict(mesh_a.shape)  # provably cross-mesh
        # The restored params live under mesh B's sharding rules now.
        w = restored.params["wq"]
        assert w.sharding.mesh.shape == mesh_b.shape
        run_steps(mesh_b, restored, cut, total, losses)

        assert set(losses) == set(ref_losses)
        for step in sorted(ref_losses):
            assert losses[step] == ref_losses[step], (
                f"step {step}: {losses[step]} != {ref_losses[step]}"
            )


class TestDataSourceResume:
    def test_synthetic_seek_no_replay_no_skip(self):
        fresh = data_lib.synthetic_batches(
            100, 8, 16, process_index=0, process_count=1
        )
        want = [next(fresh)[0] for _ in range(10)]
        resumed = data_lib.synthetic_batches(
            100, 8, 16, process_index=0, process_count=1, start_batch=4
        )
        got = [next(resumed)[0] for _ in range(6)]
        for i, g in enumerate(got):
            np.testing.assert_array_equal(g, want[4 + i])

    def test_synthetic_hosts_stay_disjoint_after_seek(self):
        a = next(data_lib.synthetic_batches(
            100, 8, 16, process_index=0, process_count=2, start_batch=3
        ))[0]
        b = next(data_lib.synthetic_batches(
            100, 8, 16, process_index=1, process_count=2, start_batch=3
        ))[0]
        assert not np.array_equal(a, b)

    def test_token_file_seek_no_replay_no_skip(self, tmp_path):
        path = tmp_path / "tokens.bin"
        np.arange(4 * 9 * 4, dtype=np.uint16).tofile(path)  # 16 windows of 9
        fresh = data_lib.token_file_batches(
            str(path), global_batch=4, seq=8,
            process_index=0, process_count=1,
        )
        want = [next(fresh)[0] for _ in range(8)]  # wraps after 4 batches
        resumed = data_lib.token_file_batches(
            str(path), global_batch=4, seq=8,
            process_index=0, process_count=1, start_batch=3,
        )
        got = [next(resumed)[0] for _ in range(5)]
        for i, g in enumerate(got):
            np.testing.assert_array_equal(g, want[3 + i])

    def test_token_file_seek_past_wrap(self, tmp_path):
        path = tmp_path / "tokens.bin"
        np.arange(4 * 9 * 4, dtype=np.uint16).tofile(path)
        fresh = data_lib.token_file_batches(
            str(path), global_batch=4, seq=8,
            process_index=0, process_count=1,
        )
        want = [next(fresh)[0] for _ in range(7)]
        resumed = data_lib.token_file_batches(
            str(path), global_batch=4, seq=8,
            process_index=0, process_count=1, start_batch=6,
        )
        np.testing.assert_array_equal(next(resumed)[0], want[6])

    def test_token_file_noloop_respects_offset(self, tmp_path):
        path = tmp_path / "tokens.bin"
        np.arange(4 * 9 * 4, dtype=np.uint16).tofile(path)
        it = data_lib.token_file_batches(
            str(path), global_batch=4, seq=8, loop=False,
            process_index=0, process_count=1, start_batch=2,
        )
        assert len(list(it)) == 2  # 4 per pass, 2 already consumed


class TestCommitProtocol:
    def test_incomplete_checkpoint_ignored(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), process_index=0, process_count=1)
        state = {"x": jnp.arange(8.0)}
        mgr.save(5, state, block=True)
        # A later step whose commit marker is missing (killed mid-write).
        torn = tmp_path / "step-00000009"
        torn.mkdir()
        (torn / "manifest.json").write_text(json.dumps(
            {"step": 9, "process_count": 1, "leaves": []}
        ))
        (torn / "shard-00000.npz").write_bytes(b"garbage")
        assert mgr.latest_step() == 5
        restored, manifest = mgr.restore({"x": jnp.zeros(8)})
        assert manifest["step"] == 5

    def test_prune_keeps_latest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2,
                                process_index=0, process_count=1)
        state = {"x": jnp.arange(4.0)}
        for step in (1, 2, 3, 4):
            mgr.save(step, state, block=True)
        assert mgr.complete_steps() == [3, 4]

    def test_multihost_restore_merges_shards(self, tmp_path):
        """Two processes' shard files (each holding half the rows) rebuild
        the full array; a missing host's file fails loudly instead of
        restoring zeros where that host's rows were."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh(dp=2, fsdp=4, devices=jax.devices()[:8])
        full = jnp.arange(64.0).reshape(8, 8)
        arr = jax.device_put(full, NamedSharding(mesh, P(("dp", "fsdp"), None)))
        m0 = CheckpointManager(str(tmp_path), process_index=0, process_count=2)
        m1 = CheckpointManager(str(tmp_path), process_index=1, process_count=2)
        m0.save(1, {"w": arr}, block=True)
        assert m1.latest_step() is None  # only host 0 committed so far
        m1.save(1, {"w": arr}, block=True)
        assert m1.latest_step() == 1
        # Carve the single-process stand-in into true per-host files: host 0
        # keeps the shards for rows 0..3, host 1 rows 4..7.
        step_dir = tmp_path / "step-00000001"
        for pi, keep in ((0, range(0, 4)), (1, range(4, 8))):
            f = step_dir / f"shard-{pi:05d}.npz"
            with np.load(f) as z:
                kept = {
                    k: z[k] for k in z.files
                    if int(k.split("@")[1].split(":")[0]) in keep
                }
            with open(f, "wb") as fh:
                np.savez(fh, **kept)
        template = {"w": jax.device_put(jnp.zeros((8, 8)), arr.sharding)}
        restored, _ = m1.restore(template)
        np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(full))
        # Host 1's file gone -> its rows are uncovered -> loud failure.
        (step_dir / "shard-00001.npz").unlink()
        with pytest.raises(ValueError, match="cover"):
            m1.restore(template)

    def test_save_error_degrades_never_raises(self, tmp_path):
        emitter = CaptureEmitter()
        target = tmp_path / "dir"
        target.mkdir()
        blocker = target / "step-00000001"
        blocker.write_text("a file where the step dir must go")
        mgr = CheckpointManager(str(target), telemetry=emitter,
                                process_index=0, process_count=1)
        mgr.save(1, {"x": jnp.zeros(4)}, block=True)
        assert mgr.save_errors == 1
        assert emitter.marks("checkpoint_error")
        # The bracket still closes: a dangling checkpoint_start would bill
        # wall clock to checkpoint_s in the ledger until the window edge.
        ends = emitter.marks("checkpoint_end")
        assert len(ends) == len(emitter.marks("checkpoint_start"))
        assert mgr.latest_step() is None
        # The manager still works after the failure.
        mgr.save(2, {"x": jnp.zeros(4)}, block=True)
        assert mgr.latest_step() == 2

    def test_snapshot_stage_failure_closes_bracket(self, tmp_path):
        """A failure BEFORE the write thread (device->host stage) must also
        emit checkpoint_end — the ledger would otherwise attribute wall
        clock to checkpoint_s until the window edge."""

        class Unsnapshotable:
            shape = (2,)
            dtype = np.float32

            def __array__(self, *a, **k):
                raise RuntimeError("host OOM")

        emitter = CaptureEmitter()
        mgr = CheckpointManager(str(tmp_path), telemetry=emitter,
                                process_index=0, process_count=1)
        mgr.save(1, {"bad": Unsnapshotable()}, block=True)
        assert mgr.save_errors == 1
        assert emitter.marks("checkpoint_error")
        ends = emitter.marks("checkpoint_end")
        assert len(ends) == 1 and ends[0].get("failed") is True


class TestTrainHooks:
    def test_checkpoint_hook_saves_on_cadence(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=8,
                                process_index=0, process_count=1)
        box = {"state": {"x": jnp.arange(2.0)}}
        hook = train_lib.make_checkpoint_hook(
            mgr, every=2, total_steps=10, get_state=lambda: box["state"]
        )
        for step in range(1, 7):
            hook(step, None)
        mgr.close()
        assert mgr.complete_steps() == [2, 4, 6]
        assert mgr.read_manifest(4)["data_offset"] == 4

    def test_crash_hook_fires_once_then_respects_resume(self, monkeypatch):
        monkeypatch.setenv("DSTACK_TPU_TRAIN_CRASH_AT_STEP", "3")
        hook = train_lib.make_checkpoint_hook(
            None, every=0, total_steps=10, get_state=lambda: None, resumed=False
        )
        hook(2, None)
        with pytest.raises(SystemExit):
            hook(3, None)
        resumed_hook = train_lib.make_checkpoint_hook(
            None, every=0, total_steps=10, get_state=lambda: None, resumed=True
        )
        resumed_hook(3, None)  # a resumed run sails past the crash step

    def test_timed_loop_resumes_numbering(self, capsys):
        seen = []
        stats = train_lib._timed_loop(
            6, 2, 4, lambda: jnp.float32(0.5), start_step=4,
            on_step=lambda s, _l: seen.append(s),
        )
        out = capsys.readouterr().out
        assert "step 5/6" in out
        assert seen == [5, 6]
        assert "done: 2 steps" in out


class TestGoodputLedger:
    def _iso(self, off):
        import datetime

        from dstack_tpu.utils.common import to_iso

        base = datetime.datetime(2026, 8, 1, tzinfo=datetime.timezone.utc)
        return to_iso(base + datetime.timedelta(seconds=off))

    def test_checkpoint_bucket_from_marks(self):
        from dstack_tpu.server.services.metrics import compute_goodput

        points = [
            {"ts": self._iso(0), "kind": "mark", "event": "run_start"},
            {"ts": self._iso(1), "kind": "step", "step": 2, "step_time_s": 1.0},
            {"ts": self._iso(1.1), "kind": "mark", "event": "checkpoint_start"},
            {"ts": self._iso(1.6), "kind": "mark", "event": "checkpoint_end",
             "blocked_s": 0.5},
            {"ts": self._iso(2.6), "kind": "step", "step": 3, "step_time_s": 1.0},
        ]
        ledger = compute_goodput(points)
        assert ledger["checkpoint_s"] == 0.5
        assert ledger["steps"] == 2
        assert ledger["rework_s"] == 0.0
        # checkpoint_s no longer hides in other_s.
        assert ledger["other_s"] < 0.2

    def test_checkpoint_bracket_without_measured_value(self):
        from dstack_tpu.server.services.metrics import compute_goodput

        points = [
            {"ts": self._iso(0), "kind": "step", "step": 1, "step_time_s": 0.5},
            {"ts": self._iso(1), "kind": "mark", "event": "checkpoint_start"},
            {"ts": self._iso(1.7), "kind": "mark", "event": "checkpoint_end"},
            {"ts": self._iso(2), "kind": "step", "step": 2, "step_time_s": 0.3},
        ]
        ledger = compute_goodput(points)
        assert ledger["checkpoint_s"] == pytest.approx(0.7)

    def test_rework_debits_redone_steps(self):
        from dstack_tpu.server.services.metrics import compute_goodput

        points = [
            {"ts": self._iso(0), "kind": "mark", "event": "run_start"},
            {"ts": self._iso(1), "kind": "step", "step": 2, "step_time_s": 1.0},
            {"ts": self._iso(2), "kind": "step", "step": 3, "step_time_s": 1.0},
            # preemption: restart from scratch
            {"ts": self._iso(10), "kind": "mark", "event": "run_start"},
            {"ts": self._iso(11), "kind": "step", "step": 2, "step_time_s": 1.0},
            {"ts": self._iso(12), "kind": "step", "step": 3, "step_time_s": 1.0},
            {"ts": self._iso(13), "kind": "step", "step": 4, "step_time_s": 1.0},
        ]
        ledger = compute_goodput(points)
        assert ledger["steps"] == 3          # net progress: 2, 3, 4
        assert ledger["productive_s"] == 3.0
        assert ledger["rework_s"] == 2.0     # redone 2 and 3
        assert ledger["restart_s"] == 8.0    # the gap before the 2nd run_start
        assert ledger["ratio"] == pytest.approx(3.0 / 13.0, abs=1e-3)

    def test_resume_past_frontier_is_all_productive(self):
        from dstack_tpu.server.services.metrics import compute_goodput

        points = [
            {"ts": self._iso(0), "kind": "step", "step": 5, "step_time_s": 1.0},
            {"ts": self._iso(5), "kind": "mark", "event": "restart"},
            {"ts": self._iso(6), "kind": "step", "step": 6, "step_time_s": 1.0},
        ]
        ledger = compute_goodput(points)
        assert ledger["rework_s"] == 0.0
        assert ledger["steps"] == 2
        # The gap between the dead process's last point (t=0) and the restart.
        assert ledger["restart_s"] == pytest.approx(5.0)
