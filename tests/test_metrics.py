"""Metrics pipeline: agent sample -> process_metrics loop -> job_metrics_points ->
metrics API + CLI shape -> Prometheus export -> TTL sweep.

Parity: reference background/tasks/process_metrics.py, services/metrics.py
(cpu % from consecutive counter samples), routers/metrics.py, prometheus.py:31.
The TPU sample rides the agent's runtime scrape (runner/src/tpu_metrics.cpp),
the DCGM-exporter analog."""

import asyncio
import datetime
import json

import pytest
from aiohttp import web

from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.server.services import metrics as metrics_service
from dstack_tpu.utils.common import now_utc, to_iso
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.common import api_server

pytestmark = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)


async def _drive(api, passes=3):
    for _ in range(passes):
        await tasks.process_submitted_jobs(api.db)
        await tasks.process_running_jobs(api.db)
        await tasks.process_runs(api.db)
        await asyncio.sleep(0.1)


class TestMetricsPipeline:
    async def test_collect_query_prometheus_and_sweep(self, tmp_path):
        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        try:
            async with api_server() as api:
                spec = {
                    "run_spec": {
                        "run_name": "m-run",
                        "configuration": {
                            "type": "task",
                            # Burn a little CPU so the usage counter advances.
                            "commands": [
                                "python3 -c \"import time; t=time.time()\n"
                                "while time.time()-t < 6: sum(range(2000))\""
                            ],
                        },
                    }
                }
                await api.post("/api/project/main/runs/submit", spec)
                for _ in range(60):
                    await _drive(api, passes=1)
                    run = await api.post("/api/project/main/runs/get", {"run_name": "m-run"})
                    if run["status"] == "running":
                        break
                else:
                    raise AssertionError("run never reached running")

                # Two collection passes ~1s apart -> at least 2 points -> cpu %.
                n1 = await metrics_service.collect_job_metrics(api.db)
                await asyncio.sleep(1.2)
                n2 = await metrics_service.collect_job_metrics(api.db)
                assert n1 == 1 and n2 == 1

                res = await api.post(
                    "/api/project/main/metrics/job", {"run_name": "m-run", "limit": 10}
                )
                assert len(res["points"]) >= 1
                point = res["points"][0]
                assert point["memory_usage_bytes"] > 0
                assert point["cpu_usage_percent"] >= 0.0

                # Prometheus exposition reflects the run and the sample.
                resp = await api.client.get("/metrics")
                text = await resp.text()
                assert resp.status == 200
                assert 'dstack_tpu_runs_total{project="main",status="running"} 1' in text
                assert "dstack_tpu_job_cpu_seconds_total" in text
                assert 'run="m-run"' in text

                # TTL sweep: age the points out and confirm deletion.
                old = to_iso(now_utc() - datetime.timedelta(hours=2))
                await api.db.execute("UPDATE job_metrics_points SET timestamp = ?", (old,))
                await metrics_service.sweep_metrics(api.db)
                left = await api.db.fetchone("SELECT COUNT(*) AS n FROM job_metrics_points")
                assert left["n"] == 0

                # Cleanup: stop the run (kills the local runner process).
                await api.post("/api/project/main/runs/stop", {"runs_names": ["m-run"], "abort": True})
                for _ in range(40):
                    await tasks.process_terminating_jobs(api.db)
                    await tasks.process_runs(api.db)
                    run = await api.post("/api/project/main/runs/get", {"run_name": "m-run"})
                    if run["status"] in ("terminated", "aborted", "failed", "done"):
                        break
                    await asyncio.sleep(0.1)
        finally:
            logs_service.set_log_storage(None)

    async def test_unreachable_runner_does_not_fail_pass(self):
        async with api_server() as api:
            # A running job whose agent endpoint is dead (default project + admin).
            proj = await api.db.fetchone("SELECT * FROM projects LIMIT 1")
            await api.db.execute(
                "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at, status,"
                " run_spec) VALUES ('r1', ?, ?, 'dead-run', '2026-01-01', 'running', '{}')",
                (proj["id"], proj["owner_id"]),
            )
            jpd = {
                "backend": "local",
                "instance_type": {"name": "local", "resources": {"cpus": 1, "memory_gb": 1, "disk_gb": 1}},
                "instance_id": "i-dead",
                "hostname": "127.0.0.1",
                "region": "local",
                "ssh_port": 0,
                "backend_data": json.dumps({"runner_port": 1}),  # nothing listens
            }
            await api.db.execute(
                "INSERT INTO jobs (id, project_id, run_id, run_name, job_spec, status,"
                " submitted_at, job_provisioning_data) VALUES ('j1', ?, 'r1', 'dead-run',"
                " '{}', 'running', '2026-01-01', ?)",
                (proj["id"], json.dumps(jpd)),
            )
            n = await metrics_service.collect_job_metrics(api.db)
            assert n == 0  # unreachable — skipped, no exception


class TestTpuRuntimeScrape:
    async def test_agent_reports_tpu_sample(self, tmp_path):
        """The agent scrapes a Prometheus TPU runtime endpoint and reduces per-chip
        series to one host sample."""
        exposition = "\n".join(
            [
                "# HELP duty_cycle TPU duty cycle",
                "# TYPE duty_cycle gauge",
                'duty_cycle{accelerator_id="0"} 80',
                'duty_cycle{accelerator_id="1"} 60',
                'memory_used{accelerator_id="0"} 1000000',
                'memory_used{accelerator_id="1"} 2000000',
                'memory_total{accelerator_id="0"} 16000000',
                'memory_total{accelerator_id="1"} 16000000',
                "",
            ]
        )

        async def metrics_handler(request):
            return web.Response(text=exposition, content_type="text/plain")

        app = web.Application()
        app.router.add_get("/metrics", metrics_handler)
        runner_http = web.AppRunner(app)
        await runner_http.setup()
        site = web.TCPSite(runner_http, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        import os
        import subprocess
        import tempfile

        from tests.test_container import _LISTEN_RE

        env = dict(os.environ)
        env["DSTACK_TPU_RUNTIME_METRICS_URL"] = f"http://127.0.0.1:{port}/metrics"
        proc = subprocess.Popen(
            [
                find_runner_binary(),
                "--host", "127.0.0.1",
                "--port", "0",
                "--base-dir", tempfile.mkdtemp(),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        try:
            line = proc.stdout.readline().decode()
            m = _LISTEN_RE.search(line)
            assert m, line
            from dstack_tpu.server.services.runner.client import RunnerClient

            client = RunnerClient("127.0.0.1", int(m.group(1)))
            sample = await client.metrics()
            tpu = sample["tpu"]
            assert tpu["duty_cycle_percent"] == 70.0  # averaged across chips
            assert tpu["hbm_usage_bytes"] == 3000000  # summed
            assert tpu["hbm_total_bytes"] == 32000000
        finally:
            proc.kill()
            proc.wait()
            await runner_http.cleanup()
