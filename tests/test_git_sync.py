"""Git-based code sync: clone + pinned checkout + working-tree diff apply.

Parity: reference runner executor/repo.go + repo/{manager,diff}.go — the blob
channel carries only the DIFF, so repository size never hits the upload cap.
Exercised against the real C++ agent with a real local git remote."""

import asyncio
import subprocess

import pytest

from dstack_tpu.core.models.runs import ClusterInfo
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.test_container import Runner, _job_spec, _pull_until_terminal, spawn_runner

pytestmark = pytest.mark.skipif(
    find_runner_binary() is None, reason="native runner binary unavailable"
)


def _git(cwd, *args):
    subprocess.run(
        ["git", *args], cwd=cwd, check=True, capture_output=True,
        env={"PATH": "/usr/bin:/bin", "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
             "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
             "HOME": str(cwd)},
    )


@pytest.fixture()
def git_remote(tmp_path):
    """A 'remote' (local bare repo) with one commit, plus a diff against it."""
    work = tmp_path / "work"
    work.mkdir()
    _git(work, "init", "-q", "-b", "main")
    (work / "tracked.txt").write_text("tracked-content\n")
    (work / "script.py").write_text("print('original')\n")
    _git(work, "add", ".")
    _git(work, "commit", "-q", "-m", "init")
    bare = tmp_path / "origin.git"
    _git(work, "clone", "-q", "--bare", str(work), str(bare))
    commit = subprocess.run(
        ["git", "rev-parse", "HEAD"], cwd=work, capture_output=True, text=True
    ).stdout.strip()
    # A working-tree change that exists ONLY as a diff.
    (work / "script.py").write_text("print('patched-by-diff')\n")
    diff = subprocess.run(
        ["git", "diff", "HEAD", "--binary"], cwd=work, capture_output=True
    ).stdout
    return {"clone_url": str(bare), "commit": commit, "diff": diff}


class TestGitSync:
    async def test_clone_checkout_and_diff_apply(self, tmp_path, git_remote):
        runner = spawn_runner("never", str(tmp_path / "nosock"))
        try:
            spec = _job_spec(["cat tracked.txt", "python3 script.py"], image="")
            await runner.client.submit(
                spec,
                ClusterInfo(),
                run_spec={
                    "repo_data": {
                        "mode": "git",
                        "clone_url": git_remote["clone_url"],
                        "commit": git_remote["commit"],
                    }
                },
            )
            await runner.client.upload_code(git_remote["diff"])
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "done", final
            assert "tracked-content" in final["all_logs"]  # cloned + checked out
            assert "patched-by-diff" in final["all_logs"]  # diff applied
            assert "checked out" in final["all_logs"]
        finally:
            runner.kill()

    async def test_clone_without_diff(self, tmp_path, git_remote):
        runner = spawn_runner("never", str(tmp_path / "nosock"))
        try:
            spec = _job_spec(["python3 script.py"], image="")
            await runner.client.submit(
                spec,
                ClusterInfo(),
                run_spec={
                    "repo_data": {
                        "mode": "git",
                        "clone_url": git_remote["clone_url"],
                        "commit": git_remote["commit"],
                    }
                },
            )
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "done", final
            assert "original" in final["all_logs"]  # pinned commit, no diff
        finally:
            runner.kill()

    async def test_bad_remote_falls_back_to_archive(self, tmp_path):
        import tarfile

        payload = tmp_path / "payload"
        payload.mkdir()
        (payload / "fallback.txt").write_text("archive-wins\n")
        tar_path = tmp_path / "code.tar.gz"
        with tarfile.open(tar_path, "w:gz") as tf:
            tf.add(payload / "fallback.txt", arcname="fallback.txt")

        runner = spawn_runner("never", str(tmp_path / "nosock"))
        try:
            spec = _job_spec(["cat fallback.txt"], image="")
            await runner.client.submit(
                spec,
                ClusterInfo(),
                run_spec={
                    "repo_data": {
                        "mode": "git",
                        "clone_url": str(tmp_path / "does-not-exist.git"),
                        "commit": "deadbeef",
                    }
                },
            )
            await runner.client.upload_code(tar_path.read_bytes())
            await runner.client.run_job()
            final = await _pull_until_terminal(runner.client)
            assert final["state"] == "done", final
            assert "archive-wins" in final["all_logs"]
            assert "falling back" in final["all_logs"]
        finally:
            runner.kill()
