"""Gateway subsystem: appliance routing, provisioning loop, service sync,
OpenAI model routing (both in-server and on the appliance).

Parity: reference proxy/gateway/app.py, gateway/services/nginx.py:75-110,
registry.py:34-373, process_gateways.py. The appliance is a real process
(`python -m dstack_tpu.gateway`) provisioned by the local backend exactly like
runner agents; on gcp it is a GCE VM (scripted-transport test)."""

import asyncio
import json

import aiohttp
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from dstack_tpu.gateway.app import create_app
from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import logs as logs_service
from dstack_tpu.server.services import proxy as proxy_service
from dstack_tpu.utils.runner_binary import find_runner_binary
from tests.common import api_server


async def _echo_app_server(marker: str):
    """A tiny upstream that echoes path + marker (stands in for a model server)."""

    async def handler(request):
        body = await request.read()
        return web.json_response(
            {"marker": marker, "path": request.path_qs, "body": body.decode() or None}
        )

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, port


class TestApplianceRouting:
    async def test_register_route_model_and_domain(self):
        up1, port1 = await _echo_app_server("r1")
        up2, port2 = await _echo_app_server("r2")
        gw_client = TestClient(TestServer(create_app("tok")))
        await gw_client.start_server()
        auth = {"Authorization": "Bearer tok"}
        try:
            # Registry requires the token.
            resp = await gw_client.post("/api/registry/register", json={})
            assert resp.status == 401

            entry = {
                "project": "main",
                "run_name": "llama",
                "domain": "llama.example.com",
                "model": {"name": "llama-70b", "prefix": "/v1"},
                "replicas": [
                    {"host": "127.0.0.1", "port": port1},
                    {"host": "127.0.0.1", "port": port2},
                ],
            }
            resp = await gw_client.post("/api/registry/register", json=entry, headers=auth)
            assert resp.status == 200

            # Path routing round-robins both replicas.
            markers = set()
            for _ in range(4):
                resp = await gw_client.get("/services/main/llama/generate?x=1")
                assert resp.status == 200
                data = await resp.json()
                markers.add(data["marker"])
                assert data["path"] == "/generate?x=1"
            assert markers == {"r1", "r2"}

            # OpenAI model routing: body["model"] selects the service, the
            # request lands on the model prefix.
            resp = await gw_client.post(
                "/models/main/v1/chat/completions",
                json={"model": "llama-70b", "messages": []},
            )
            assert resp.status == 200
            data = await resp.json()
            assert data["path"] == "/v1/chat/completions"
            assert json.loads(data["body"])["model"] == "llama-70b"

            resp = await gw_client.get("/models/main/v1/models")
            listing = await resp.json()
            assert [m["id"] for m in listing["data"]] == ["llama-70b"]

            resp = await gw_client.post(
                "/models/main/v1/chat/completions", json={"model": "ghost"}
            )
            assert resp.status == 404

            # Domain routing via the Host header.
            resp = await gw_client.get("/infer", headers={"Host": "llama.example.com"})
            assert (await resp.json())["path"] == "/infer"
            resp = await gw_client.get("/infer", headers={"Host": "other.example.com"})
            assert resp.status == 404

            # Request stats: path (4) + model completion (1) + domain (1)
            # admitted requests are bucketed for the autoscaler pull; listing
            # GETs, unknown models, and unknown hosts don't count.
            resp = await gw_client.get("/api/registry/stats", headers=auth)
            assert resp.status == 200
            payload = await resp.json()
            assert isinstance(payload["now"], float)  # for skew rebasing
            svc_stats = payload["services"]
            assert svc_stats[0]["run_name"] == "llama"
            assert sum(svc_stats[0]["buckets"].values()) == 6

            # Re-registration (replica churn) keeps the window.
            await gw_client.post("/api/registry/register", json=entry, headers=auth)
            resp = await gw_client.get("/api/registry/stats", headers=auth)
            assert sum((await resp.json())["services"][0]["buckets"].values()) == 6

            # Scaled-to-zero: a request against an empty replica set 503s but
            # still RECORDS — that demand is what wakes the service.
            entry_zero = dict(entry, replicas=[])
            await gw_client.post("/api/registry/register", json=entry_zero, headers=auth)
            resp = await gw_client.get("/services/main/llama/generate")
            assert resp.status == 503
            resp = await gw_client.get("/api/registry/stats", headers=auth)
            assert sum((await resp.json())["services"][0]["buckets"].values()) == 7

            # Unregister removes the routes.
            await gw_client.post(
                "/api/registry/unregister",
                json={"project": "main", "run_name": "llama"},
                headers=auth,
            )
            resp = await gw_client.get("/services/main/llama/x")
            assert resp.status == 404
        finally:
            await gw_client.close()
            await up1.cleanup()
            await up2.cleanup()


@pytest.mark.skipif(find_runner_binary() is None, reason="native runner binary unavailable")
class TestGatewayE2E:
    async def test_provision_sync_and_route(self, tmp_path):
        """Full path: create a gateway (local backend spawns the real appliance),
        run a service with a registered model, process_gateways syncs it, traffic
        routes THROUGH the appliance to the service replica; the in-server
        /proxy/models route serves the same model."""
        from tests.test_services import _APP, _drive_until_replicas, _stop_run

        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        proxy_service.stats.reset()
        try:
            async with api_server() as api:
                gw = await api.post(
                    "/api/project/main/gateways/create",
                    {"configuration": {"type": "gateway", "backend": "local", "region": "local", "name": "gw"}},
                )
                assert gw["status"] == "submitted"
                await tasks.process_gateways(api.db)
                gws = await api.post("/api/project/main/gateways/list")
                assert gws[0]["status"] == "running"
                assert gws[0]["ip_address"] == "127.0.0.1"
                assert gws[0]["default"] is True

                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "msvc",
                            "configuration": {
                                "type": "service",
                                "commands": [_APP],
                                "port": 8000,
                                "model": "pong-model",
                            },
                        }
                    },
                )
                await _drive_until_replicas(api, "msvc", 1)
                await tasks.process_gateways(api.db)  # sync pass

                row = await api.db.fetchone("SELECT * FROM gateways")
                pd = json.loads(row["provisioning_data"])
                endpoint = f"http://127.0.0.1:{pd['port']}"
                async with aiohttp.ClientSession() as session:
                    # Wait for the service socket, then route through the appliance.
                    body = None
                    for _ in range(50):
                        try:
                            async with session.get(
                                f"{endpoint}/services/main/msvc/ping"
                            ) as resp:
                                if resp.status == 200:
                                    body = await resp.text()
                                    break
                        except aiohttp.ClientError:
                            pass
                        await asyncio.sleep(0.2)
                    assert body == "pong:/ping"

                    # The model is served through the appliance's OpenAI surface.
                    async with session.get(f"{endpoint}/models/main/v1/models") as resp:
                        listing = await resp.json()
                    assert [m["id"] for m in listing["data"]] == ["pong-model"]

                # ... and through the in-server model route.
                resp = await api.client.post(
                    "/proxy/models/main/v1/chat/completions",
                    json={"model": "pong-model"},
                    headers={"Authorization": f"Bearer {api.token}"},
                )
                assert resp.status == 200
                assert (await resp.text()).startswith("pong:/v1/chat/completions")

                resp = await api.client.get(
                    "/proxy/models/main/v1/models",
                    headers={"Authorization": f"Bearer {api.token}"},
                )
                assert [m["id"] for m in (await resp.json())["data"]] == ["pong-model"]

                # Gateway-routed traffic feeds the autoscaler: the next sync
                # pass pulls the appliance's request buckets into the server's
                # stats window, so scaling sees demand that never touched the
                # in-server proxy.
                run_row = await api.db.fetchone(
                    "SELECT id FROM runs WHERE run_name = 'msvc'"
                )
                proxy_service.stats.reset()  # drop in-server-proxy counts
                assert proxy_service.stats.rps(run_row["id"], window=600.0) == 0
                await tasks.process_gateways(api.db)
                assert proxy_service.stats.rps(run_row["id"], window=600.0) > 0

                # Stop the run; the next sync unregisters it from the appliance.
                await _stop_run(api, "msvc")
                await tasks.process_gateways(api.db)
                async with aiohttp.ClientSession() as session:
                    async with session.get(
                        f"{endpoint}/services/main/msvc/ping"
                    ) as resp:
                        assert resp.status == 404

                # Delete the gateway: the appliance process dies.
                await api.post("/api/project/main/gateways/delete", {"names": ["gw"]})
                await asyncio.sleep(0.3)
                async with aiohttp.ClientSession() as session:
                    with pytest.raises(aiohttp.ClientError):
                        async with session.get(f"{endpoint}/healthcheck"):
                            pass
        finally:
            logs_service.set_log_storage(None)


class TestGcpGatewayProvisioning:
    async def test_create_gateway_vm_via_rest(self):
        from dstack_tpu.core.models.configurations import GatewayConfiguration
        from tests.test_gcp_backend import FakeTransport, make_gcp

        t = FakeTransport()
        t.on(
            "GET",
            "/instances/",
            {
                "networkInterfaces": [
                    {"networkIP": "10.0.0.5", "accessConfigs": [{"natIP": "34.1.2.3"}]}
                ]
            },
        )
        gcp = make_gcp(t)
        conf = GatewayConfiguration(type="gateway", backend="gcp", region="us-east5")
        pd = await gcp.create_gateway(conf, "gw-token")
        assert pd.ip_address == "34.1.2.3"
        assert json.loads(pd.backend_data)["zone"].startswith("us-east5-")
        [(method, url, body, _)] = [
            r for r in t.requests if r[0] == "POST" and "/instances" in r[1]
        ]
        assert "compute.googleapis.com" in url
        assert body["machineType"].endswith("e2-small")
        script = body["metadata"]["items"][0]["value"]
        assert "dstack_tpu.gateway" in script and "gw-token" in script
        assert body["labels"]["dstack_gateway"] == "true"

        await gcp.terminate_gateway(pd.instance_id, "us-east5", pd.backend_data)
        assert any(r[0] == "DELETE" and "/instances/" in r[1] for r in t.requests)


class TestRateLimits:
    async def test_rate_limit_enforced_on_appliance(self):
        """rate_limits buckets requests per prefix (reference nginx limit_req)."""
        up, port = await _echo_app_server("rl")
        gw_client = TestClient(TestServer(create_app("tok")))
        await gw_client.start_server()
        try:
            await gw_client.post(
                "/api/registry/register",
                json={
                    "project": "main",
                    "run_name": "limited",
                    "replicas": [{"host": "127.0.0.1", "port": port}],
                    "rate_limits": [{"prefix": "/", "rps": 1, "burst": 2}],
                },
                headers={"Authorization": "Bearer tok"},
            )
            statuses = []
            for _ in range(5):
                resp = await gw_client.get("/services/main/limited/x")
                statuses.append(resp.status)
            # burst of 2 passes, the rest are throttled.
            assert statuses[:2] == [200, 200]
            assert 429 in statuses[2:]
        finally:
            await gw_client.close()
            await up.cleanup()

    async def test_in_server_proxy_rate_limit(self, tmp_path):
        from dstack_tpu.server.services import logs as logs_service
        from dstack_tpu.server.services.proxy import rate_limiter
        from tests.test_services import _APP, _drive_until_replicas, _stop_run

        logs_service.set_log_storage(logs_service.FileLogStorage(str(tmp_path)))
        rate_limiter.reset()
        try:
            async with api_server() as api:
                await api.post(
                    "/api/project/main/runs/submit",
                    {
                        "run_spec": {
                            "run_name": "rlsvc",
                            "configuration": {
                                "type": "service",
                                "commands": [_APP],
                                "port": 8000,
                                "rate_limits": [{"prefix": "/", "rps": 1, "burst": 2}],
                            },
                        }
                    },
                )
                await _drive_until_replicas(api, "rlsvc", 1)
                headers = {"Authorization": f"Bearer {api.token}"}
                statuses = []
                for _ in range(5):
                    resp = await api.client.get(
                        "/proxy/services/main/rlsvc/ping", headers=headers
                    )
                    statuses.append(resp.status)
                assert 429 in statuses
                await _stop_run(api, "rlsvc")
        finally:
            logs_service.set_log_storage(None)


class TestStatsSkewRebasing:
    def test_buckets_rebase_by_clock_delta(self):
        from dstack_tpu.server.services.gateways import stats_rows_from_payload

        run_ids = {"svc": "run-1"}
        payload = {
            "now": 1_000_000.0,  # appliance clock 120s behind the server
            "services": [
                {"project": "main", "run_name": "svc", "buckets": {"999990": 5}},
                {"project": "other", "run_name": "svc", "buckets": {"999990": 9}},
                {"project": "main", "run_name": "ghost", "buckets": {"999990": 9}},
            ],
        }
        rows = stats_rows_from_payload(payload, run_ids, "main", now=1_000_120.0)
        # Only the matching project+run survives; bucket shifted by +120.
        assert rows == [("run-1", 999990 + 120, 5)]

    def test_legacy_list_payload_assumes_no_skew(self):
        from dstack_tpu.server.services.gateways import stats_rows_from_payload

        rows = stats_rows_from_payload(
            [{"project": "main", "run_name": "svc", "buckets": {"100": 2}}],
            {"svc": "run-1"}, "main", now=500.0,
        )
        assert rows == [("run-1", 100, 2)]
