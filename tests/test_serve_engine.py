"""Continuous-batching serving engine: paged KV correctness, scheduling, the
SSE stream through the proxy, and the latency autoscaler's decisions.

The engine invariant everything here leans on: continuous batching is a
SCHEDULING optimization — it must never change a single emitted token. The
equivalence tests pin that against (a) a full-context greedy reference decode
and (b) the same engine run one-request-at-a-time, in fp32 on CPU so argmax
ties can't blur the comparison."""

import asyncio
import json
import threading

import jax
import numpy as np
import pytest

from dstack_tpu.core.models.services import ScalingMetric, ScalingSpec
from dstack_tpu.server.services import autoscaler as autoscaler_service
from dstack_tpu.server.services import proxy as proxy_service
from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import serve as serve_lib
from dstack_tpu.workloads.config import get_config

TINY = get_config(
    "test", n_layers=2, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
    vocab_size=251, max_seq_len=128, dtype="float32", param_dtype="float32",
    remat=False,
)

PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9], [10, 11, 12, 13, 14, 15, 16]]


@pytest.fixture(scope="module")
def params():
    return model_lib.init_params(TINY, jax.random.PRNGKey(0))


def make_engine(params, **overrides) -> serve_lib.ServeEngine:
    kwargs = dict(page_size=8, num_pages=32, max_batch=4, max_seq=128)
    kwargs.update(overrides)
    return serve_lib.ServeEngine(
        TINY, serve_lib.EngineConfig(**kwargs), params=params
    )


def run_to_completion(engine, limit=500):
    steps = 0
    while engine.has_work():
        engine.step()
        steps += 1
        assert steps < limit, "engine never drained"
    return steps


class TestEquivalence:
    def test_continuous_batch_matches_full_forward_reference(self, params):
        """In-flight batched decode over the paged cache emits exactly the
        tokens a full-context forward() greedy loop emits."""
        engine = make_engine(params)
        reqs = [engine.submit(p, max_new_tokens=6) for p in PROMPTS]
        run_to_completion(engine)
        for prompt, req in zip(PROMPTS, reqs):
            ref = serve_lib.greedy_reference_decode(params, TINY, prompt, 6)
            assert req.tokens == ref, f"paged decode diverged for {prompt}"

    def test_continuous_batch_matches_one_at_a_time(self, params):
        """Same engine, max_batch=1 (sequential): batching changes nothing."""
        batched = make_engine(params)
        reqs = [batched.submit(p, max_new_tokens=8) for p in PROMPTS]
        run_to_completion(batched)

        sequential = make_engine(params, max_batch=1)
        for prompt, batched_req in zip(PROMPTS, reqs):
            solo = sequential.submit(prompt, max_new_tokens=8)
            run_to_completion(sequential)
            assert solo.tokens == batched_req.tokens

    def test_eos_stops_generation_early(self, params):
        probe = make_engine(params)
        req = probe.submit(PROMPTS[0], max_new_tokens=6)
        run_to_completion(probe)
        eos = req.tokens[2]  # deterministic: greedy always reproduces this

        engine = make_engine(params)
        stopped = engine.submit(PROMPTS[0], max_new_tokens=6, eos_id=eos)
        run_to_completion(engine)
        assert stopped.tokens == req.tokens[:3]  # eos token included, then stop
        assert stopped.done


class TestPagedCache:
    def test_pages_freed_across_request_churn(self, params):
        """Way more requests than the pool fits concurrently: every page must
        come back; no leak, no double-free."""
        engine = make_engine(params, num_pages=16, page_size=8, max_batch=2)
        total = engine.ecfg.num_pages
        reqs = [
            engine.submit([(i * 3 + j) % 200 + 1 for j in range(5)],
                          max_new_tokens=5)
            for i in range(12)
        ]
        run_to_completion(engine, limit=1000)
        assert all(r.done for r in reqs)
        assert engine.free_pages == total
        assert sorted(engine._free) == list(range(total))  # each page exactly once
        assert all(not p for p in engine.slot_pages)
        assert not engine.page_tables.any()

    def test_admission_waits_for_pages(self, params):
        """A request that doesn't fit the free pool stays queued (visible as
        queue depth — the autoscaler's signal) and is admitted once pages free."""
        engine = make_engine(params, num_pages=4, page_size=8, max_batch=2)
        # 17 prompt tokens + headroom = 3 of 4 pages.
        big = engine.submit(list(range(1, 18)), max_new_tokens=4)
        engine.step()
        # Second big request can't fit alongside: 2 pages needed, 1 free.
        queued = engine.submit(list(range(1, 10)), max_new_tokens=4)
        engine.step()
        assert engine.queue_depth == 1 and not queued.tokens
        run_to_completion(engine)
        assert big.done and queued.done
        assert queued.tokens == serve_lib.greedy_reference_decode(
            params, TINY, queued.prompt, 4
        )

    def test_preemption_under_page_pressure_keeps_tokens_identical(self, params):
        """When decode growth drains the pool, the youngest request is
        preempted and later re-prefilled from prompt + generated — emitted
        tokens still match the reference exactly. The pool is sized so the
        SAME request gets preempted more than once: a resume prompt that
        re-appended already-absorbed tokens would corrupt its context here."""
        engine = make_engine(params, num_pages=7, page_size=4, max_batch=3,
                             max_seq=96)
        prompts = [[i + 1, i + 2, i + 3, i + 4, i + 5] for i in (0, 10, 20)]
        reqs = [engine.submit(p, max_new_tokens=20) for p in prompts]
        run_to_completion(engine, limit=2000)
        assert max(r.preemptions for r in reqs) >= 2, (
            "pool was sized to preempt one request repeatedly"
        )
        for prompt, req in zip(prompts, reqs):
            assert req.tokens == serve_lib.greedy_reference_decode(
                params, TINY, prompt, 20
            )
        assert engine.free_pages == engine.ecfg.num_pages


class TestInterleave:
    def test_midflight_admission_does_not_disturb_running_decode(self, params):
        """Admit B while A is mid-decode: A's token stream continues one per
        step (prefill of B batches separately), and both match the reference."""
        engine = make_engine(params)
        a = engine.submit(PROMPTS[0], max_new_tokens=10)
        for _ in range(3):
            engine.step()
        a_before = len(a.tokens)
        # Admission step emits prefill token + a decode token; then 1/step.
        assert a_before == 4
        b = engine.submit(PROMPTS[2], max_new_tokens=6)
        events = engine.step()
        # The admission step emits B's prefill token AND A's next decode token.
        assert {ev.req_id for ev in events} == {a.req_id, b.req_id}
        assert len(a.tokens) == a_before + 1
        run_to_completion(engine)
        assert a.tokens == serve_lib.greedy_reference_decode(
            params, TINY, PROMPTS[0], 10
        )
        assert b.tokens == serve_lib.greedy_reference_decode(
            params, TINY, PROMPTS[2], 6
        )

    def test_static_policy_admits_only_into_drained_batch(self, params):
        engine = make_engine(params, policy="static")
        a = engine.submit(PROMPTS[0], max_new_tokens=4)
        engine.step()
        b = engine.submit(PROMPTS[1], max_new_tokens=4)
        while not a.done:
            engine.step()
        assert not b.tokens  # nothing until the whole batch drained
        run_to_completion(engine)
        assert b.tokens == serve_lib.greedy_reference_decode(
            params, TINY, PROMPTS[1], 4
        )


class _GatedRunner(serve_lib.EngineRunner):
    """EngineRunner whose step loop advances only when the test releases it —
    makes 'the stream is open mid-generation' deterministic, no timing."""

    def __init__(self, engine):
        super().__init__(engine)
        self.gate = threading.Semaphore(0)

    def release(self, steps: int = 1) -> None:
        for _ in range(steps):
            self.gate.release()

    def run(self):
        while not self._stop.is_set():
            if not self.gate.acquire(timeout=0.05):
                continue
            self.step_once()

    def shutdown(self):
        super().shutdown()
        self.gate.release()


class TestSseThroughProxy:
    async def test_tokens_stream_unbuffered_and_record_ttft(self, params):
        """Extends the PR 2 pass-through test with the REAL engine upstream:
        the client receives the first SSE token while generation is still
        gated (so nothing buffered the stream), and the proxy's first-chunk
        hook has already recorded TTFT + the engine queue-depth gauge."""
        from aiohttp import web as aioweb

        from tests.common import api_server
        from tests.test_serving_fast_path import _Fixture, seed_service

        engine = make_engine(params)
        gated = _GatedRunner(engine)
        gated.start()
        app_runner = aioweb.AppRunner(serve_lib.create_serve_app(gated))
        await app_runner.setup()
        site = aioweb.TCPSite(app_runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        try:
            with _Fixture():
                async with api_server() as api:
                    run_id, _ = await seed_service(api.db, "engine", port)
                    resp = await api.client.post(
                        "/proxy/services/main/engine/generate",
                        json={"prompt_tokens": PROMPTS[0], "max_tokens": 5,
                              "stream": True},
                    )
                    assert resp.status == 200
                    assert resp.headers["Content-Type"].startswith(
                        "text/event-stream"
                    )
                    # One engine step = prefill = exactly one token event.
                    gated.release(1)
                    first = await asyncio.wait_for(
                        resp.content.readuntil(b"\n\n"), timeout=10
                    )
                    payload = json.loads(first[len(b"data: "):])
                    assert payload["index"] == 0
                    # Generation is still gated: the stream being readable NOW
                    # proves the path is unbuffered end to end. And the proxy
                    # recorded TTFT + queue depth off that first chunk, while
                    # the held-open stream counts as in-flight demand (what
                    # stops scale-to-zero mid-generation).
                    assert proxy_service.stats.latency_quantiles(run_id)
                    assert proxy_service.stats.queue_depth(run_id) is not None
                    assert proxy_service.stats.inflight(run_id) == 1
                    gated.release(10)
                    rest = await asyncio.wait_for(resp.content.read(), timeout=10)
                    events = [l for l in rest.split(b"\n\n") if l]
                    assert events[-1] == b"data: [DONE]"
                    assert len(events) == 5  # 4 more tokens + DONE
                    for _ in range(50):  # let the proxy handler's finally run
                        if proxy_service.stats.inflight(run_id) == 0:
                            break
                        await asyncio.sleep(0.01)
                    assert proxy_service.stats.inflight(run_id) == 0
        finally:
            gated.shutdown()
            await app_runner.cleanup()

    async def test_generate_rejects_bad_tokens(self, params):
        from aiohttp import web as aioweb
        from aiohttp.test_utils import TestClient, TestServer

        runner = serve_lib.EngineRunner(make_engine(params))
        runner.start()
        try:
            client = TestClient(TestServer(serve_lib.create_serve_app(runner)))
            await client.start_server()
            try:
                resp = await client.post(
                    "/generate", json={"prompt_tokens": [999999]}
                )
                assert resp.status == 400
                resp = await client.post(
                    "/generate", json={"prompt": "hi", "max_tokens": "8"}
                )
                assert resp.status == 400  # not a 500 from deep in submit()
                resp = await client.post("/generate", json={"prompt": "hi",
                                                            "max_tokens": 2,
                                                            "stream": False})
                assert resp.status == 200
                body = await resp.json()
                assert len(body["tokens"]) == 2
                assert "X-Dstack-Queue-Depth" in resp.headers
                stats = await (await client.get("/stats")).json()
                assert stats["finished_requests"] >= 1
            finally:
                await client.close()
        finally:
            runner.shutdown()


def _spec(metric="latency", target=0.2, qd=4, rmin=0, rmax=4) -> ScalingSpec:
    return ScalingSpec(
        metric=metric, target=target, queue_depth_target=qd,
        scale_up_delay=0, scale_down_delay=0,
    )


class TestAutoscalerDecisions:
    """decide() from synthetic windows: the satellite's up/down/zero matrix."""

    def test_high_p90_scales_up(self):
        sig = autoscaler_service.Signals(rps=2.0, p50=0.1, p90=0.5)
        assert autoscaler_service.decide(_spec(), 0, 4, 2, sig) == 3

    def test_deep_engine_queue_scales_up_despite_healthy_latency(self):
        sig = autoscaler_service.Signals(rps=2.0, p50=0.05, p90=0.08,
                                         queue_depth=12)
        assert autoscaler_service.decide(_spec(), 0, 4, 2, sig) == 3

    def test_comfortable_latency_scales_down(self):
        sig = autoscaler_service.Signals(rps=2.0, p50=0.02, p90=0.05,
                                         queue_depth=0)
        assert autoscaler_service.decide(_spec(), 0, 4, 3, sig) == 2

    def test_dead_band_holds_steady(self):
        # p90 between 0.5*target and target: neither direction.
        sig = autoscaler_service.Signals(rps=2.0, p50=0.1, p90=0.15)
        assert autoscaler_service.decide(_spec(), 0, 4, 2, sig) == 2

    def test_idle_window_scales_to_zero_only_when_min_allows(self):
        idle = autoscaler_service.Signals(rps=0.0)
        assert autoscaler_service.decide(_spec(), 0, 4, 2, idle) == 0
        assert autoscaler_service.decide(_spec(rmin=1), 1, 4, 2, idle) == 1

    def test_inflight_stream_blocks_scale_to_zero(self):
        """A >60s SSE generation leaves no RPS trace but is still demand:
        the held-open stream must pin the service above zero — on BOTH
        metrics (the rps branch computes ceil(0/target)=0 otherwise)."""
        streaming = autoscaler_service.Signals(rps=0.0, inflight=1)
        assert not streaming.idle
        assert autoscaler_service.decide(_spec(), 0, 4, 1, streaming) == 1
        rps_spec = ScalingSpec(metric="rps", target=2)
        assert autoscaler_service.decide(rps_spec, 0, 4, 1, streaming) == 1

    def test_live_traffic_never_scales_below_one(self):
        """Healthy fast traffic on the last replica: comfortable p90 must not
        step active-1 down to zero — that would kill/cold-start-cycle every
        lightly-loaded scale-to-zero service. Zero is the idle path only."""
        light = autoscaler_service.Signals(rps=5.0, p50=0.02, p90=0.03,
                                           queue_depth=0, inflight=3)
        assert autoscaler_service.decide(_spec(), 0, 4, 1, light) == 1

    def test_demand_against_zero_replicas_wakes_one(self):
        sig = autoscaler_service.Signals(rps=0.5)  # no latency samples yet
        assert autoscaler_service.decide(_spec(), 0, 4, 0, sig) == 1

    def test_max_clamps_runaway_latency(self):
        sig = autoscaler_service.Signals(rps=9.0, p50=1.0, p90=3.0)
        assert autoscaler_service.decide(_spec(), 0, 2, 2, sig) == 2

    def test_rps_metric_unchanged(self):
        spec = ScalingSpec(metric="rps", target=2)
        sig = autoscaler_service.Signals(rps=5.0)
        assert autoscaler_service.decide(spec, 0, 8, 1, sig) == 3


class TestStatsSignals:
    def test_latency_quantiles_and_queue_depth_window(self):
        stats = proxy_service.ServiceStats()
        for v in (0.1, 0.2, 0.3, 0.4, 1.0):
            stats.record_latency("r1", v)
        q = stats.latency_quantiles("r1")
        assert q["count"] == 5
        assert q["p50"] == pytest.approx(0.3)
        assert q["p90"] == pytest.approx(1.0)
        assert stats.latency_quantiles("ghost") is None

        stats.record_queue_depth("r1", 3)
        stats.record_queue_depth("r1", 7)
        stats.record_queue_depth("r1", 2)
        assert stats.queue_depth("r1") == 7  # max in window: spikes must show
        assert stats.queue_depth("ghost") is None
        stats.drop_run("r1")
        assert stats.latency_quantiles("r1") is None
        assert stats.queue_depth("r1") is None


class TestAutoscalerIntegration:
    """The background pass end to end against a fake service: injected p90
    scales up (run_events carries the autoscaler actor), an idle window
    scales back to zero — no cloud, no runner."""

    async def test_latency_scale_up_then_to_zero(self):
        from dstack_tpu.server.background import tasks
        from tests.common import api_server, setup_mock_backend

        proxy_service.stats.reset()
        try:
            async with api_server() as api:
                await setup_mock_backend(api)
                await api.post(
                    "/api/project/main/runs/submit",
                    {"run_spec": {
                        "run_name": "lat-svc",
                        "configuration": {
                            "type": "service",
                            "commands": ["python -m dstack_tpu.workloads.serve"],
                            "port": 8000,
                            "replicas": "0..2",
                            "resources": {"tpu": "v5e-8"},
                            "scaling": {
                                "metric": "latency", "target": 0.2,
                                "queue_depth_target": 2,
                                "scale_up_delay": 0, "scale_down_delay": 0,
                            },
                        },
                    }},
                )
                row = await api.db.fetchone(
                    "SELECT * FROM runs WHERE run_name = 'lat-svc'"
                )
                assert not await api.db.fetchall(
                    "SELECT * FROM jobs WHERE run_id = ?", (row["id"],)
                )  # replicas.min = 0: born scaled to zero

                for _ in range(30):
                    proxy_service.stats.record(row["id"])
                    proxy_service.stats.record_latency(row["id"], 0.9)
                await tasks.process_autoscaler(api.db)
                jobs = await api.db.fetchall(
                    "SELECT * FROM jobs WHERE run_id = ?", (row["id"],)
                )
                assert len(jobs) == 1 and jobs[0]["status"] == "submitted"

                data = await api.post(
                    "/api/project/main/runs/get_events", {"run_name": "lat-svc"}
                )
                auto = [e for e in data["events"] if e["actor"] == "autoscaler"]
                assert auto and auto[0]["reason"] == "scale_from_zero"

                # Demand evaporates -> back to zero; the replica's jobs get
                # the scaled_down termination the run FSM ignores.
                proxy_service.stats.reset()
                await tasks.process_autoscaler(api.db)
                jobs = await api.db.fetchall(
                    "SELECT * FROM jobs WHERE run_id = ?", (row["id"],)
                )
                assert {j["status"] for j in jobs} <= {"terminating", "terminated"}
                assert all(
                    j["termination_reason"] == "scaled_down" for j in jobs
                )
                run = await api.post(
                    "/api/project/main/runs/get", {"run_name": "lat-svc"}
                )
                assert run["status"] not in ("failed", "terminated")
        finally:
            proxy_service.stats.reset()

    async def test_queue_depth_header_recorded_through_proxy(self):
        """A replica reporting X-Dstack-Queue-Depth feeds the gauge the
        latency autoscaler reads — via the normal proxied-response path."""
        from aiohttp import web as aioweb

        from tests.common import api_server
        from tests.test_serving_fast_path import _Fixture, seed_service

        async def handler(request):
            return aioweb.Response(text="ok",
                                   headers={"X-Dstack-Queue-Depth": "5"})

        upstream = aioweb.Application()
        upstream.router.add_get("/{tail:.*}", handler)
        app_runner = aioweb.AppRunner(upstream)
        await app_runner.setup()
        site = aioweb.TCPSite(app_runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        try:
            with _Fixture():
                async with api_server() as api:
                    run_id, _ = await seed_service(api.db, "qd", port)
                    resp = await api.client.get("/proxy/services/main/qd/ping")
                    assert resp.status == 200
                    assert proxy_service.stats.queue_depth(run_id) == 5.0
        finally:
            await app_runner.cleanup()
