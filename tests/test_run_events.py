"""Run lifecycle tracing: event timeline ordering, derived phase durations,
histogram exposition, and a strict Prometheus text-format parser.

The parser test is the regression net for the hand-rendered exposition
(services/prometheus.py): every family must carry HELP+TYPE, histogram series
must be cumulative and consistent (_bucket/+Inf == _count), and label values
must be escaped — exactly the properties a real Prometheus scraper enforces."""

import re

import pytest

from dstack_tpu.core import tracing
from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from dstack_tpu.server.services import events as events_service
from dstack_tpu.server.services import request_metrics
from tests.common import (
    FakeRunnerClient,
    api_server,
    drive,
    setup_mock_backend,
    tpu_task_spec,
)


@pytest.fixture(autouse=True)
def _fake_runner(monkeypatch):
    FakeRunnerClient.reset()
    backends_service.reset_compute_cache()
    monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
    tracing.reset()
    request_metrics.reset()
    yield
    FakeRunnerClient.reset()
    tracing.reset()


class TestEventTimeline:
    async def test_full_lifecycle_ordering_and_phases(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("ev-run", "v5e-8"))
            await drive(api.db)
            run = await api.post("/api/project/main/runs/get", {"run_name": "ev-run"})
            assert run["status"] == "done"

            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "ev-run"}
            )
            events = data["events"]
            # First event is the user's submission of the run itself.
            assert events[0]["new_status"] == "submitted"
            assert events[0]["actor"] == "user"
            assert events[0]["job_id"] is None

            # The job walks the whole FSM, in order, with no repeats.
            job_events = [e for e in events if e["job_id"]]
            assert [e["new_status"] for e in job_events] == [
                "submitted", "provisioning", "pulling", "running", "terminating", "done",
            ]
            # Every transition's old_status chains to the previous new_status.
            for prev, cur in zip(job_events, job_events[1:]):
                assert cur["old_status"] == prev["new_status"]

            # Run-level aggregation follows and the run reaches a terminal event.
            run_events = [e for e in events if e["job_id"] is None]
            assert run_events[-1]["new_status"] == "done"
            assert run_events[-1]["reason"] == "all_jobs_done"

            # Scheduler-written events carry a trace id for log correlation.
            assert all(
                e["trace_id"] for e in events if e["actor"] in ("scheduler", "runner")
            )

            # Derived phases: the run visited every phase, so none is None and
            # total covers the sum of the parts.
            phases = data["phases"]
            for name in ("queue", "provision", "pull", "run", "total"):
                assert phases[name] is not None and phases[name] >= 0
            assert phases["total"] >= max(
                phases["queue"], phases["provision"], phases["pull"], phases["run"]
            )

    async def test_stop_records_user_event(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            spec = tpu_task_spec("ev-stop", "v5e-8")
            await api.post("/api/project/main/runs/submit", spec)
            await api.post(
                "/api/project/main/runs/stop",
                {"runs_names": ["ev-stop"], "abort_requested": False},
            )
            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "ev-stop"}
            )
            stop = [e for e in data["events"] if e["new_status"] == "terminating"]
            assert stop and stop[0]["actor"] == "user"
            assert stop[0]["reason"] == "stopped_by_user"

    async def test_unknown_run_is_404(self):
        async with api_server() as api:
            await api.post(
                "/api/project/main/runs/get_events", {"run_name": "ghost"}, expect=404
            )


class TestPhaseDerivation:
    def test_compute_phases_from_synthetic_timeline(self):
        def ev(t, new, old=None, job="j1"):
            return {
                "timestamp": f"2026-01-01T00:00:{t:06.3f}+00:00",
                "new_status": new,
                "old_status": old,
                "job_id": job,
                "actor": "scheduler",
                "reason": None,
                "message": None,
                "trace_id": None,
            }

        events = [
            ev(0.0, "submitted", job=None),
            ev(0.0, "submitted"),
            ev(2.0, "provisioning", "submitted"),
            ev(5.0, "pulling", "provisioning"),
            ev(6.0, "running", "pulling"),
            ev(6.5, "running", "provisioning", job=None),
            ev(9.0, "terminating", "running"),
            ev(9.5, "done", "terminating"),
            ev(10.0, "terminating", "running", job=None),
            ev(10.0, "done", "terminating", job=None),
        ]
        phases = events_service.compute_phases(events)
        assert phases["queue"] == pytest.approx(2.0)
        assert phases["provision"] == pytest.approx(3.0)
        assert phases["pull"] == pytest.approx(1.0)
        assert phases["run"] == pytest.approx(4.0)
        assert phases["total"] == pytest.approx(10.0)

    def test_unvisited_phases_are_none(self):
        events = [
            {
                "timestamp": "2026-01-01T00:00:00+00:00",
                "new_status": "submitted",
                "old_status": None,
                "job_id": None,
                "actor": "user",
                "reason": None,
                "message": None,
                "trace_id": None,
            }
        ]
        phases = events_service.compute_phases(events)
        assert phases["queue"] is None
        assert phases["provision"] is None
        assert phases["total"] is None
        assert events_service.compute_phases([])["total"] is None


# ---------------------------------------------------------------------------
# Strict Prometheus text exposition parser


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _parse_labels(s: str) -> dict:
    """Parse `k="v",k2="v2"` enforcing quoting and escape rules."""
    labels = {}
    i = 0
    while i < len(s):
        m = re.match(r"([a-zA-Z_][a-zA-Z0-9_]*)=\"", s[i:])
        assert m, f"bad label start at {s[i:]!r}"
        name = m.group(1)
        i += m.end()
        val = []
        while True:
            assert i < len(s), f"unterminated label value in {s!r}"
            ch = s[i]
            if ch == "\\":
                assert i + 1 < len(s) and s[i + 1] in '\\"n', f"bad escape in {s!r}"
                val.append({"n": "\n"}.get(s[i + 1], s[i + 1]))
                i += 2
            elif ch == '"':
                i += 1
                break
            else:
                assert ch != "\n", "raw newline in label value"
                val.append(ch)
                i += 1
        labels[name] = "".join(val)
        if i < len(s):
            assert s[i] == ",", f"expected ',' at {s[i:]!r}"
            i += 1
    return labels


def parse_exposition(text: str) -> dict:
    """Validate the whole exposition; returns {family: {"type", "samples"}}
    where samples is [(name, labels, value)]. Raises AssertionError on any
    format violation."""
    assert text.endswith("\n"), "exposition must end with a newline"
    families: dict = {}
    current = None  # (family, type)
    pending_help = None
    for line in text.splitlines():
        assert line.strip() == line, f"stray whitespace: {line!r}"
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            fam, _, help_text = rest.partition(" ")
            assert _NAME_RE.match(fam), f"bad family name {fam!r}"
            assert fam not in families, f"duplicate HELP for {fam}"
            assert help_text, f"empty HELP for {fam}"
            pending_help = fam
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, type_ = rest.partition(" ")
            assert fam == pending_help, f"TYPE {fam} not preceded by its HELP"
            assert type_ in ("counter", "gauge", "histogram"), type_
            families[fam] = {"type": type_, "samples": []}
            current = (fam, type_)
            pending_help = None
            continue
        assert not line.startswith("#"), f"unknown comment line {line!r}"
        assert current is not None, f"sample before any TYPE: {line!r}"
        fam, type_ = current
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$", line)
        assert m, f"unparsable sample line {line!r}"
        name, label_str, value_str = m.groups()
        if type_ == "histogram":
            assert name in (f"{fam}_bucket", f"{fam}_sum", f"{fam}_count"), (
                f"sample {name} does not belong to histogram {fam}"
            )
        else:
            assert name == fam, f"sample {name} does not belong to {fam}"
        labels = _parse_labels(label_str) if label_str else {}
        for k in labels:
            assert _LABEL_NAME_RE.match(k), f"bad label name {k!r}"
        value = float(value_str)  # raises on malformed numbers
        families[fam]["samples"].append((name, labels, value))
    # Histogram consistency: per label set, buckets are cumulative and
    # +Inf == _count; _sum/_count present exactly once.
    for fam, data in families.items():
        if data["type"] != "histogram":
            continue
        series: dict = {}
        for name, labels, value in data["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            entry = series.setdefault(key, {"buckets": [], "sum": None, "count": None})
            if name.endswith("_bucket"):
                assert "le" in labels, f"{fam} bucket without le"
                le = float("inf") if labels["le"] == "+Inf" else float(labels["le"])
                entry["buckets"].append((le, value))
            elif name.endswith("_sum"):
                assert entry["sum"] is None, f"duplicate {fam}_sum"
                entry["sum"] = value
            else:
                assert entry["count"] is None, f"duplicate {fam}_count"
                entry["count"] = value
        for key, entry in series.items():
            assert entry["buckets"], f"{fam}{dict(key)} has no buckets"
            les = [le for le, _ in entry["buckets"]]
            assert les == sorted(les), f"{fam} buckets out of order"
            assert les[-1] == float("inf"), f"{fam} missing +Inf bucket"
            counts = [c for _, c in entry["buckets"]]
            assert counts == sorted(counts), f"{fam} buckets not cumulative"
            assert entry["count"] is not None and entry["sum"] is not None
            assert counts[-1] == entry["count"], f"{fam} +Inf != count"
    return families


class TestPrometheusExposition:
    async def test_every_family_parses_strictly(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("px", "v5e-8"))
            await drive(api.db)
            # A proxied-latency observation and a loop-lag gauge, so those
            # families render with samples too.
            tracing.observe(
                "dstack_tpu_service_request_latency_seconds", 0.034, {"run": "px"}
            )
            tracing.set_gauge(
                "dstack_tpu_background_loop_lag_seconds", {"task": "process_runs"}, 0.0
            )
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())

            # The advertised histogram families are all present and typed.
            for fam in (
                "dstack_tpu_run_queue_wait_seconds",
                "dstack_tpu_run_provision_duration_seconds",
                "dstack_tpu_scheduler_pass_duration_seconds",
                "dstack_tpu_service_request_latency_seconds",
            ):
                assert families[fam]["type"] == "histogram", fam
            assert families["dstack_tpu_runs_total"]["type"] == "gauge"
            assert families["dstack_tpu_background_loop_lag_seconds"]["type"] == "gauge"

    async def test_histogram_bucket_counts(self):
        async with api_server() as api:
            await setup_mock_backend(api)
            await api.post("/api/project/main/runs/submit", tpu_task_spec("hx", "v5e-8"))
            await drive(api.db)
            resp = await api.client.get("/metrics")
            families = parse_exposition(await resp.text())

            # One single-job run = one job left 'submitted' and one left
            # 'provisioning': each phase histogram observed exactly once.
            for fam in (
                "dstack_tpu_run_queue_wait_seconds",
                "dstack_tpu_run_provision_duration_seconds",
                "dstack_tpu_run_pull_duration_seconds",
            ):
                counts = [
                    v for name, labels, v in families[fam]["samples"]
                    if name.endswith("_count")
                ]
                assert counts == [1.0], (fam, families[fam]["samples"])
            # Scheduler pass histograms: one series per instrumented pass,
            # counts match the number of drive() iterations (10 each).
            passes = {
                labels["pass"]
                for name, labels, _ in
                families["dstack_tpu_scheduler_pass_duration_seconds"]["samples"]
                if name.endswith("_count")
            }
            assert passes == {
                "process_submitted_jobs", "process_running_jobs",
                "process_terminating_jobs", "process_runs",
            }

    def test_parser_rejects_malformed_expositions(self):
        good = (
            "# HELP m_total things\n# TYPE m_total counter\n"
            'm_total{a="b"} 1\n'
        )
        parse_exposition(good)
        with pytest.raises(AssertionError):
            parse_exposition("m_total 1\n")  # sample with no HELP/TYPE
        with pytest.raises(AssertionError):  # TYPE without preceding HELP
            parse_exposition("# TYPE m_total counter\nm_total 1\n")
        with pytest.raises(AssertionError):  # unescaped quote in label value
            parse_exposition(
                "# HELP m things\n# TYPE m gauge\n" 'm{a="b"c"} 1\n'
            )
        with pytest.raises(AssertionError):  # histogram without +Inf
            parse_exposition(
                "# HELP h x\n# TYPE h histogram\n"
                'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n'
            )
        with pytest.raises(AssertionError):  # non-cumulative buckets
            parse_exposition(
                "# HELP h x\n# TYPE h histogram\n"
                'h_bucket{le="1"} 2\nh_bucket{le="+Inf"} 1\nh_sum 1\nh_count 1\n'
            )

    def test_label_escaping_round_trips(self):
        from dstack_tpu.server.services.prometheus import _fmt

        text = _fmt(
            "m_esc", "weird labels", "gauge",
            [({"a": 'quote" back\\slash \n newline'}, 1.0)],
        ) + "\n"
        fams = parse_exposition(text)
        ((_, labels, _),) = fams["m_esc"]["samples"]
        assert labels["a"] == 'quote" back\\slash \n newline'


class TestUnmatchedRouteBucketing:
    async def test_unmatched_paths_share_one_label(self):
        async with api_server() as api:
            for i in range(5):
                resp = await api.client.get(f"/no/such/path-{i}")
                assert resp.status == 404
            routes = {route for (_, route, _), _, _ in request_metrics.snapshot()}
            for i in range(5):
                assert f"/no/such/path-{i}" not in routes
            assert "unmatched" in routes
            # Matched routes still use their canonical template.
            await api.post("/api/project/main/runs/list")
            routes = {route for (_, route, _), _, _ in request_metrics.snapshot()}
            assert "/api/project/{project_name}/runs/list" in routes


class TestTracer:
    def test_span_nesting_and_trace_propagation(self):
        tracing.new_trace()
        tid = tracing.current_trace_id()
        assert tid
        with tracing.span("outer"):
            outer_sid = tracing.current_span_id()
            assert tracing.current_trace_id() == tid
            with tracing.span("inner"):
                assert tracing.current_span_id() != outer_sid
            assert tracing.current_span_id() == outer_sid
        assert tracing.current_span_id() is None

    def test_span_feeds_histogram(self):
        with tracing.span("x", histogram="test_hist", labels={"k": "v"}):
            pass
        buckets, series = tracing.histogram_snapshot("test_hist")
        ((labels, cumulative, total, count),) = series
        assert labels == {"k": "v"}
        assert count == 1 and cumulative[-1] == 1
        assert total >= 0

    def test_slow_span_warns(self, monkeypatch, caplog):
        import logging

        monkeypatch.setenv("DSTACK_TPU_TRACE_SLOW_SECONDS", "0.0001")
        with caplog.at_level(logging.WARNING, logger="dstack_tpu.core.tracing"):
            with tracing.span("slow.op", run="r1"):
                import time

                time.sleep(0.002)
        assert any("slow span slow.op" in r.message for r in caplog.records)
        assert any("run=r1" in r.message for r in caplog.records)

    def test_deleted_run_latency_series_swept(self):
        from dstack_tpu.server.services import proxy as proxy_service

        tracing.observe(
            "dstack_tpu_service_request_latency_seconds", 0.05, {"run": "dead-svc"}
        )
        tracing.observe(
            "dstack_tpu_service_request_latency_seconds", 0.05, {"run": "live-svc"}
        )
        proxy_service.forget_run("run-dead", "dead-svc")
        _, series = tracing.histogram_snapshot(
            "dstack_tpu_service_request_latency_seconds"
        )
        assert [labels for labels, _, _, _ in series] == [{"run": "live-svc"}]

    def test_summary_quantiles(self):
        for v in (0.004, 0.02, 0.02, 0.2):
            tracing.observe("q_hist", v)
        s = tracing.summary("q_hist")
        assert s["count"] == 4
        assert s["p50"] == 0.025  # bucket upper bound containing the median
        assert s["mean"] == pytest.approx(0.061)


class TestHistogramEdgeCases:
    """Edge cases of the fixed-bucket cumulative histogram the whole metrics
    surface rides on (ISSUE 18 satellite)."""

    def test_observation_exactly_on_bucket_boundary(self):
        """Prometheus semantics: le is INCLUSIVE — a value exactly equal to a
        bucket bound lands in that bucket, not the next one."""
        hist = tracing.Histogram("edge", buckets=(0.1, 0.5, 1.0))
        hist.observe(0.5)
        ((_, cumulative, total, count),) = hist.snapshot()
        # cumulative = [<=0.1, <=0.5, <=1.0, +Inf]
        assert cumulative == [0.0, 1.0, 1.0, 1.0]
        assert count == 1 and total == 0.5

    def test_observation_above_every_bucket(self):
        hist = tracing.Histogram("edge", buckets=(0.1, 0.5))
        hist.observe(7.0)
        ((_, cumulative, _, count),) = hist.snapshot()
        assert cumulative == [0.0, 0.0, 1.0]  # only +Inf
        assert count == 1

    def test_concurrent_observes_lose_nothing(self):
        """module-level observe() is the thread-shared entry point (engine
        thread + event loop + DB worker all call it): under the lock, N
        threads x M observes must land exactly N*M counts."""
        import threading

        name = "edge_concurrent_hist"
        n_threads, per_thread = 8, 200

        def worker(i: int) -> None:
            for j in range(per_thread):
                tracing.observe(name, 0.01 * ((i + j) % 5), {"replica": str(i % 2)})

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _, series = tracing.histogram_snapshot(name)
        assert sum(count for _, _, _, count in series) == n_threads * per_thread
        # Cumulative monotonicity survived the interleaving in every series.
        for _, cumulative, _, count in series:
            assert cumulative == sorted(cumulative)
            assert cumulative[-1] == count

    def test_drop_series_of_live_label_set(self):
        """drop_series removes exactly the named label set; the family and its
        other series stay, and the dropped set can be re-observed fresh."""
        name = "edge_drop_hist"
        tracing.observe(name, 0.1, {"run": "a"})
        tracing.observe(name, 0.2, {"run": "a"})
        tracing.observe(name, 0.3, {"run": "b"})
        tracing.drop_series(name, {"run": "a"})
        _, series = tracing.histogram_snapshot(name)
        assert [labels for labels, _, _, _ in series] == [{"run": "b"}]
        # Re-observing the dropped set starts a fresh counter vector, not a
        # resurrected one.
        tracing.observe(name, 0.4, {"run": "a"})
        _, series = tracing.histogram_snapshot(name)
        by_labels = {tuple(sorted(l.items())): c for l, _, _, c in series}
        assert by_labels[(("run", "a"),)] == 1
        assert by_labels[(("run", "b"),)] == 1

    def test_drop_series_unknown_family_and_labels_noop(self):
        tracing.drop_series("edge_never_registered", {"run": "x"})
        tracing.observe("edge_known", 0.1, {"run": "y"})
        tracing.drop_series("edge_known", {"run": "z"})  # no such series
        _, series = tracing.histogram_snapshot("edge_known")
        assert len(series) == 1
