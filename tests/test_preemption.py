"""Preemption-aware gang rescue through the real server FSM: kill-mid-step
lifecycle with run_events timeline assertions, time-to-recover histogram,
and elastic retry onto a different slice topology.

Same strategy as test_scheduler.py: real FSM loops + real DB + mock Compute +
scripted runner clients."""

import json

import pytest

from dstack_tpu.core import tracing
from dstack_tpu.server import settings
from dstack_tpu.server.background import tasks
from dstack_tpu.server.services import backends as backends_service
from tests.common import (
    FakeRunnerClient,
    api_server,
    drive,
    setup_mock_backend,
    tpu_task_spec,
)


@pytest.fixture(autouse=True)
def _fake_runner(monkeypatch):
    FakeRunnerClient.reset()
    backends_service.reset_compute_cache()
    tracing.reset()
    monkeypatch.setattr(tasks, "get_runner_client", FakeRunnerClient.for_jpd)
    monkeypatch.setattr(settings, "RETRY_BACKOFF_BASE", 0.0)
    yield
    FakeRunnerClient.reset()


async def _job_rows(db, run_name):
    return await db.fetchall(
        "SELECT * FROM jobs WHERE run_name = ?"
        " ORDER BY submission_num, replica_num, job_num",
        (run_name,),
    )


def _recovery_count():
    snap = tracing.histogram_snapshot("dstack_tpu_run_recovery_seconds")
    if snap is None:
        return 0
    _, series = snap
    return sum(count for _labels, _cum, _total, count in series)


class TestGangRescueLifecycle:
    async def test_kill_mid_step_rescue_timeline_and_recovery(self):
        """A job dying mid-run (exit 1 while RUNNING) tears the gang down,
        the retry policy resubmits it whole, and the rescued run finishes —
        with the full story readable from run_events and the time-to-recover
        observed into dstack_tpu_run_recovery_seconds."""
        async with api_server() as api:
            await setup_mock_backend(api)
            orig_for_jpd = FakeRunnerClient.for_jpd
            injected = []

            def failing_first_attempt(jpd, jrd):
                fake = orig_for_jpd(jpd, jrd)
                if not injected and fake.submitted is None:
                    injected.append(True)
                    # RUNNING for a couple of pulls, then the container dies
                    # mid-step (what a preempted host's workload looks like
                    # from the agent).
                    fake.script = [
                        {"job_states": [{"state": "running"}], "logs": [], "offset": 1},
                        {"job_states": [], "logs": [], "offset": 2},
                        {
                            "job_states": [{"state": "failed", "exit_status": 137}],
                            "logs": [],
                            "offset": 3,
                        },
                    ]
                return fake

            tasks.get_runner_client = failing_first_attempt
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec(
                    "rescue", "v5p-16",
                    retry={"on_events": ["error"], "duration": "1h"},
                ),
            )
            await drive(api.db, passes=25)
            run = await api.post("/api/project/main/runs/get", {"run_name": "rescue"})
            assert run["status"] == "done"
            rows = await _job_rows(api.db, "rescue")
            assert max(r["submission_num"] for r in rows) == 1
            # 2 hosts x 2 submissions, second gang complete.
            assert len([r for r in rows if r["submission_num"] == 1]) == 2

            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "rescue"}
            )
            events = data["events"]
            # The first lineage ran and died...
            seq = [
                (e["new_status"], e["reason"])
                for e in events
                if e["job_id"] is not None
            ]
            statuses = [s for s, _ in seq]
            assert "running" in statuses
            fail_idx = statuses.index("failed")
            assert statuses.index("running") < fail_idx
            # ...then the rescue: gang_retry resubmission AFTER the failure,
            # reaching running and done again.
            retry_idx = seq.index(("submitted", "gang_retry"))
            assert retry_idx > fail_idx
            assert "running" in statuses[retry_idx:]
            assert statuses[-1] == "done"
            # Both gang members were resubmitted by the retry.
            assert seq.count(("submitted", "gang_retry")) == 2

            # Time-to-recover observed exactly once (lead job only — not
            # once per gang host).
            assert _recovery_count() == 1

    async def test_recovery_histogram_advertised_on_metrics(self):
        async with api_server() as api:
            resp = await api.client.get("/metrics")
            text = await resp.text()
            assert "# TYPE dstack_tpu_run_recovery_seconds histogram" in text


class TestElasticRetry:
    async def test_interruption_reschedules_onto_alternate_topology(self, monkeypatch):
        """A slice lost mid-run (runner unreachable -> INSTANCE_UNREACHABLE,
        an interruption event) retries the gang onto the run's next elastic
        topology: v5e-8 (2 hosts) shrinks to v5e-4 (1 host), and the
        resubmitted spec carries the new slice."""
        monkeypatch.setattr(settings, "RUNNER_DISCONNECT_TIMEOUT", 0.0)
        async with api_server() as api:
            await setup_mock_backend(api)
            orig_for_jpd = FakeRunnerClient.for_jpd
            lost = []

            class LostSliceClient:
                def __init__(self, inner):
                    self.inner = inner

                def __getattr__(self, name):
                    return getattr(self.inner, name)

                async def pull(self, offset: int = 0):
                    if self.inner.pulls >= 1:
                        raise ConnectionError("slice preempted")
                    return await self.inner.pull(offset)

            def for_jpd(jpd, jrd):
                fake = orig_for_jpd(jpd, jrd)
                if not lost or fake.key in lost:
                    # Only the FIRST submission's workers become unreachable.
                    if fake.key not in lost and len(lost) < 2:
                        lost.append(fake.key)
                    if fake.key in lost:
                        return LostSliceClient(fake)
                return fake

            tasks.get_runner_client = for_jpd
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec(
                    "elastic", "v5e-16",
                    retry={"on_events": ["interruption"], "duration": "1h"},
                    elastic=["v5e-8"],
                ),
            )
            await drive(api.db, passes=30)
            run = await api.post("/api/project/main/runs/get", {"run_name": "elastic"})
            assert run["status"] == "done", run["status"]

            rows = await _job_rows(api.db, "elastic")
            sub0 = [r for r in rows if r["submission_num"] == 0]
            sub1 = [r for r in rows if r["submission_num"] == 1]
            assert len(sub0) == 2  # v5e-16 = 2 hosts
            assert len(sub1) == 1  # v5e-8 = 1 host — the gang SHRANK
            spec = json.loads(sub1[0]["job_spec"])
            tpu = spec["requirements"]["resources"]["tpu"]
            assert tpu["chips"] == 8
            assert all(r["status"] == "done" for r in sub1)

            # The timeline says why, and the recovery histogram closed.
            data = await api.post(
                "/api/project/main/runs/get_events", {"run_name": "elastic"}
            )
            retried = [
                e for e in data["events"]
                if e["new_status"] == "submitted" and e["reason"] == "gang_retry"
            ]
            assert retried and any(
                "elastic retry onto v5e-8" in (e["message"] or "") for e in retried
            )
            assert _recovery_count() == 1

    async def test_error_failure_does_not_rotate_topology(self):
        """A plain container error (the workload's own bug) retries the gang
        but does NOT switch topology — elastic rotation is reserved for
        capacity failures (preemption/stockout)."""
        async with api_server() as api:
            await setup_mock_backend(api)
            orig_for_jpd = FakeRunnerClient.for_jpd
            injected = []

            def failing_for_jpd(jpd, jrd):
                fake = orig_for_jpd(jpd, jrd)
                if not injected and fake.submitted is None:
                    injected.append(True)
                    fake.script = [
                        {
                            "job_states": [{"state": "failed", "exit_status": 1}],
                            "logs": [],
                            "offset": 1,
                        }
                    ]
                return fake

            tasks.get_runner_client = failing_for_jpd
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec(
                    "no-rotate", "v5e-16",
                    retry={"on_events": ["error"], "duration": "1h"},
                    elastic=["v5e-4"],  # never used: error is not a capacity event
                ),
            )
            await drive(api.db, passes=25)
            run = await api.post(
                "/api/project/main/runs/get", {"run_name": "no-rotate"}
            )
            assert run["status"] == "done"
            rows = await _job_rows(api.db, "no-rotate")
            sub1 = [r for r in rows if r["submission_num"] == 1]
            assert len(sub1) == 2  # still v5e-16's 2 hosts
            tpu = json.loads(sub1[0]["job_spec"])["requirements"]["resources"]["tpu"]
            assert tpu["chips"] == 16

    async def test_elastic_requires_tpu_resources(self):
        async with api_server() as api:
            await api.post(
                "/api/project/main/runs/submit",
                {
                    "run_spec": {
                        "run_name": "bad-elastic",
                        "configuration": {
                            "type": "task",
                            "commands": ["echo hi"],
                            "elastic": ["v5e-4"],
                        },
                    }
                },
                expect=422,
            )

    async def test_elastic_validates_topology_names_at_submit(self):
        async with api_server() as api:
            await api.post(
                "/api/project/main/runs/submit",
                tpu_task_spec("bad-topo", "v5e-8", elastic=["warp9"]),
                expect=422,
            )


class TestLatestSubmissions:
    def test_shrunk_gang_leaves_no_phantom_jobs(self):
        rows = [
            {"replica_num": 0, "job_num": 0, "submission_num": 0, "status": "failed"},
            {"replica_num": 0, "job_num": 1, "submission_num": 0, "status": "failed"},
            {"replica_num": 0, "job_num": 0, "submission_num": 1, "status": "running"},
        ]
        latest = tasks._latest_submissions(rows)
        assert set(latest) == {(0, 0)}
        assert latest[(0, 0)]["submission_num"] == 1

    def test_grown_gang_takes_all_new_jobs(self):
        rows = [
            {"replica_num": 0, "job_num": 0, "submission_num": 0, "status": "failed"},
            {"replica_num": 0, "job_num": 0, "submission_num": 1, "status": "running"},
            {"replica_num": 0, "job_num": 1, "submission_num": 1, "status": "running"},
        ]
        latest = tasks._latest_submissions(rows)
        assert set(latest) == {(0, 0), (0, 1)}

    def test_replicas_keep_independent_submissions(self):
        rows = [
            {"replica_num": 0, "job_num": 0, "submission_num": 2, "status": "running"},
            {"replica_num": 1, "job_num": 0, "submission_num": 0, "status": "running"},
        ]
        latest = tasks._latest_submissions(rows)
        assert latest[(0, 0)]["submission_num"] == 2
        assert latest[(1, 0)]["submission_num"] == 0
