"""Request-level serving observability (ISSUE 18): proxy -> engine trace
propagation, per-request lifecycle stage attribution, and the flight recorder.

The invariant everything rides on: instrumentation is host-side bookkeeping —
timestamps, a ring buffer, histogram observes — and must never change a single
emitted token. The first test pins that against the greedy reference decode;
the rest drive the trace path end to end (client -> proxy -> replica ->
flight recorder -> get_traces API) with the real in-server proxy."""

import threading
import time

import jax
import pytest

from dstack_tpu.core import tracing
from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import serve as serve_lib
from dstack_tpu.workloads.config import get_config
from tests.test_run_events import parse_exposition

TINY = get_config(
    "test", n_layers=2, d_model=64, n_heads=4, n_kv_heads=2, d_ff=128,
    vocab_size=251, max_seq_len=128, dtype="float32", param_dtype="float32",
    remat=False,
)

PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9], [10, 11, 12, 13, 14, 15, 16]]


@pytest.fixture(scope="module")
def params():
    return model_lib.init_params(TINY, jax.random.PRNGKey(0))


def make_engine(params, **overrides) -> serve_lib.ServeEngine:
    kwargs = dict(page_size=8, num_pages=32, max_batch=4, max_seq=128)
    kwargs.update(overrides)
    return serve_lib.ServeEngine(
        TINY, serve_lib.EngineConfig(**kwargs), params=params
    )


def run_to_completion(engine, limit=500):
    steps = 0
    while engine.has_work():
        engine.step()
        steps += 1
        assert steps < limit, "engine never drained"
    return steps


class TestTokenIdentity:
    def test_instrumented_engine_token_identical(self, params):
        """The whole instrumented path (stage stamps, histogram observes,
        flight recording) emits exactly the tokens the full-context greedy
        reference emits — instrumentation is provably scheduling-invisible."""
        engine = make_engine(params)
        reqs = [engine.submit(p, max_new_tokens=6) for p in PROMPTS]
        run_to_completion(engine)
        for prompt, req in zip(PROMPTS, reqs):
            ref = serve_lib.greedy_reference_decode(params, TINY, prompt, 6)
            assert req.tokens == ref, f"instrumented decode diverged for {prompt}"

    def test_stage_timestamps_monotonic(self, params):
        """enqueued <= admitted <= prefill start <= first token <= finished,
        and the flight record's derived durations agree with the stamps."""
        engine = make_engine(params)
        reqs = [engine.submit(p, max_new_tokens=5) for p in PROMPTS]
        run_to_completion(engine)
        for req in reqs:
            assert req.submitted_t <= req.admitted_t <= req.prefill_start_t
            assert req.prefill_start_t <= req.first_token_t <= req.finished_t
            assert len(req.token_times) == len(req.tokens)
        traces = engine.flight.snapshot()
        assert len(traces) == len(PROMPTS)
        for t in traces:
            assert t["queue_wait_s"] >= 0
            assert t["prefill_s"] >= 0
            assert t["ttft_s"] >= t["prefill_s"]
            assert t["total_s"] >= t["ttft_s"]
            assert t["total_s"] == pytest.approx(
                t["ttft_s"] + t["decode_s"], abs=1e-4
            )
            assert len(t["itl_ms"]) == t["tokens"] - 1

    def test_lifecycle_histograms_observed(self, params):
        """Every request-lifecycle family registers with the replica label and
        counts match the workload (one TTFT per request, one ITL per
        consecutive token pair)."""
        tracing.reset()
        try:
            engine = make_engine(params)
            reqs = [engine.submit(p, max_new_tokens=5) for p in PROMPTS]
            run_to_completion(engine)
            labels = {"replica": engine.replica}
            assert tracing.summary(
                "dstack_tpu_serve_ttft_seconds", labels
            )["count"] == len(PROMPTS)
            assert tracing.summary(
                "dstack_tpu_serve_queue_wait_seconds", labels
            )["count"] == len(PROMPTS)
            itl = tracing.summary("dstack_tpu_serve_itl_seconds", labels)
            assert itl["count"] == sum(len(r.tokens) - 1 for r in reqs)
            # Step-stage split: admit/prefill/decode all saw work this run.
            stages = {
                s[0].get("stage")
                for s in tracing.histogram_snapshot(
                    "dstack_tpu_serve_step_stage_seconds"
                )[1]
            }
            assert stages == {"admit", "prefill", "decode"}
        finally:
            tracing.reset()


class TestFlightRecorder:
    def _trace(self, i: int, total: float = 0.01) -> dict:
        return {
            "req_id": f"req-{i}", "trace_id": f"tid-{i}", "replica": "0",
            "finished_at": float(i), "queue_wait_s": 0.0, "prefill_s": 0.0,
            "ttft_s": 0.005, "decode_s": total - 0.005, "total_s": total,
            "prompt_tokens": 3, "cached_tokens": 0, "tokens": 4,
            "preemptions": 0, "spec_proposed": 0, "spec_accepted": 0,
            "itl_ms": [1.0, 1.0, 1.0],
        }

    def test_ring_bounded_newest_first(self):
        fr = serve_lib.FlightRecorder(capacity=8, slow_threshold=100.0)
        for i in range(20):
            fr.record(self._trace(i))
        got = fr.snapshot()
        assert len(got) == 8
        assert [t["req_id"] for t in got] == [f"req-{i}" for i in range(19, 11, -1)]

    def test_slow_requests_survive_fast_burst(self):
        """A slow trace must stay queryable after capacity-many fast
        completions — the whole point of the second ring."""
        fr = serve_lib.FlightRecorder(capacity=4, slow_threshold=1.0)
        fr.record(self._trace(0, total=5.0))
        for i in range(1, 10):
            fr.record(self._trace(i, total=0.01))
        got = fr.snapshot()
        slow = [t for t in got if t["slow"]]
        assert [t["req_id"] for t in slow] == ["req-0"]
        assert fr.snapshot(request_id="req-0")[0]["total_s"] == 5.0

    def test_filters_and_limit(self):
        fr = serve_lib.FlightRecorder(capacity=16, slow_threshold=100.0)
        for i in range(6):
            fr.record(self._trace(i))
        assert [t["req_id"] for t in fr.snapshot(limit=2)] == ["req-5", "req-4"]
        assert fr.snapshot(trace_id="tid-3")[0]["req_id"] == "req-3"
        assert fr.snapshot(request_id="req-1", trace_id="tid-2") == []

    def test_latency_summary_quantiles(self):
        fr = serve_lib.FlightRecorder(capacity=16, slow_threshold=100.0)
        for i in range(4):
            fr.record(self._trace(i))
        out = fr.latency_summary()
        assert out["ttft_p50_ms"] == 5.0
        assert out["itl_p50_ms"] == 1.0
        assert serve_lib.FlightRecorder(capacity=4).latency_summary() == {}


class TestTraceContextAcrossThreads:
    def test_wrap_with_context_carries_trace_id(self):
        """Regression for the contextvars-don't-cross-threads trap: a bare
        thread target sees no trace id; the wrapped one sees the spawner's."""
        tid = tracing.new_trace()
        seen = {}

        def target(key):
            seen[key] = tracing.current_trace_id()

        bare = threading.Thread(target=target, args=("bare",))
        wrapped = threading.Thread(
            target=tracing.wrap_with_context(target), args=("wrapped",)
        )
        bare.start(); bare.join()
        wrapped.start(); wrapped.join()
        assert seen["bare"] is None
        assert seen["wrapped"] == tid

    def test_wrap_snapshots_at_construction(self):
        """The snapshot is taken when the wrapper is BUILT (EngineRunner
        construction), not when the thread later calls it."""
        first = tracing.new_trace()
        wrapped = tracing.wrap_with_context(tracing.current_trace_id)
        tracing.new_trace()  # rebind after capture
        assert wrapped() == first

    def test_engine_runner_thread_joins_constructing_trace(self, params):
        """The runner's step loop runs under the trace that was current when
        the runner was constructed (satellite 1 wired into EngineRunner)."""
        tid = tracing.new_trace()
        runner = serve_lib.EngineRunner(make_engine(params), idle_wait=0.01)
        seen = {}
        orig = runner.step_once

        def spying_step_once():
            seen["trace"] = tracing.current_trace_id()
            return orig()

        runner.step_once = spying_step_once
        runner.start()
        try:
            req = runner.submit([1, 2, 3], 2, lambda ev: None)
            deadline = time.monotonic() + 30
            while not req.done and time.monotonic() < deadline:
                time.sleep(0.01)
            assert req.done
            assert seen["trace"] == tid
        finally:
            runner.shutdown()


class TestServeAppTracePath:
    async def _with_app(self, params, fn, **engine_overrides):
        from aiohttp.test_utils import TestClient, TestServer

        runner = serve_lib.EngineRunner(make_engine(params, **engine_overrides))
        runner.start()
        try:
            client = TestClient(TestServer(serve_lib.create_serve_app(runner)))
            await client.start_server()
            try:
                return await fn(client, runner)
            finally:
                await client.close()
        finally:
            runner.shutdown()

    async def test_trace_header_adopted_and_echoed(self, params):
        """A caller-supplied X-Dstack-Trace-Id is adopted (stamped on the
        engine request, echoed on the response) and the flight-recorder entry
        is retrievable by it via GET /debug/traces."""
        async def fn(client, runner):
            resp = await client.post(
                "/generate",
                json={"prompt_tokens": [1, 2, 3], "max_tokens": 3,
                      "stream": False},
                headers={tracing.TRACE_HEADER: "trace-e2e-1"},
            )
            assert resp.status == 200
            assert resp.headers[tracing.TRACE_HEADER] == "trace-e2e-1"
            body = await resp.json()
            assert body["trace_id"] == "trace-e2e-1"
            assert len(body["tokens"]) == 3

            dbg = await client.get("/debug/traces", params={"trace": "trace-e2e-1"})
            assert dbg.status == 200
            payload = await dbg.json()
            assert payload["replica"] == runner.engine.replica
            (trace,) = payload["traces"]
            assert trace["req_id"] == body["request_id"]
            assert trace["tokens"] == 3
        await self._with_app(params, fn)

    async def test_trace_id_minted_when_absent(self, params):
        async def fn(client, runner):
            resp = await client.post(
                "/generate",
                json={"prompt_tokens": [5, 6], "max_tokens": 2, "stream": False},
            )
            assert resp.status == 200
            minted = resp.headers[tracing.TRACE_HEADER]
            assert minted
            body = await resp.json()
            assert body["trace_id"] == minted
            assert runner.engine.flight.snapshot(trace_id=minted)
        await self._with_app(params, fn)

    async def test_sse_stream_carries_trace_header(self, params):
        async def fn(client, runner):
            resp = await client.post(
                "/generate",
                json={"prompt_tokens": [9, 10, 11], "max_tokens": 2,
                      "stream": True},
                headers={tracing.TRACE_HEADER: "trace-sse"},
            )
            assert resp.status == 200
            assert resp.headers[tracing.TRACE_HEADER] == "trace-sse"
            text = await resp.text()
            assert "[DONE]" in text
        await self._with_app(params, fn)

    async def test_replica_metrics_endpoint_strict_parses(self, params):
        """GET /metrics on the replica renders every serve family in valid
        exposition format (validated by the same strict parser that guards
        the control plane's renderer), advertised even before traffic."""
        tracing.reset()
        try:
            async def fn(client, runner):
                cold = await client.get("/metrics")
                assert cold.status == 200
                families = parse_exposition(await cold.text())
                for name in serve_lib.SERVE_HISTOGRAM_HELP:
                    assert name in families

                resp = await client.post(
                    "/generate",
                    json={"prompt_tokens": [2, 3, 4], "max_tokens": 3,
                          "stream": False},
                )
                assert resp.status == 200
                warm = await client.get("/metrics")
                families = parse_exposition(await warm.text())
                samples = families["dstack_tpu_serve_ttft_seconds"]["samples"]
                count = [
                    v for n, labels, v in samples
                    if n.endswith("_count")
                    and labels.get("replica") == runner.engine.replica
                ]
                assert count == [1.0]
            await self._with_app(params, fn)
        finally:
            tracing.reset()


class TestProxyToEngineTracePath:
    async def test_proxy_issued_trace_id_reaches_flight_recorder(self, params):
        """The acceptance path: a request through the REAL in-server proxy gets
        a proxy-minted X-Dstack-Trace-Id, the replica's flight recorder keys
        its record by it, and the runs/get_traces API (the `dstack-tpu trace`
        backend) finds that record fleet-wide by the same id."""
        from aiohttp.test_utils import TestClient, TestServer

        from tests.common import api_server
        from tests.test_serving_fast_path import _Fixture, seed_service

        runner = serve_lib.EngineRunner(make_engine(params))
        runner.start()
        try:
            replica = TestClient(TestServer(serve_lib.create_serve_app(runner)))
            await replica.start_server()
            try:
                with _Fixture():
                    async with api_server() as api:
                        await seed_service(
                            api.db, "svc-obs", replica.server.port
                        )
                        resp = await api.client.post(
                            "/proxy/services/main/svc-obs/generate",
                            json={"prompt_tokens": [1, 2, 3, 4],
                                  "max_tokens": 3, "stream": False},
                        )
                        assert resp.status == 200
                        tid = resp.headers[tracing.TRACE_HEADER]
                        assert tid
                        body = await resp.json()
                        assert body["trace_id"] == tid

                        data = await api.post(
                            "/api/project/main/runs/get_traces",
                            {"run_name": "svc-obs", "trace_id": tid},
                        )
                        assert data["replicas_queried"] == 1
                        assert data["errors"] == []
                        (trace,) = data["traces"]
                        assert trace["trace_id"] == tid
                        assert trace["req_id"] == body["request_id"]
                        assert trace["tokens"] == 3
            finally:
                await replica.close()
        finally:
            runner.shutdown()

    async def test_client_supplied_trace_id_wins(self, params):
        """A client correlating across services keeps its own id: the proxy
        reuses rather than re-mints, end to end into the engine record."""
        from aiohttp.test_utils import TestClient, TestServer

        from tests.common import api_server
        from tests.test_serving_fast_path import _Fixture, seed_service

        runner = serve_lib.EngineRunner(make_engine(params))
        runner.start()
        try:
            replica = TestClient(TestServer(serve_lib.create_serve_app(runner)))
            await replica.start_server()
            try:
                with _Fixture():
                    async with api_server() as api:
                        await seed_service(
                            api.db, "svc-own-id", replica.server.port
                        )
                        resp = await api.client.post(
                            "/proxy/services/main/svc-own-id/generate",
                            json={"prompt_tokens": [8, 9], "max_tokens": 2,
                                  "stream": False},
                            headers={tracing.TRACE_HEADER: "caller-id-7"},
                        )
                        assert resp.status == 200
                        assert resp.headers[tracing.TRACE_HEADER] == "caller-id-7"
                        assert runner.engine.flight.snapshot(
                            trace_id="caller-id-7"
                        )
            finally:
                await replica.close()
        finally:
            runner.shutdown()


class TestTraceCli:
    def test_timeline_renders_all_stages(self, capsys):
        from dstack_tpu.cli.main import _render_trace_timeline

        _render_trace_timeline({
            "req_id": "http-3", "trace_id": "abcd1234", "replica": "1",
            "queue_wait_s": 0.05, "prefill_s": 0.2, "ttft_s": 0.25,
            "decode_s": 0.75, "total_s": 1.0, "prompt_tokens": 64,
            "cached_tokens": 32, "tokens": 12, "preemptions": 1,
            "spec_proposed": 10, "spec_accepted": 7, "slow": True,
        })
        out = capsys.readouterr().out
        assert "http-3" in out and "abcd1234" in out and "[SLOW]" in out
        for stage in ("queue", "prefill", "decode", "total"):
            assert stage in out
        assert "spec accepted 7/10" in out
        assert "ttft 250.0ms" in out

    def test_cmd_trace_lists_and_narrows(self, capsys, monkeypatch):
        import dstack_tpu.cli.main as cli_main

        records = [{
            "req_id": "http-1", "trace_id": "tid-x", "replica": "0",
            "queue_wait_s": 0.001, "prefill_s": 0.01, "ttft_s": 0.011,
            "decode_s": 0.02, "total_s": 0.031, "tokens": 5, "slow": False,
        }]

        class FakeRuns:
            def get_traces(self, run_name, request_id=None, trace_id=None,
                           limit=20):
                out = records
                if request_id:
                    out = [t for t in out if t["req_id"] == request_id]
                return {"run_name": run_name, "replicas_queried": 1,
                        "errors": [], "traces": out}

        class FakeClient:
            runs = FakeRuns()

        monkeypatch.setattr(cli_main, "_client", lambda: FakeClient())
        parser = cli_main.build_parser()

        args = parser.parse_args(["trace", "svc"])
        args.func(args)
        out = capsys.readouterr().out
        assert "http-1" in out and "REQUEST" in out

        args = parser.parse_args(["trace", "svc", "--request", "http-1"])
        args.func(args)
        out = capsys.readouterr().out
        assert "queue" in out and "decode" in out  # timeline mode

        args = parser.parse_args(["trace", "svc", "--request", "nope"])
        args.func(args)
        assert "no recorded request traces" in capsys.readouterr().out
